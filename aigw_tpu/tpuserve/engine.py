"""Continuous-batching engine.

The TPU-native scheduler design (not a vLLM port):

- **Fixed decode geometry**: decode runs a single jit-compiled program of
  shape [max_batch, 1] every tick; finished slots are masked, not removed,
  so there is exactly ONE compiled decode program for the engine lifetime.
- **Bucketed prefill**: prompts are right-padded to power-of-two buckets so
  the number of compiled prefill programs is log(max_seq_len).
- **Sampling fused into the step**: logits never leave the device — each
  tick transfers only [max_batch] int32 sampled tokens to the host.
- **Donated cache**: the paged KV pool is donated through every step, so
  XLA updates it in place (no per-tick HBM copy of the cache).
- **Engine thread**: the loop runs in its own thread; JAX dispatch is
  async, so the thread overlaps host bookkeeping with device compute.
  Tokens flow back to asyncio consumers via loop.call_soon_threadsafe.

Telemetry (KV occupancy, queue depth, active slots) feeds the endpoint
picker — the reference's EPP signal (SURVEY.md §3.4).
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from aigw_tpu.analysis.registry import engine_thread_only
from aigw_tpu.models import kvq, llama
from aigw_tpu.obs.metrics import EnginePhases
from aigw_tpu.obs.xla_events import CompileTracker
from aigw_tpu.tpuserve import constrain, speculation
from aigw_tpu.tpuserve.kvcache import (
    OutOfPagesError,
    PageAllocator,
    PrefixCache,
    RefcountedAllocator,
    page_chain_hashes,
)
from aigw_tpu.tpuserve.sampling import (
    SamplingParams,
    apply_penalties,
    sample,
    spec_accept,
)

logger = logging.getLogger(__name__)


class EngineOverloadedError(Exception):
    """Admission queue full — callers should surface 429/503."""


def device_memory_stats() -> tuple[int, int]:
    """Live (bytes_in_use, bytes_limit) of device 0 from jax
    memory_stats() — the MEASURED per-device HBM signal /state exports
    (VERDICT r5: the topology-aware picker consumed labels, not
    signals). (0, 0) on backends without memory stats (CPU)."""
    try:
        ms = jax.local_devices()[0].memory_stats() or {}
    except Exception:  # noqa: BLE001 — telemetry must never raise
        return 0, 0
    return (int(ms.get("bytes_in_use", 0) or 0),
            int(ms.get("bytes_limit", 0) or 0))


def device_memory_stats_all() -> list[tuple[int, str, int, int]]:
    """Live (device_id, platform, bytes_in_use, bytes_limit) for EVERY
    local device — the mesh-serving fix for PR 9's device-0-only poll
    (a sharded engine's hottest device is rarely device 0). Zeros on
    backends without memory stats (CPU); the list itself is still real
    so per-device KV/param accounting has a device to hang off."""
    out: list[tuple[int, str, int, int]] = []
    try:
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — telemetry must never raise
        return out
    for d in devices:
        try:
            ms = d.memory_stats() or {}
        except Exception:  # noqa: BLE001
            ms = {}
        out.append((int(d.id), str(getattr(d, "platform", "")),
                    int(ms.get("bytes_in_use", 0) or 0),
                    int(ms.get("bytes_limit", 0) or 0)))
    return out


def _per_device_bytes(tree: Any) -> dict[int, int]:
    """Bytes each device holds of ``tree``'s array leaves, from the
    arrays' real shard layout (an unsharded array is one shard on one
    device). The measured half of the bench's per-device-param-bytes ≈
    total/tp claim."""
    per: dict[int, int] = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        if getattr(leaf, "is_deleted", lambda: False)():
            # a donated-away buffer (mid-reassignment on another
            # thread) is a stats gap, not an engine-loop fatality
            continue
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            continue
        for sh in shards:
            d = int(sh.device.id)
            per[d] = per.get(d, 0) + int(sh.data.nbytes)
    return per


class MigrationError(Exception):
    """A migration export/import could not be performed (request not
    active, finished during the cut, prefix cache disabled, malformed
    blob). The session is left exactly as it was — a failed export
    never kills the stream it tried to move."""


@dataclass
class EngineConfig:
    max_batch_size: int = 8
    max_seq_len: int = 2048
    page_size: int = 128
    num_pages: int = 0  # 0 = auto: enough for max_batch full sequences
    min_prefill_bucket: int = 64
    # Decode steps executed per host round-trip (lax.scan inside one jitted
    # program). Amortizes host↔device latency; tokens sampled after a
    # sequence's EOS within a window are discarded by the host.
    decode_steps_per_tick: int = 8
    # Automatic prefix caching: full prompt pages are content-addressed and
    # shared across requests (chat-history reuse → TTFT win).
    enable_prefix_cache: bool = True
    # Admission cap: waiting requests beyond this are rejected at submit
    # (the server surfaces 429 + retry-after) instead of growing an
    # unbounded queue.
    max_queued_requests: int = 256
    # Sequence-parallel prefill: prompts at least this long run through
    # the ring-attention path when the mesh has an sp axis > 1 (context
    # parallelism for prompts whose attention working set exceeds one
    # chip). Shorter prompts use the plain prefill — the ICI rotation
    # only pays for itself on long sequences.
    sp_prefill_min_tokens: int = 1024
    # Sequence-parallel chunked prefill (the long-context path):
    # "chunked" runs sp prompts as sp_chunk_tokens-sized ring-attention
    # chunk steps (models.<family>.prefill_sp_suffix) with a decode
    # tick between chunks — the chunked-prefill liveness guarantee
    # holds on the sp path too, and the path resumes at page-aligned
    # prefix-cache / migration offsets. "monolithic" restores the
    # single full-rung ring-attention program (no interleaving, no
    # resume — prefix hits fall back to the single-device chunk loop).
    # The chunked path additionally requires page_size % sp == 0 (the
    # gathered page window is sharded over sp); other geometries fall
    # back to monolithic automatically.
    sp_prefill_mode: str = "chunked"  # "chunked" | "monolithic"
    # Chunk size for the sp chunked path, rounded up to a multiple of
    # the sp axis at use. Larger than prefill_chunk_tokens by default:
    # each sp chunk step re-gathers the sequence's page window, so
    # chunks amortize the window pass while staying small enough that
    # decode ticks interleave every few hundred ms at 32k-128k.
    sp_chunk_tokens: int = 2048
    # Chunked prefill: prompts longer than this run as fixed-size
    # prefill_suffix steps with a decode tick between chunks — bounding
    # both the largest compiled bucket and how long active streams
    # stall behind a long prompt. 0 disables (whole-prompt prefill).
    # Default ON: a long prompt must never stall in-flight decodes for
    # its whole prefill (model families without prefill_suffix fall
    # back to whole-prompt prefill automatically).
    prefill_chunk_tokens: int = 256
    # Adaptive decode windows: shrink the per-tick window to
    # min_decode_steps_per_tick while the admission queue is non-empty
    # or a stream just started (TTFT-/admission-latency-sensitive), and
    # regrow to decode_steps_per_tick once the batch is steady
    # (throughput-sensitive). Each window size is its own compiled
    # program; the ladder is {min, max} so at most two decode programs
    # exist per page bucket.
    adaptive_decode_window: bool = True
    # Small window used under pressure. 0 = auto: max(1, K // 4).
    min_decode_steps_per_tick: int = 0
    # Async device→host token transfers: the sampled-token fetch for a
    # decode window is started at dispatch time (copy_to_host_async)
    # and resolved at drain time, so the copy overlaps the next
    # on-device window instead of blocking the engine thread. False
    # restores the blocking device_get at drain — token streams are
    # byte-identical either way (tests/test_serving_overlap.py).
    async_transfers: bool = True
    # Idle-burst coalescing: when the engine is COMPLETELY idle and a
    # request arrives, wait this long for the rest of its burst before
    # admitting, so B near-simultaneous arrivals prefill as ONE batched
    # [G, S] call instead of a 1+(B-1) split (a burst's submits span a
    # few ms of event-loop scheduling). Busy engines never wait —
    # arrivals already coalesce between decode windows. 0 disables.
    admission_coalesce_ms: float = 3.0
    # First-token fast path: token 0 is sampled by the prefill step
    # itself, so (a) its device→host copy is started at prefill dispatch
    # (copy_to_host_async — the same machinery as async_transfers) so
    # the host never pays a separate fetch round-trip after the compute
    # lands, and (b) a LONE arrival to an idle engine prefills
    # immediately instead of riding the admission_coalesce_ms timer
    # (coalescing only pays when a second request is already queued).
    # False restores the round-6 behavior; token streams are
    # byte-identical either way (tests/test_serving_overlap.py).
    first_token_fast_path: bool = True
    # Pre-compile the batched-prefill programs for the N smallest
    # prompt buckets at warmup (all power-of-two group sizes up to
    # max_batch_size): a traffic burst must not pay an XLA prefill
    # compile for a group shape the warm traffic happened not to hit.
    # 0 = off (each (group, bucket) shape compiles on first use).
    warm_prefill_buckets: int = 0
    # Pre-compile the decode-window ladder (lean/full × window sizes ×
    # spec verify rungs) AND the row-update scatters at the first N
    # pow2 PAGE buckets, not just the quiesced bucket-1 state (ISSUE
    # 10): the decode program re-traces per page-table width, so the
    # first admission whose sequence needs a bucket the warmup never
    # visited pays an XLA compile (and a pipeline-draining rebuild) on
    # the hot path — the CompileTracker showed exactly this at first
    # mesh admission. 0 keeps the old single-bucket warm (cheapest
    # cold start); N warms buckets 1, 2, …, 2^(N-1) capped at
    # max_pages_per_seq.
    warm_decode_buckets: int = 0
    # Prefill bucket rungs per octave: 1 keeps the classic power-of-two
    # ladder (worst-case padding ≈ 2× the prompt); 2 adds a 1.5×S rung
    # between octaves (worst-case padding 1.5×); 4 adds 1.25×/1.5×/1.75×
    # rungs (worst-case 1.25×). Prefill compute scales with the PADDED
    # length, so padding waste is paid directly in TTFT — a ~90-token
    # chat prompt on the pow2 ladder runs a 128-wide prefill, ~35%
    # slower than the 96-wide rung. Compiled-program count stays
    # bounded: rungs × log2(max_seq/min_bucket) shapes per group size.
    prefill_bucket_rungs: int = 2
    # Speculative decoding: the maximum draft tokens verified per decode
    # step (0 = off). Each draft-length rung of the adaptive ladder
    # ({0, 2, 4, 8}-style, capped here) is one fixed-shape [B, D+1]
    # verify program; a step advances by the accepted count — see
    # tpuserve/speculation.py.
    spec_tokens: int = 0
    # Adaptive draft length: per-slot controllers walk the rung ladder
    # on a rolling acceptance EWMA, collapsing to D=0 (plain decode,
    # zero overhead) on adversarial traffic and re-probing
    # occasionally. False pins every eligible slot at spec_tokens —
    # the fixed-D A/B and determinism knob.
    spec_adaptive: bool = True
    # Ragged paged-attention Pallas kernel for the CHAINED decode loop
    # (HBM reads scale with actual sequence lengths, not the padded
    # window). Resolved through the decode fallback matrix
    # (tpuserve/attention.resolve_decode_backend): single-chip native
    # pools run the chained kernel; a mesh or a quantized pool
    # escalates to the fused rung (the PR 10 gather-on-mesh row is
    # deleted); /state exports the resolution + why.
    pallas_attn: bool = False
    # Decode attention rung (ISSUE 13, tpuserve/attention.py):
    # "auto"/"chained" — the classic per-layer chain (rope → scatter →
    # window gather / chained Pallas kernel); "fused" — ONE program
    # per decode dispatch: RoPE + quantized KV append + online-softmax
    # paged attention (the Pallas kernel on single-chip TPU, an XLA
    # page-walk reference off-TPU, and a shard_map per-device local
    # pool walk on a mesh — no GSPMD gather). The resolved impl and
    # reason export on /state (decode_attn_impl / decode_attn_reason).
    decode_backend: str = "auto"
    # Prefill attention backend (tpuserve/attention.py):
    # "xla-bucketed" — the classic per-sequence bucket ladder with
    # batched same-bucket groups; "pallas-ragged" — a mixed-length
    # admission burst packs into ONE ragged paged-attention program
    # sized by TOTAL tokens (padded to a token-budget chunk rung, not
    # per-sequence buckets), with per-sequence start offsets making
    # prefix-cache resumes and chunked continuations first-class.
    # pallas-ragged auto-falls back per the fallback matrix in
    # tpuserve/attention.py: the Pallas kernel on single-chip TPU, the
    # XLA windowed program off-TPU AND on a mesh (it runs SPMD with KV
    # sharded on heads), xla-bucketed only for model families without a
    # ragged prefill entry point; /state exports the resolution + why.
    attention_backend: str = "xla-bucketed"
    # Ragged backend geometry: packed totals pad to multiples of this
    # chunk (plus two sub-chunk rungs for short tails/resumes)...
    ragged_chunk_tokens: int = 256
    # ...and one packed call carries at most chunk × this many tokens;
    # larger bursts split at budget boundaries with decode ticks
    # interleaved (chunked-prefill liveness, kept). The compiled
    # prefill surface is the rung ladder: ~(ragged_max_chunks + 2)
    # programs for ANY batch geometry.
    ragged_max_chunks: int = 8
    # KV cache element dtype: "bfloat16" (serving default), "float32"
    # (doubles KV HBM but removes the bf16 rounding that lets near-tied
    # logits argmax-flip between mathematically equivalent schedules —
    # the deterministic-equivalence test mode), or "int8"/"int4"
    # (ISSUE 13, models/kvq.py): pages store quantized rows plus
    # per-page scale blocks (one f32 absmax scale per token row × KV
    # head), dequantized in-kernel / at the gather — ~0.52x / ~0.27x
    # the bf16 KV bytes at head_dim 128, which is the
    # concurrent-sessions-per-chip lever. Quantized pages ride the
    # whole stack (spill/revive, migration + fleet fetch at native
    # dtype + scales, spec verify, CoW); the chained Pallas kernels
    # have no quantized rung, so the fallback matrix reroutes those
    # requests (attention.resolve_decode_backend).
    kv_cache_dtype: str = "bfloat16"
    # Multi-tenant fairness guard (ISSUE 7): the maximum decode slots
    # any one tenant (GenRequest.tenant; "" is one anonymous tenant) may
    # hold concurrently. Admissions beyond the cap are deferred (left at
    # the queue head, arrival order kept) until the tenant frees a slot,
    # so one tenant's burst can never occupy the whole batch while
    # another tenant's single request starves. Admission is additionally
    # deficit-weighted whenever multiple tenants are queued: tenants
    # holding fewer in-flight slots admit first. 0 disables the cap
    # (weighted ordering still applies).
    tenant_slot_cap: int = 0
    # Prefill/decode disaggregation (ISSUE 8): a slot whose prefill is
    # done but whose decode is still young (generated <= this) counts
    # toward the /state ``migratable_slots`` gauge — the gateway's
    # signal for handing completed-prefill sessions to a decode-leaning
    # replica. 0 counts every decoding slot as eligible. Export itself
    # is not gated by this (the orchestrator owns the policy).
    migration_young_tokens: int = 64
    # Grammar-constrained decoding (ISSUE 9, tpuserve/constrain.py):
    # structured outputs (response_format json_object / json_schema) and
    # tool-call envelopes enforced on-device by composing a per-slot
    # [V] token mask into the existing logit-bias row. False makes the
    # server 400 such requests instead (the pre-subsystem contract,
    # minus the silent free-text 200).
    constrained_decoding: bool = True
    # KV memory hierarchy (ISSUE 11, tpuserve/kvhost.py): byte budget of
    # the host-RAM spill tier. When > 0 (and the prefix cache is on), a
    # cache-registered page reclaimed under pool pressure is copied
    # device→host and parked in a bounded LRU keyed by its content
    # chain hash instead of being dropped; a later prefix hit on a
    # spilled chain revives the pages through the warmed batched import
    # scatters (no recompute, no hot XLA compile). 0 disables the tier
    # (classic eviction). The budget counts page bytes in the pool's
    # native KV dtype.
    kv_host_bytes: int = 0
    # Priority-tiered serving (ISSUE 19): ceiling on the fraction of
    # decode slots the offline batch class may occupy at once (at least
    # one slot when > 0). Batch requests admit only when the
    # interactive queue is empty and stay under this footprint, so a
    # saturating /v1/batches backlog can never crowd interactive
    # admissions out of the batch — interactive pressure additionally
    # preempts batch sessions (window shrink, then park) to reclaim
    # slots. 1.0 lets batch soak every idle slot; interactive still
    # evicts it on arrival.
    batch_slot_frac: float = 0.5
    # Per-token logprobs (vLLM/OpenAI parity): when > 0, the decode scan
    # also returns the chosen token's log-probability and the top-k
    # (ids, values) per step, and requests may set want_logprobs. Static
    # at trace time — 0 keeps the default decode program byte-identical.
    # Mutually exclusive with spec_tokens (the verify step emits a
    # variable number of tokens per step; logprob bookkeeping for
    # rejected drafts is not worth the complexity).
    logprobs_topk: int = 0

    def __post_init__(self) -> None:
        if self.logprobs_topk > 0 and self.spec_tokens > 0:
            raise ValueError(
                "logprobs_topk and spec_tokens are mutually exclusive")
        from aigw_tpu.tpuserve.attention import BACKENDS

        if self.attention_backend not in BACKENDS:
            raise ValueError(
                f"attention_backend must be one of {BACKENDS} "
                f"(got {self.attention_backend!r})")
        if self.ragged_chunk_tokens < 8 or self.ragged_max_chunks < 1:
            raise ValueError(
                "ragged_chunk_tokens must be >= 8 and ragged_max_chunks "
                ">= 1")
        if not 0.0 < self.batch_slot_frac <= 1.0:
            raise ValueError(
                f"batch_slot_frac must be in (0, 1] "
                f"(got {self.batch_slot_frac})")
        if self.prefill_bucket_rungs not in (1, 2, 4):
            raise ValueError(
                f"prefill_bucket_rungs must be 1, 2, or 4 "
                f"(got {self.prefill_bucket_rungs})")
        from aigw_tpu.models import kvq
        from aigw_tpu.tpuserve.attention import DECODE_BACKENDS

        if self.kv_cache_dtype not in kvq.KV_DTYPES:
            raise ValueError(
                f"kv_cache_dtype must be one of {kvq.KV_DTYPES} "
                f"(got {self.kv_cache_dtype!r})")
        if self.decode_backend not in DECODE_BACKENDS:
            raise ValueError(
                f"decode_backend must be one of {DECODE_BACKENDS} "
                f"(got {self.decode_backend!r})")
        if self.min_decode_steps_per_tick == 0:
            self.min_decode_steps_per_tick = max(
                1, self.decode_steps_per_tick // 4)
        if self.min_decode_steps_per_tick > self.decode_steps_per_tick:
            raise ValueError(
                f"min_decode_steps_per_tick "
                f"({self.min_decode_steps_per_tick}) exceeds "
                f"decode_steps_per_tick ({self.decode_steps_per_tick})")
        if self.max_seq_len % self.page_size != 0:
            raise ValueError(
                f"max_seq_len ({self.max_seq_len}) must be a multiple of "
                f"page_size ({self.page_size})"
            )
        if self.num_pages == 0:
            self.num_pages = (
                self.max_batch_size * self.max_seq_len // self.page_size
            )

    @property
    def max_pages_per_seq(self) -> int:
        return self.max_seq_len // self.page_size


@dataclass
class GenRequest:
    prompt: list[int]
    max_tokens: int
    sampling: SamplingParams
    stop_token_ids: tuple[int, ...] = ()
    # (token_id, finish_reason): token_id < 0 means no token, just finish
    emit: Callable[[int, str | None], None] = lambda t, f: None
    id: int = 0
    enqueued_at: float = field(default_factory=time.monotonic)
    # set by the consumer to abandon the request (client disconnect / stop
    # sequence hit); the engine frees the slot at the next tick
    cancelled: threading.Event = field(default_factory=threading.Event)
    # LoRA adapter name ("" = base model)
    adapter: str = ""
    # Tenant key for fairness + accounting ("" = anonymous). The server
    # derives it from the x-aigw-tenant header (relayed by the gateway)
    # or the adapter suffix of the requested model name.
    tenant: str = ""
    # Priority class (ISSUE 19): "interactive" rides the normal
    # admission queue; "batch" rides the never-shed offline queue,
    # admits only into slots interactive doesn't want (ceiling:
    # batch_slot_frac), and may be preempted — parked host-side and
    # resumed later byte-identically — when interactive arrivals need
    # its slot. The server derives it from the x-aigw-priority header
    # or the /v1/batches surface.
    priority: str = "interactive"
    # Per-token logprobs: when set (and the engine was built with
    # logprobs_topk > 0), emit_lp is called INSTEAD of emit with
    # (token, finish, logprob, top) where top = [(token_id, logprob)]
    # of the engine's top-k (callers slice to the request's own k).
    emit_lp: "Callable[[int, str | None, float | None, list | None], None] | None" = None
    # Pre-computed page-chain prefix hashes (kvcache.page_chain_hashes
    # over this prompt at the ENGINE's page size) — the server's
    # tokenizer pool rolls them during encode so admission-time lookup
    # costs no extra pass over the prompt. None (or a stale length —
    # defensive) falls back to hashing at classification time.
    prefix_hashes: list | None = None
    # Migration continuation (ISSUE 8): set on requests that RESUME a
    # session exported by another replica. The prompt then carries the
    # original prompt PLUS every token generated so far; this dict
    # restores the slot state the continuation must inherit to stay
    # byte-identical with a solo-served run:
    #   orig_prompt_len — where the original prompt ended (tokens past
    #       it are generated history: they seed the repetition-penalty
    #       counts and are EXCLUDED from usage input accounting),
    #   generated — tokens already emitted upstream (usage offset),
    #   key_seed / key_counter — the sampling key state at the cut, so
    #       the first resumed token samples with the exact key the solo
    #       run would have used at that position.
    # None everywhere else; continuation requests always take the
    # per-request admission path (never the batched prefill).
    import_state: dict | None = None
    # Grammar constraint (ISSUE 9): a compiled, shared
    # constrain.TokenFSM — the slot builds its own ConstraintState
    # cursor at admission. None = unconstrained (the only path touched
    # for such requests is an `is None` check, keeping unconstrained
    # streams byte-identical with the subsystem compiled in).
    constraint: Any = None
    # Request-lifecycle sink (obs.flight.RequestTrace or None): the
    # engine reports queue-wait, admission classification, prefill
    # geometry, first-token, decode windows, and EOS/cancel through it
    # into the flight recorder + the request's span tree. Duck-typed and
    # optional — None costs one attribute check per call site.
    trace: Any = None
    # Usage metering sink (ISSUE 20): called EXACTLY ONCE per request
    # lifetime with the engine-truth MeterRecord dict, on the engine
    # thread, strictly before the terminal emit — so a consumer that
    # dequeues the finish item observes the record. Migrated/parked
    # continuations do NOT fire it at the cut; the accumulated meter
    # rides the export blob and the resumed slot's record covers the
    # whole spliced stream. None = metering off for this request.
    meter_sink: "Callable[[dict], None] | None" = None


@dataclass
class _Slot:
    req: GenRequest
    # Position at which the *pending input token* will be written by the
    # next decode step. After prefilling a prompt of length n, the first
    # sampled token is the pending input at position n.
    pos: int
    generated: int
    key_seed: int
    pending_token: int = 0
    limit: int = 0  # exclusive max write position (page-safety fence)
    page_row: np.ndarray | None = None
    # generated-token histogram (repetition penalties survive state
    # rebuilds across admissions)
    token_counts: dict[int, int] = field(default_factory=dict)
    adapter_row: int = 0
    # ordered generated tokens (the slot's device history row is built
    # from prompt + these — uploaded by the incremental row update, not
    # a full state rebuild)
    gen_tokens: list[int] = field(default_factory=list)
    # speculative decoding (spec-eligible slots only): the adaptive
    # draft-length controller, the prefix-cache continuation lookahead
    # (tokens + the absolute position of tokens[0]), and the draft_len
    # value currently live on device (to skip no-op row patches)
    ctrl: Any = None  # speculation.DraftController | None
    la_base: int = 0
    la_tokens: list[int] = field(default_factory=list)
    dev_draft_len: int = 0
    # monotonic time of the slot's first emitted token (feeds the
    # decode-per-token histogram at finish)
    first_emit_at: float = 0.0
    # grammar-constrained decoding (ISSUE 9): the slot's FSM cursor and
    # its rollback epoch — windows capture the epoch at dispatch, and a
    # drain whose captured epoch trails the slot's discards that
    # window's tokens (they were sampled past a grammar violation)
    cn: Any = None  # constrain.ConstraintState | None
    cn_epoch: int = 0
    # usage metering accumulators (ISSUE 20) — engine-truth per-request
    # counts folded into the MeterRecord at the terminal emit. Residency
    # is integrated piecewise: m_res_bytes is the slot's current KV
    # page·bytes and m_res_t0 the wall clock it last changed, so
    # HBM page·byte·seconds accrue as sum(bytes × dwell) across segments.
    m_prefill_real: int = 0
    m_prefill_padded: int = 0
    m_prefix_reused: int = 0
    m_spec_drafted: int = 0
    m_spec_accepted: int = 0
    m_res_t0: float = 0.0
    m_res_bytes: int = 0
    m_hbm_pbs: float = 0.0
    # carry imported from a migration/park export blob: the meter
    # accumulated by earlier segments of this spliced stream
    m_carry: dict | None = None


@dataclass
class EngineStats:
    active_slots: int = 0
    queued: int = 0
    kv_pages_free: int = 0
    kv_occupancy: float = 0.0
    tokens_generated: int = 0
    # extra tokens landed by accepted speculative drafts (beyond the one
    # token per step the plain decode path yields)
    spec_accepted: int = 0
    # draft tokens proposed to the verifier (per-slot draft length ×
    # steps the slot was live in a speculative window)
    spec_drafted: int = 0
    # cumulative accepted / drafted (refreshed each tick)
    spec_accept_rate: float = 0.0
    # draft width of the most recent dispatch (0 = plain decode — the
    # adaptive ladder is collapsed or speculation is off)
    spec_draft_len: int = 0
    # adaptive-ladder transitions (includes rung-0 re-probes as ups)
    spec_rung_ups: int = 0
    spec_rung_downs: int = 0
    # admissions whose draft source includes a prefix-cache
    # continuation lookahead (repeated-traffic free drafts)
    spec_lookahead_slots: int = 0
    # full device-state rebuilds that drained a LIVE pipeline (page-
    # bucket growth only — speculative admission no longer forces one;
    # from-idle builds are not counted). The zero-rebuild acceptance
    # criterion asserts on this.
    state_rebuilds: int = 0
    # adapter serving subsystem (ISSUE 7, tpuserve/adapters.py): hot
    # loads into device rows, LRU evictions under row pressure, the
    # resident-adapter count, and how many live slots currently decode
    # through a non-base adapter row
    adapter_loads: int = 0
    adapter_evictions: int = 0
    adapter_resident: int = 0
    adapter_slots: int = 0
    # multi-tenant fairness surface: distinct tenants holding decode
    # slots, the largest per-tenant in-flight count, and admissions
    # deferred by the per-tenant slot cap (each deferral = one pass a
    # request waited because its tenant was at cap)
    tenants_active: int = 0
    tenant_max_slots: int = 0
    tenant_deferrals: int = 0
    # priority-tiered serving (ISSUE 19): the offline batch class.
    # batch_queued counts waiting batch work (the never-shed queue plus
    # host-parked preempted sessions), batch_active the decode slots it
    # holds now (always <= the batch_slot_frac ceiling),
    # batch_preemptions the sessions parked off-device because an
    # interactive arrival wanted the slot, batch_resumed the parked
    # sessions re-admitted (byte-identical continuation), batch_tokens
    # the tokens the class has generated — the idle-slot-soak volume
    # the bench's batch_tier A/B prices.
    batch_queued: int = 0
    batch_active: int = 0
    batch_preemptions: int = 0
    batch_resumed: int = 0
    batch_tokens: int = 0
    # prefill/decode disaggregation (ISSUE 8): sessions exported to /
    # imported from other replicas, the KV pages that moved with them,
    # and the live count of migration-eligible slots (prefill done,
    # decode young — the gateway's disaggregation signal)
    migrations_out: int = 0
    migrations_in: int = 0
    migration_pages_out: int = 0
    migration_pages_in: int = 0
    migratable_slots: int = 0
    # grammar-constrained decoding (ISSUE 9, tpuserve/constrain.py):
    # live constrained slots, requests admitted with a constraint,
    # window rollbacks (a decode window ran past a grammar boundary —
    # tokens after the violation were discarded and the slot's row
    # re-uploaded, the spec-decode rejection discipline), mask-row
    # device patches, and the compiled-grammar cache size
    constrained_slots: int = 0
    constraint_requests: int = 0
    constraint_rollbacks: int = 0
    constraint_mask_updates: int = 0
    constraint_grammars: int = 0
    # real per-device memory signals (ISSUE 9 satellite, VERDICT r5
    # residue): live jax memory_stats() bytes (0 on backends without
    # them, e.g. CPU) + the KV pool's byte occupancy — the picker's
    # first MEASURED memory signal
    device_bytes_in_use: int = 0
    device_bytes_limit: int = 0
    device_memory_frac: float = 0.0
    kv_pool_bytes: int = 0
    kv_bytes_in_use: int = 0
    # mesh serving (ISSUE 10): REAL per-device signals. device_count is
    # the engine's local device population (1 off-mesh);
    # device_memory_frac_worst is the max memory_stats fraction across
    # them — the picker scores the WORST device, not device 0 (one hot
    # shard saturates the whole tensor-parallel step). The ICI pair is
    # the analytical per-device collective volume of the TP/EP layout
    # (parallel/sharding.analytical_ici_bytes_per_token): bytes one
    # decoded token moves over ICI, and its cumulative total
    device_count: int = 1
    device_memory_frac_worst: float = 0.0
    ici_bytes_per_token: int = 0
    ici_bytes_total: int = 0
    # quantized KV pages (ISSUE 13, models/kvq.py): bits per stored KV
    # element (32/16 native, 8/4 quantized) and the all-layer HBM bytes
    # one cached token costs INCLUDING its per-page scale share — the
    # capacity-planning pair behind "half the KV bytes = twice the
    # concurrent sessions per chip"
    kv_quant_bits: int = 16
    kv_bytes_per_token: float = 0.0
    # KV memory hierarchy (ISSUE 11): the host-RAM spill tier and the
    # cross-replica page fetch surface. Spills/revives/spill-evictions
    # mirror the HostKVTier counters (pages demoted to host RAM on
    # eviction, pages promoted back by a prefix hit, pages the host
    # LRU budget dropped); the live pair is what the tier holds NOW.
    # Fetches count cross-replica /kv/pages traffic: _out = page sets
    # this replica served to siblings, _in = page sets imported from a
    # sibling ahead of a local prefill.
    kv_spills: int = 0
    kv_revives: int = 0
    kv_spill_evictions: int = 0
    kv_spilled_pages: int = 0
    kv_spill_bytes: int = 0
    kv_host_bytes: int = 0
    kv_fetches_out: int = 0
    kv_fetches_in: int = 0
    kv_fetch_pages_out: int = 0
    kv_fetch_pages_in: int = 0
    prefills: int = 0
    sp_prefills: int = 0  # prefills routed through ring attention
    # long-context sp surface: sp prefills that ran as chunked
    # ring-attention steps (vs one monolithic full-rung program), and
    # how many of those resumed at a nonzero cached offset (prefix-
    # cache partial hit / migration continuation on the sp path)
    sp_chunked_prefills: int = 0
    sp_resume_prefills: int = 0
    # short requests admitted AT a chunk boundary of a running sp
    # chunked prefill — the decode-liveness counter: each one is a
    # first token that did not wait out a long prefill
    sp_interactive_admits: int = 0
    chunked_prefill_steps: int = 0  # intermediate chunk device steps
    decode_steps: int = 0
    prefix_cache_hits: int = 0
    prefix_tokens_reused: int = 0
    # prefix-cache surface (ISSUE 3): misses counted over page-eligible
    # prompts (≥ one full page of potential reuse), so hit_rate is
    # hits / (hits + misses) over prompts the cache could have served
    prefix_cache_misses: int = 0
    prefix_cache_evictions: int = 0
    # full-prefix hits: the whole prompt's KV was cached — admission
    # skips the prompt prefill and runs a single-token resume against a
    # copy-on-write'd final page
    prefix_full_hits: int = 0
    prefix_cow_copies: int = 0
    # gauges refreshed from the cache/allocator each tick
    prefix_pages_resident: int = 0
    prefix_pages_pinned: int = 0
    prefix_cache_hit_rate: float = 0.0
    # adaptive decode window: the K chosen for the most recent dispatch
    # and how often the policy moved it (obs/metrics.py exports these)
    decode_window: int = 0
    window_shrinks: int = 0
    window_grows: int = 0
    # MoE routing surface (ISSUE 18, MoE families only — constant 0 on
    # dense models): cumulative (token, k) expert assignments placed /
    # dropped by the capacity fence across every layer, the resulting
    # drop fraction, and the hottest-expert load imbalance (max
    # per-expert tokens / mean — 1.0 is perfectly balanced). Counts are
    # over rows the programs processed, padding included. The picker
    # prices imbalance with the PR 10 worst-device discipline: a
    # replica is as fast as its hottest expert shard.
    moe_tokens_routed: int = 0
    moe_tokens_dropped: int = 0
    moe_dropped_frac: float = 0.0
    moe_expert_imbalance: float = 0.0
    # serving-path phase breakdown (cumulative milliseconds):
    # prefill_ms = host time blocked on prefill device calls,
    # transfer_ms = host time blocked fetching window tokens,
    # emit_ms = host time distributing tokens to consumers,
    # first_emit_ms = host time from a prefill's sampled token being
    # host-available to its first-token emit callback returning (the
    # fast path's residual: slot setup + prefix-cache insert + emit)
    prefill_ms: float = 0.0
    transfer_ms: float = 0.0
    emit_ms: float = 0.0
    first_emit_ms: float = 0.0
    # prefill padding tax (ISSUE 6): real prompt tokens vs tokens the
    # padded program geometry actually processed (bucket/batch padding
    # on xla-bucketed, chunk-rung residue on pallas-ragged);
    # padded_frac = 1 - real/padded, refreshed per tick — the
    # per-replica observable behind the ragged backend's claim
    prefill_tokens_real: int = 0
    prefill_tokens_padded: int = 0
    prefill_padded_frac: float = 0.0
    # warmup cost: wall time of the last warmup() and the compiled
    # hot-path program count it left behind (compile tracker) — the
    # "collapsed compile surface = faster cold start" observables
    warmup_ms: float = 0.0
    warm_programs: int = 0
    # age of the oldest queued request (picker queue-latency signal)
    queue_wait_ms: float = 0.0
    # XLA compile tracker (obs/xla_events.py): backend compiles observed
    # since the engine came up and their total wall time — refreshed per
    # tick; a post-warmup delta is a hot-path compile regression
    xla_compiles: int = 0
    xla_compile_ms: float = 0.0
    # prefill rate the gateway prices prompt length with (/state
    # prefill_ms_per_token): a token-decayed average rather than the
    # process-lifetime mean, so a traffic-mix change (chunked-sp long
    # prompts start arriving) re-prices within roughly one half-life
    # of prefilled tokens instead of lagging forever. Both
    # accumulators decay by 0.5 ** (tokens / half_life) per observed
    # prefill call, so the ratio is an exponentially weighted mean
    # over the most recent ~PREFILL_RATE_HALF_LIFE_TOKENS tokens.
    prefill_ms_decayed: float = 0.0
    prefill_tokens_decayed: float = 0.0
    # usage metering (ISSUE 20): engine-truth accounting counters,
    # incremented ONLY inside _meter_emit — i.e. exactly when a
    # MeterRecord is handed to the request's sink — so the gateway's
    # ledger totals reconcile against these token-for-token by
    # construction. meter_records counts records emitted;
    # meter_*_tokens mirror the per-record token dimensions; the
    # page_byte_s pair integrates KV residency (HBM + host-parked)
    # in page·byte·seconds, the TPU-native cost dimension.
    meter_records: int = 0
    meter_prefill_tokens: int = 0
    meter_prefill_padded_tokens: int = 0
    meter_prefix_reused_tokens: int = 0
    meter_decode_tokens: int = 0
    meter_spec_drafted: int = 0
    meter_spec_accepted: int = 0
    meter_hbm_page_byte_s: float = 0.0
    meter_host_page_byte_s: float = 0.0

    PREFILL_RATE_HALF_LIFE_TOKENS = 16384

    def note_prefill_call(self, ms: float, tokens: int) -> None:
        """Fold one prefill device call (``ms`` host-blocked time over
        ``tokens`` real prompt tokens) into the decayed rate."""
        if tokens <= 0:
            return
        decay = 0.5 ** (tokens / self.PREFILL_RATE_HALF_LIFE_TOKENS)
        self.prefill_ms_decayed = self.prefill_ms_decayed * decay + ms
        self.prefill_tokens_decayed = (
            self.prefill_tokens_decayed * decay + tokens)

    def prefill_ms_per_token(self) -> float:
        """The advertised per-token prefill rate: the decayed mean once
        any call has been observed, else the lifetime mean (0 cold)."""
        if self.prefill_tokens_decayed > 0:
            return self.prefill_ms_decayed / self.prefill_tokens_decayed
        return self.prefill_ms / max(1, self.prefill_tokens_real)


@dataclass
class _Window:
    """One dispatched decode window: the on-device sampled tokens plus
    everything the host needs to settle it at drain time."""

    sampled: Any  # jax array / tuple of arrays (logprobs, speculation)
    # (slot index, request) pairs the window computes for — slots
    # admitted after dispatch are not in here, so their rows' junk
    # samples are never emitted; a (i, req) pair whose slot has been
    # freed (or re-admitted to a new request) since dispatch is skipped
    members: tuple[tuple[int, GenRequest], ...]
    k: int  # window length actually dispatched
    # sequence ids whose pages become safe to recycle once this window
    # completes (every window dispatched while they were active has
    # then finished — nothing on device can still write their pages)
    frees: list[int]
    # speculative dispatch width (0 = plain decode window) and the
    # per-slot draft lengths at dispatch time ((slot, D_slot) pairs) —
    # the drain-side controller update needs what was actually offered
    draft: int = 0
    draft_lens: tuple[tuple[int, int], ...] = ()
    # constrained slots at DISPATCH time: (slot, rollback epoch, the
    # mask row live on device for the window). A drain whose captured
    # epoch trails the slot's current one discards that slot's tokens
    # (the window was computed past a grammar cut and its row has since
    # been rolled back); the captured mask is the window's sampling
    # distribution — tokens are accepted only while the slot's CURRENT
    # state demands the very same mask, which makes accepted streams
    # bit-identical to true per-step constrained decoding
    cn_epochs: tuple[tuple[int, int, Any], ...] = ()
    # MoE routing stats for the whole window (device [L, E+1] int32 —
    # per-expert placed counts + capacity drops, summed over the k
    # scan steps; None on dense families). Folded into the host
    # accumulators at DRAIN, when the window's results are fetched
    # anyway — reading it at dispatch would force a device sync
    moe: Any = None


class Engine:
    """One model instance on one chip/slice."""

    def __init__(
        self,
        params: dict[str, jax.Array],
        model_cfg: Any,  # LlamaConfig / MixtralConfig (shared attributes)
        cfg: EngineConfig,
        eos_token_ids: tuple[int, ...] = (),
        mesh: Any = None,
        fns: Any = None,  # models.registry.ModelFns; default = llama
        lora_params: dict[str, jax.Array] | None = None,
        adapter_names: tuple[str, ...] = (),
        # adapter serving subsystem (tpuserve/adapters.py): dynamic
        # row residency (hot load / refcounted LRU evict) over the
        # registered zoo. Mutually exclusive with the static
        # lora_params/adapter_names form above (kept for fixed-stack
        # deployments and tests).
        adapter_store: Any = None,
    ):
        from aigw_tpu.models.registry import family_fns

        self.fns = fns or family_fns("llama")
        # multi-LoRA: stacked adapters + name→row map; the LAST row of the
        # stack is the all-zeros base-model row (models/lora.py). With an
        # AdapterStore the stack and the name→row map are DYNAMIC — the
        # lora_params property reads the store fresh at every dispatch
        # (hot loads replace the stacked arrays).
        if adapter_store is not None and (lora_params or adapter_names):
            raise ValueError(
                "pass either adapter_store or lora_params/adapter_names, "
                "not both")
        self._adapter_store = adapter_store
        self._lora_static = lora_params
        if adapter_store is not None:
            self.adapter_rows = {}  # dynamic: resolved via the store
            self._base_row = adapter_store.base_row
        else:
            self.adapter_rows = {n: i for i, n in enumerate(adapter_names)}
            self._base_row = len(adapter_names)
        self.mesh = mesh
        self.params = params
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.eos = eos_token_ids
        if cfg.enable_prefix_cache and self.fns.prefill_suffix is not None:
            self.allocator = RefcountedAllocator(cfg.num_pages, cfg.page_size)
            self.prefix_cache = PrefixCache(self.allocator, cfg.page_size)
        else:
            self.allocator = PageAllocator(cfg.num_pages, cfg.page_size)
            self.prefix_cache = None
        # KV memory hierarchy (ISSUE 11, tpuserve/kvhost.py): the
        # host-RAM spill tier. Eviction demotes registered pages into it
        # (device→host through the warmed page-export program); a prefix
        # hit on a spilled chain revives them through the warmed batched
        # import scatters. Requires the refcounted prefix-cache
        # allocator — without content addressing there is nothing to
        # key the tier by.
        self.host_tier = None
        if cfg.kv_host_bytes > 0 and self.prefix_cache is not None:
            from aigw_tpu.tpuserve.kvhost import HostKVTier

            self.host_tier = HostKVTier(cfg.kv_host_bytes)
            self.prefix_cache.spill_sink = self._spill_page
        # resident+spilled chain-hash digest, refreshed (throttled) on
        # the engine thread and read lock-free by /state and the fleet
        # fetch's presence probe (an atomic tuple swap — a slightly
        # stale digest costs at most one redundant fetch, which the
        # import path dedupes)
        self._kv_digest: tuple[str, ...] = ()
        self._kv_digest_next = 0.0
        self.stats = EngineStats()
        self.stats.kv_quant_bits = kvq.quant_bits(cfg.kv_cache_dtype)
        self.stats.kv_bytes_per_token = round(
            self.kv_page_bytes / cfg.page_size, 3)
        # serving-phase latency histograms (queue_wait/prefill/ttft/…)
        # with trace-id exemplars — /metrics renders them, /state
        # summarizes p50/p95/p99 (obs/metrics.py ENGINE_HISTOGRAMS)
        self.phases = EnginePhases()
        # shared XLA compile tracker: jax.monitoring compile events plus
        # per-program jit-cache accounting over every hot-path callable
        # registered below (obs/xla_events.py — the tripwire surface)
        self.compile_tracker = CompileTracker()
        if self._adapter_store is not None:
            # the hot-load row scatter runs on the admission path: it is
            # part of the tripwire surface and warmed by warmup()
            self._adapter_store._load_fn = self.compile_tracker.register(
                "adapter_load", self._adapter_store._make_load_fn())
        self.healthy = True
        self.last_error: str | None = None

        B = cfg.max_batch_size
        self._slots: list[_Slot | None] = [None] * B
        # slot indices picked by an in-flight _admit_one whose _Slot is
        # not installed yet (the prefill runs between pick and install).
        # sp_chunked_prefill re-enters admission at chunk boundaries —
        # without the reservation a nested _admit_one would pick the
        # same first-None index and the outer install would orphan it.
        self._reserved_slots: set[int] = set()
        self._queue: "queue.Queue[GenRequest]" = queue.Queue()
        # priority-tiered serving (ISSUE 19): the offline batch class.
        # Its queue is SEPARATE (and unbounded — batch never sheds) so
        # every interactive signal stays batch-free for free: the
        # window-shrink pressure predicate, queue_wait_ms, /state
        # ``queued``, and the chunk-boundary interactive admission all
        # read only self._queue. Parked sessions are preempted batch
        # streams cut off-device through the migration export path
        # ({"blob", "data", emit/cancelled/trace}), resumed (oldest
        # first) into slots interactive doesn't want.
        self._batch_q: "queue.Queue[GenRequest]" = queue.Queue()
        self._parked_batch: list[dict] = []
        self._seq_ids = itertools.count()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None

        # device state. With a mesh, weights/cache are laid out with
        # tensor/expert-parallel shardings and every jitted step runs SPMD
        # (GSPMD inserts the collectives; SURVEY.md §2.9). The pool
        # carries ONE extra page past the allocator's range — the fused
        # decode kernel's dump page: its output pipeline must write
        # every slot's append block somewhere, and inactive slots land
        # here instead of whatever page their stale table row names
        # (the XLA paths get the same guarantee from OOB-drop
        # scatters). Never allocated, never referenced by a page
        # table, excluded from capacity accounting.
        kv_shape = (
            model_cfg.n_layers,
            2,
            (cfg.num_pages + 1) * cfg.page_size,
            model_cfg.n_kv_heads,
            model_cfg.head_dim,
        )
        if mesh is not None:
            from jax.sharding import NamedSharding

            from aigw_tpu.parallel.sharding import (
                kv_cache_spec,
                llama_param_specs,
                mixtral_param_specs,
            )

            specs = (
                mixtral_param_specs(model_cfg)
                if hasattr(model_cfg, "n_experts")
                else llama_param_specs(model_cfg)
            )

            def spec_for(key: str, value) -> object:
                # quantized weights: name.q shards like the base matrix;
                # name.scale keeps the base spec only on axes it actually
                # has extent in (keepdims axes of size 1 stay unsharded)
                from jax.sharding import PartitionSpec as P

                if key.endswith(".q"):
                    return specs[key[:-2]]
                if key.endswith(".scale"):
                    # int8: keepdims size-1 axes stay unsharded. int4:
                    # group axes ([.., in/G, out]) shard like the base
                    # only when divisible by the mesh axis — a group
                    # count smaller than the axis replicates instead of
                    # failing device_put
                    base = specs[key[: -len(".scale")]]

                    def ok(i: int, ax) -> bool:
                        if value.shape[i] <= 1 or ax is None:
                            return False
                        return value.shape[i] % mesh.shape[ax] == 0

                    return P(*(
                        ax if ok(i, ax) else None
                        for i, ax in enumerate(base)
                    ))
                return specs[key]

            self.params = {
                k: jax.device_put(v, NamedSharding(mesh, spec_for(k, v)))
                for k, v in params.items()
            }
            pool = kvq.make_pool(kv_shape, cfg.kv_cache_dtype)
            self.kv_cache = jax.device_put(
                pool, kvq.pool_sharding_tree(pool, mesh, kv_cache_spec()))
        else:
            self.kv_cache = kvq.make_pool(kv_shape, cfg.kv_cache_dtype)
        # Per-slot decode state lives ON DEVICE between ticks (uploaded
        # only when membership/sampling changes) — the decode hot loop
        # transfers just the sampled [K, B] tokens per round-trip.
        self._device_state: dict[str, jax.Array] | None = None
        # Incremental device-state maintenance: membership changes mark
        # individual rows dirty and are scattered into the live state
        # with a tiny jitted row update — no pipeline drain, no full
        # [B, V] re-upload. The speculative history/lookahead rows ride
        # the SAME path (a [H] row upload per admission), so a full
        # rebuild happens only when the page bucket grows or on first
        # use — never because a slot speculates.
        self._dirty_rows: set[int] = set()
        # live slots whose adaptive draft rung moved: patched on device
        # by a draft_len-ONLY scatter (_apply_spec_row_updates). A live
        # slot's full row must never be re-uploaded mid-pipeline — the
        # host's positions lag the in-flight window — but draft_len is
        # position-independent and safe to patch any time.
        self._spec_dirty: set[int] = set()
        # constrained slots whose FSM advanced: their bias row (user
        # bias + the new state's token mask) is patched on device by a
        # bias-ONLY scatter before the next dispatch. Like draft_len,
        # the bias row is position-independent — safe mid-pipeline.
        self._cn_dirty: set[int] = set()
        self._cn_update_fn = None
        # jax memory_stats() polling throttle (a per-tick native call
        # is cheap but pointless at engine-tick frequency)
        self._mem_next = 0.0
        self._need_rebuild = True
        self._state_bucket = 0  # page bucket the live state was built at
        self._row_update_fn = None
        self._spec_update_fn = None
        # copy-on-write page clone (full-prefix hits): one compiled
        # program regardless of src/dst ids (dynamic slice indices)
        self._copy_page_fn = None
        # migration page movers (ISSUE 8): device→host page gather and
        # host→device page scatter, each ONE compiled program for any
        # page id (dynamic indices) — pre-compiled by warmup() so an
        # import/resume never compiles on the hot path
        self._export_page_fn = None
        self._import_page_fn = None
        # migration control queue: export/import jobs posted by server
        # threads, executed on the engine thread (which owns kv_cache's
        # donation chain and the slot table)
        self._mig_q: "queue.Queue[tuple]" = queue.Queue()
        # 1-deep pipeline: the window dispatched to the device while the
        # host processes the previous window's tokens.
        self._inflight: _Window | None = None
        # pages owned by finished sequences are recycled only after
        # every window dispatched while they were active completes (an
        # in-flight window may still write into them). Frees discovered
        # here are captured by the NEXT dispatch and applied when that
        # window drains.
        self._pending_frees: list[int] = []
        # adaptive decode window state
        self._cur_window = cfg.decode_steps_per_tick
        self._steady_ticks = 0

        # per-device accounting (ISSUE 10): bytes of model weights each
        # device actually holds (measured from shard layouts — the
        # bench's per-device-bytes ≈ total/tp claim), the analytical
        # per-device ICI collective volume of one decoded token, and
        # the rolling per-device stats list _refresh_stats maintains
        self.param_bytes_by_device = _per_device_bytes(self.params)
        from aigw_tpu.parallel.sharding import (
            analytical_ici_bytes_per_token,
        )

        act_bytes = 2
        for v in self.params.values():
            act_bytes = jnp.dtype(v.dtype).itemsize
            break
        self.ici_bytes_per_token = analytical_ici_bytes_per_token(
            model_cfg, mesh, act_bytes)
        self.stats.ici_bytes_per_token = self.ici_bytes_per_token
        self.device_stats: list[dict] = []

        mc, ps = model_cfg, cfg.page_size
        K = cfg.decode_steps_per_tick
        # decode attention rung (the /state-exported half of the
        # fallback matrix — tpuserve/attention.resolve_decode_backend
        # documents the full requested × mesh × TPU × kv-dtype table;
        # resolve_attention_backend documents the prefill half)
        from aigw_tpu.tpuserve.attention import resolve_decode_backend

        self.decode_attn_impl, self.decode_attn_reason = (
            resolve_decode_backend(cfg, model_cfg, mesh))
        if (cfg.pallas_attn or cfg.decode_backend == "fused") \
                and self.decode_attn_impl == "xla-gather":
            logger.warning("decode backend fell back to xla-gather: %s",
                           self.decode_attn_reason)
        # decode_step's attn_impl argument + whether it needs the mesh
        attn_impl = {
            "xla-gather": "",
            "pallas": "pallas",
            "fused-xla": "fused",
            "fused-xla-spmd": "fused",
            "fused-pallas": "fused-pallas",
        }[self.decode_attn_impl]
        decode_mesh = mesh if self.decode_attn_impl == "fused-xla-spmd" \
            else None
        # the speculative verify step keeps the chained path at every
        # rung: its multi-position kernel has no fused port, and the
        # gather-dequant path serves quantized pools
        self.verify_attn_impl = (
            "pallas" if self.decode_attn_impl == "pallas" else "")

        model_prefill = self.fns.prefill
        model_decode = self.fns.decode_step

        # MoE routing stats (ISSUE 18): MoE families (ModelFns with
        # moe_stats=True) take a static ``moe_stats=True`` kwarg and
        # return a trailing [L, E+1] int32 routing-stats leaf —
        # per-expert placed (token, k) counts + capacity drops per
        # layer. Every jitted wrapper below returns that leaf in a
        # uniform trailing position (None on dense families: a leafless
        # pytree node, so the llama programs stay byte-identical) and
        # the host call sites fold it into the numpy accumulators via
        # _fold_moe. No extra device→host sync: the leaf rides the
        # result fetches the host already makes.
        self._moe = bool(getattr(self.fns, "moe_stats", False))
        is_moe = self._moe
        moe_kw = {"moe_stats": True} if is_moe else {}
        self._moe_experts = (int(getattr(model_cfg, "n_experts", 0))
                             if is_moe else 0)
        self._moe_expert_tokens = np.zeros(
            max(self._moe_experts, 1), np.int64)
        self._moe_layer_drops = np.zeros(
            max(int(model_cfg.n_layers), 1), np.int64)

        def _moe_split(out):
            """Normalize a model-entry-point result to
            (logits, kv, moe-or-None)."""
            if is_moe:
                return out
            logits, kv = out
            return logits, kv, None

        # Mesh jit-cache discipline (ISSUE 10): the per-slot decode
        # state chains through donated programs, and GSPMD is free to
        # give output leaves shardings that differ from the host-built
        # state's placement — the NEXT dispatch then misses the jit
        # cache on layout alone and compiles ON THE HOT PATH (the
        # CompileTracker caught the verify ladder doing exactly this at
        # second dispatch). Pinning every state leaf to one canonical
        # sharding — replicated; the state is small next to params/KV —
        # both at build time (device_put) and at every program output
        # (with_sharding_constraint inside the jitted fn) makes the
        # cache key a pure function of shape, exactly like single-chip.
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            state_sharding = NamedSharding(mesh, PartitionSpec())

            def _pin_state(st: dict) -> dict:
                return {
                    k: jax.lax.with_sharding_constraint(v, state_sharding)
                    for k, v in st.items()
                }
        else:
            state_sharding = None

            def _pin_state(st: dict) -> dict:
                return st

        self._state_sharding = state_sharding
        self._pin_state = _pin_state

        def _sample_maybe_lp(logits, keys, temp, top_p, top_k):
            """Sample; with logprobs enabled also return (chosen, top-k
            ids/vals) over the distribution actually sampled from."""
            sampled = sample(logits, keys, temp, top_p, top_k)
            if not cfg.logprobs_topk:
                return sampled
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            chosen = logp[jnp.arange(sampled.shape[0]), sampled]
            tk_vals, tk_ids = jax.lax.top_k(logp, cfg.logprobs_topk)
            return sampled, chosen, tk_ids, tk_vals

        def _prefill_step(params, lora, tokens, seq_lens, kv, page_table,
                          keys, temp, top_p, top_k, bias, adapter_idx):
            logits, kv, moe = _moe_split(model_prefill(
                params, mc, tokens, seq_lens, kv, page_table, ps,
                lora=lora, adapter_idx=adapter_idx, **moe_kw))
            return _sample_maybe_lp(logits + bias, keys, temp, top_p,
                                    top_k), kv, moe

        model_prefill_suffix = self.fns.prefill_suffix

        def _prefill_suffix_step(params, lora, tokens, prefix_lens,
                                 seq_lens, kv, page_table, keys, temp,
                                 top_p, top_k, bias, adapter_idx):
            logits, kv, moe = _moe_split(model_prefill_suffix(
                params, mc, tokens, prefix_lens, seq_lens, kv, page_table,
                ps, lora=lora, adapter_idx=adapter_idx, **moe_kw))
            return _sample_maybe_lp(logits + bias, keys, temp, top_p,
                                    top_k), kv, moe

        # sequence-parallel (ring attention) prefill for long prompts on
        # an sp mesh (SURVEY §2.9 context parallelism)
        self._sp = int(mesh.shape.get("sp", 1)) if mesh is not None else 1
        self._prefill_sp_fn = None
        if self._sp > 1 and self.fns.prefill_sp is not None:
            model_prefill_sp = self.fns.prefill_sp

            def _prefill_sp_step(params, lora, tokens, seq_lens, kv,
                                 page_table, keys, temp, top_p, top_k,
                                 bias, adapter_idx):
                logits, kv, moe = _moe_split(model_prefill_sp(
                    params, mc, tokens, seq_lens, kv, page_table, ps,
                    mesh=mesh, lora=lora, adapter_idx=adapter_idx,
                    **moe_kw))
                return _sample_maybe_lp(logits + bias, keys, temp, top_p,
                                        top_k), kv, moe

            self._prefill_sp_fn = jax.jit(_prefill_sp_step,
                                          donate_argnums=(4,))

        # sequence-sharded CHUNKED prefill: the prefill_suffix contract
        # (resume at a page-aligned offset, full-window gather) with
        # ring attention per chunk — the long-context path. Requires
        # page_size % sp == 0 so the gathered page window shards evenly
        # over the sp axis; other geometries (e.g. sp=6, page 128) fall
        # back to the monolithic program above.
        self._prefill_sp_suffix_fn = None
        if (self._sp > 1 and self.fns.prefill_sp_suffix is not None
                and cfg.sp_prefill_mode == "chunked"
                and ps % self._sp == 0):
            model_prefill_sp_suffix = self.fns.prefill_sp_suffix

            def _prefill_sp_suffix_step(params, lora, tokens,
                                        prefix_lens, seq_lens, kv,
                                        page_table, keys, temp, top_p,
                                        top_k, bias, adapter_idx):
                logits, kv, moe = _moe_split(model_prefill_sp_suffix(
                    params, mc, tokens, prefix_lens, seq_lens, kv,
                    page_table, ps, mesh=mesh, lora=lora,
                    adapter_idx=adapter_idx, **moe_kw))
                return _sample_maybe_lp(logits + bias, keys, temp,
                                        top_p, top_k), kv, moe

            self._prefill_sp_suffix_fn = jax.jit(
                _prefill_sp_suffix_step, donate_argnums=(5,))

        def _decode_scan(k: int, lean: bool = False):
            """Factory: k fused decode+sample steps; sampled tokens feed
            forward on-device (no host round-trip inside the window).
            Each window length is one compiled program (the adaptive
            ladder is {min, max} so at most two exist per bucket).

            ``lean``: compiled WITHOUT the repetition-penalty ops (the
            per-step [B, V] counts scatter-add and both penalty terms —
            logit bias stays). Dispatched whenever no active slot uses
            penalties: zero penalties contribute exactly 0.0 to every
            logit, so lean and full windows sample bit-identical tokens
            while the lean program drops the most expensive non-matmul
            ops from the hot loop. Device-side counts go stale for
            penalty-free slots during lean windows — harmless (their
            penalty coefficients are zero) and refreshed from the
            host-side token_counts whenever a penalized admission
            switches the engine back to the full program."""
            lp_k = cfg.logprobs_topk

            def body(params, lora, carry):
                kv, st, macc = carry
                act = st["active"] & (st["positions"] < st["limits"])
                logits, kv, moe = _moe_split(model_decode(
                    params, mc, st["tokens"], st["positions"], kv,
                    st["page_table"], ps, act,
                    lora=lora, adapter_idx=st["adapter_idx"],
                    attn_impl=attn_impl, mesh=decode_mesh, **moe_kw))
                macc = macc if moe is None else macc + moe
                if lean:
                    logits = logits + st["bias"]
                else:
                    logits = apply_penalties(
                        logits, st["counts"], st["freq_pen"],
                        st["pres_pen"], st["bias"],
                    )
                sampled = sample(logits, st["keys"], st["temp"],
                                 st["top_p"], st["top_k"])
                step = act.astype(jnp.uint32)
                B = sampled.shape[0]
                counts = (st["counts"] if lean
                          else st["counts"].at[
                              jnp.arange(B), sampled
                          ].add(act.astype(st["counts"].dtype)))
                new = dict(
                    st,
                    tokens=jnp.where(act, sampled, st["tokens"]),
                    positions=jnp.where(act, st["positions"] + 1,
                                        st["positions"]),
                    keys=st["keys"].at[:, 1].add(step),
                    counts=counts,
                )
                if lp_k:  # static: 0 compiles the exact round-3 program
                    # logprobs over the PENALIZED distribution — the one
                    # the token was actually sampled from
                    logp = jax.nn.log_softmax(
                        logits.astype(jnp.float32), axis=-1)
                    chosen = logp[jnp.arange(B), sampled]
                    tk_vals, tk_ids = jax.lax.top_k(logp, lp_k)
                    return (kv, new, macc), (sampled, chosen, tk_ids,
                                             tk_vals)
                return (kv, new, macc), sampled

            def scan_k(params, lora, kv, state):
                macc0 = (jnp.zeros((mc.n_layers, mc.n_experts + 1),
                                   jnp.int32) if is_moe else None)
                (kv, state, macc), sampled = jax.lax.scan(
                    lambda c, _: body(params, lora, c),
                    (kv, state, macc0), None, length=k
                )
                return sampled, _pin_state(state), kv, macc

            return scan_k

        # speculative decoding (tpuserve/speculation.py): a rung ladder
        # of [B, D+1] verify programs replaces the [B, 1] decode step
        # whenever an eligible slot's adaptive controller holds a
        # nonzero draft length; a step advances by the accepted draft
        # count. Same fixed-geometry contract — one compiled program
        # per rung, warmed like the prefill ladder.
        self._spec_rungs = (
            speculation.draft_rungs(cfg.spec_tokens)
            if cfg.spec_tokens > 0 and self.fns.verify_step is not None
            else (0,)
        )
        self._spec_max = self._spec_rungs[-1]
        self._accept_prior = speculation.AcceptancePrior()
        model_verify = self.fns.verify_step
        verify_impl = self.verify_attn_impl
        V = model_cfg.vocab_size
        H = cfg.max_seq_len

        def _spec_scan(k_steps: int, D: int):
            """Factory: k speculative steps at draft rung D; outputs
            (sampled [k, B, D+1], n_emit [k, B]) — the host emits
            sampled[k, b, :n_emit[k, b]]. Slots whose per-slot
            ``draft_len`` row sits below D get the excess candidate
            positions poisoned on device: they still advance ≥1
            model-exact token per step, just without the extra
            drafts."""
            D1 = D + 1

            def body(params, lora, carry):
                kv, st, macc = carry
                act = st["active"] & (st["positions"] < st["limits"])
                # penalty and sampling slots advance exactly one token
                # per step (see speculation.py module docstring):
                # poison their drafts
                elig = ((st["freq_pen"] == 0.0)
                        & (st["pres_pen"] == 0.0)
                        & (st["temp"] <= 0.0))
                # multi-source drafts: prefix-cache continuation where
                # the lookahead buffer covers the position, n-gram
                # prompt lookup everywhere else
                ng = speculation.ngram_drafts(
                    st["history"], st["positions"], D)
                la = speculation.lookahead_drafts(
                    st["lookahead"], st["la_base"], st["la_len"],
                    st["positions"], D)
                drafts = speculation.combine_drafts(la, ng)
                d_off = jnp.arange(D, dtype=jnp.int32)[None, :]
                ok = elig[:, None] & (d_off < st["draft_len"][:, None])
                drafts = jnp.where(ok, drafts, -1)
                inputs = jnp.concatenate(
                    [st["tokens"][:, None], jnp.maximum(drafts, 0)], axis=1
                )
                logits_all, kv, moe = _moe_split(model_verify(
                    params, mc, inputs, st["positions"], kv,
                    st["page_table"], ps, act, st["limits"],
                    lora=lora, adapter_idx=st["adapter_idx"],
                    attn_impl=verify_impl, **moe_kw))  # [B, D1, V]
                macc = macc if moe is None else macc + moe
                # counts are window-start values: exact at d=0, and later
                # positions only accept on penalty-free slots where the
                # count term is zero anyway
                lT = logits_all.transpose(1, 0, 2)  # [D1, B, V]
                lT = jax.vmap(
                    lambda l: apply_penalties(
                        l, st["counts"], st["freq_pen"], st["pres_pen"],
                        st["bias"],
                    )
                )(lT)
                # per-position keys [seed, pos+d] — the same key the
                # non-speculative path would use at that position, so
                # accepted tokens are bit-identical to plain decoding
                offs = jnp.arange(D1, dtype=jnp.uint32)
                keys_d = (
                    jnp.broadcast_to(st["keys"], (D1,) + st["keys"].shape)
                    .at[:, :, 1].add(offs[:, None])
                )
                sampled = jax.vmap(
                    lambda l, k: sample(l, k, st["temp"], st["top_p"],
                                        st["top_k"])
                )(lT, keys_d).T  # [B, D1]
                n_emit, emit_mask = spec_accept(
                    drafts, sampled, act,
                    st["limits"] - st["positions"])
                B = sampled.shape[0]
                rows = jnp.arange(B)
                new_pending = sampled[rows, jnp.clip(n_emit - 1, 0, D)]
                d_idx = jnp.arange(D1, dtype=jnp.int32)[None, :]
                # sampled[d] is the token at position pos+1+d
                wpos = jnp.where(emit_mask,
                                 st["positions"][:, None] + 1 + d_idx, H)
                history = st["history"].at[rows[:, None], wpos].set(
                    sampled, mode="drop"
                )
                counts = st["counts"].at[
                    rows[:, None], jnp.where(emit_mask, sampled, V)
                ].add(1, mode="drop")
                new = dict(
                    st,
                    tokens=jnp.where(n_emit > 0, new_pending, st["tokens"]),
                    positions=st["positions"] + n_emit,
                    keys=st["keys"].at[:, 1].add(n_emit.astype(jnp.uint32)),
                    counts=counts,
                    history=history,
                )
                # draft tokens actually OFFERED this step (the longest
                # non-poisoned prefix) — the host-side controllers
                # distinguish proposed-and-rejected from nothing-to-
                # propose, and spec_drafted counts real proposals
                n_prop = jnp.sum(jnp.cumprod(
                    (drafts >= 0).astype(jnp.int32), axis=1), axis=1)
                n_prop = jnp.where(act, n_prop, 0)
                return (kv, new, macc), (sampled, n_emit, n_prop)

            def scan_k(params, lora, kv, state):
                macc0 = (jnp.zeros((mc.n_layers, mc.n_experts + 1),
                                   jnp.int32) if is_moe else None)
                (kv, state, macc), out = jax.lax.scan(
                    lambda c, _: body(params, lora, c),
                    (kv, state, macc0), None, length=k_steps)
                return out, _pin_state(state), kv, macc

            return scan_k

        self._prefill_fn = self.compile_tracker.register(
            "prefill", jax.jit(_prefill_step, donate_argnums=(4,)))
        self._prefill_suffix_fn = self.compile_tracker.register(
            "prefill_suffix",
            jax.jit(_prefill_suffix_step, donate_argnums=(5,)))
        if self._prefill_sp_fn is not None:
            self.compile_tracker.register("prefill_sp",
                                          self._prefill_sp_fn)
        if self._prefill_sp_suffix_fn is not None:
            self.compile_tracker.register("prefill_sp_chunked",
                                          self._prefill_sp_suffix_fn)
        # ragged packed prefill (the pallas-ragged backend's single
        # program family — one compiled shape per token-budget rung).
        # Attention impl: the Pallas kernel on TPU, the XLA windowed
        # reference elsewhere (auto-fallback; AIGW_RAGGED_PREFILL_IMPL
        # in {xla, pallas} overrides for A/B and parity tests).
        self._prefill_ragged_fn = None
        self._ragged_impl = ""
        self._ragged_reason = ("no ragged prefill entry point "
                               "(hand-built ModelFns)")
        model_prefill_ragged = self.fns.prefill_ragged
        if model_prefill_ragged is not None:
            from aigw_tpu.ops.pallas._compat import is_tpu_backend

            impl = os.environ.get("AIGW_RAGGED_PREFILL_IMPL", "").lower()
            if impl not in ("xla", "pallas"):
                impl = ("pallas" if is_tpu_backend() and mesh is None
                        else "xla")
            if impl == "pallas" and mesh is not None:
                # the kernel's scalar-prefetch page walk addresses ONE
                # local pool — honor the explicit override only where
                # it can run
                impl = "xla"
            quant_kv = kvq.is_quantized_dtype(cfg.kv_cache_dtype)
            if impl == "pallas" and quant_kv:
                # narrowed matrix row: the ragged prefill kernel has no
                # quantized-pool rung — the XLA windowed program
                # dequantizes prefix pages at the read
                impl = "xla"
            self._ragged_impl = "" if impl == "xla" else "pallas"
            if self._ragged_impl == "pallas":
                self._ragged_reason = "Pallas kernel (single-chip TPU)"
            elif quant_kv:
                self._ragged_reason = (
                    f"XLA windowed fallback: {cfg.kv_cache_dtype} KV "
                    "pages — the ragged prefill kernel has no "
                    "quantized-pool rung; the windowed program "
                    "dequantizes prefix pages at the read")
            elif mesh is not None:
                self._ragged_reason = (
                    "XLA windowed fallback: the Pallas ragged-prefill "
                    "kernel is single-chip (scalar-prefetch page walk "
                    "over one local pool); the windowed program runs "
                    "SPMD with KV sharded on heads")
            else:
                self._ragged_reason = (
                    "XLA windowed fallback: no TPU backend")
            ragged_impl = self._ragged_impl

            def _prefill_ragged_step(params, lora, tokens, row_seq,
                                     positions, last_rows, kv,
                                     page_table, keys, temp, top_p,
                                     top_k, bias, adapter_idx):
                logits, kv, moe = _moe_split(model_prefill_ragged(
                    params, mc, tokens, row_seq, positions, last_rows,
                    kv, page_table, ps, attn_impl=ragged_impl,
                    lora=lora, adapter_idx=adapter_idx, **moe_kw))
                return _sample_maybe_lp(logits + bias, keys, temp,
                                        top_p, top_k), kv, moe

            self._prefill_ragged_fn = self.compile_tracker.register(
                "prefill_ragged",
                jax.jit(_prefill_ragged_step, donate_argnums=(6,)))
        self._decode_scan_factory = _decode_scan
        self._spec_scan_factory = _spec_scan
        self._decode_fns: dict[tuple[int, bool, int], Callable] = {}
        # admission burst bookkeeping for lifecycle traces: (id, size)
        # of the burst currently being admitted
        self._burst_seq = itertools.count(1)
        self._cur_burst: tuple[int, int] = (0, 0)
        # reentrancy latch for chunk-boundary admission: a short
        # request admitted mid-chunk-loop may itself run a chunked
        # (non-sp) prefill whose boundaries must NOT admit again
        self._in_chunk_admit = False
        # prefill attention backend (tpuserve/attention.py): owns the
        # prefill programs + geometry policy behind _admit's dispatch
        from aigw_tpu.tpuserve.attention import make_attention_backend

        self.attn = make_attention_backend(self)
        # populate the per-device /state surface before any traffic
        # (telemetry consumers poll a freshly booted replica)
        self._refresh_stats()

    def _decode_fn_for(self, k: int, lean: bool = False,
                       draft: int = 0):
        """Jitted decode program for window length k at draft rung
        ``draft`` (0 = plain decode; cached; jit itself caches per
        page-bucket shape). ``lean`` selects the penalty-free plain
        variant (verify programs have no lean variant — their
        draft-eligibility logic reads the penalty fields)."""
        if draft:
            lean = False
        fn = self._decode_fns.get((k, lean, draft))
        if fn is None:
            scan = (self._spec_scan_factory(k, draft) if draft
                    else self._decode_scan_factory(k, lean))
            fn = jax.jit(scan, donate_argnums=(2, 3))
            self._decode_fns[(k, lean, draft)] = fn
            self.compile_tracker.register(
                f"decode[k={k},lean={lean},d={draft}]", fn)
        return fn

    # -- adapter rows (tpuserve/adapters.py) -------------------------------
    @property
    def lora_params(self):
        """The stacked LoRA arrays for the NEXT dispatch. With an
        AdapterStore this must be read fresh every dispatch — hot loads
        replace the stacked arrays (donated row writes)."""
        if self._adapter_store is not None:
            return self._adapter_store.params or None
        return self._lora_static

    def _adapter_known(self, name: str) -> bool:
        if self._adapter_store is not None:
            return self._adapter_store.knows(name)
        return name in self.adapter_rows

    def _acquire_adapter(self, name: str) -> int:
        """Resolve an adapter name to its device row for a new slot,
        pinning (and hot-loading, when non-resident) the row in store
        mode. Raises adapters.UnknownAdapterError for names outside the
        zoo and adapters.AdapterCapacityError when every row is pinned
        (caller requeues, like KV page pressure)."""
        if self._adapter_store is not None:
            return self._adapter_store.acquire(name)
        row = self.adapter_rows.get(name)
        if row is None:
            from aigw_tpu.tpuserve.adapters import UnknownAdapterError

            raise UnknownAdapterError(name)
        return row

    def _release_adapter_row(self, row: int) -> None:
        """Drop a slot's pin on its adapter row. Safe at slot-free time
        even with a window in flight: a freed slot's window outputs are
        discarded at drain (members check), and device-side reads of a
        subsequently rewritten row are ordered behind the in-flight
        computation by the normal JAX dependency chain."""
        if self._adapter_store is not None and row != self._base_row:
            self._adapter_store.release(row)

    def _adapter_row_of(self, req: GenRequest) -> int:
        """Device row for an ADMITTED request (the attention backends'
        sampling-row builder). In store mode the row was acquired at
        admission, so the lookup must succeed — a missing name here is
        an acquire-ordering bug, not routine miss traffic."""
        if not req.adapter:
            return self._base_row
        if self._adapter_store is not None:
            return self._adapter_store.row_of(req.adapter)
        return self.adapter_rows.get(req.adapter, self._base_row)

    # -- tenant fairness ----------------------------------------------------
    def _tenant_slots(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for s in self._slots:
            if s is not None:
                t = s.req.tenant
                counts[t] = counts.get(t, 0) + 1
        return counts

    def _fair_admission(
        self, pending: list[GenRequest], free: int,
    ) -> tuple[list[GenRequest], list[GenRequest], int]:
        """(admit_now, requeue, n_capped): the fairness guard over one
        admission pass. The per-tenant slot cap defers requests whose
        tenant already holds (or would reach) ``tenant_slot_cap``
        in-flight slots; remaining requests are deficit-ordered —
        tenants with fewer live slots admit first, arrival order kept
        within a tenant — so a multi-tenant burst splits the batch
        instead of first-come-take-all. ``requeue`` preserves arrival
        order (deferred + past-``free`` overflow). Single-tenant
        traffic with nothing live passes through untouched."""
        cap = self.cfg.tenant_slot_cap
        live = self._tenant_slots()
        if cap <= 0 and len({r.tenant for r in pending} | set(live)) <= 1:
            return pending[:free], pending[free:], 0
        taken: dict[str, int] = {}
        eligible: list[GenRequest] = []
        capped: list[GenRequest] = []
        for req in pending:
            t = req.tenant
            if cap > 0 and live.get(t, 0) + taken.get(t, 0) >= cap:
                capped.append(req)
                continue
            taken[t] = taken.get(t, 0) + 1
            eligible.append(req)
        if len({r.tenant for r in eligible}) > 1:
            # deficit round-robin in ONE pass (ISSUE 19 satellite — the
            # old scan re-walked the whole remainder per admission,
            # O(n²) on the queue bound): per-tenant FIFOs + a heap
            # keyed (live-slot count, head arrival index). Only a
            # tenant's HEAD can ever win the old min-scan (same count,
            # earlier position than its followers), and comparing head
            # positions across tenants is comparing arrival indices —
            # so popping the heap min and re-pushing the tenant at
            # count+1 with its next head reproduces the old order
            # exactly (tests/test_batch_tier.py holds the old loop as
            # the property-test oracle).
            fifos: dict[str, list[tuple[int, GenRequest]]] = {}
            for j, req in enumerate(eligible):
                fifos.setdefault(req.tenant, []).append((j, req))
            heap = [(live.get(t, 0), lst[0][0], t)
                    for t, lst in fifos.items()]
            heapq.heapify(heap)
            heads = dict.fromkeys(fifos, 0)
            ordered: list[GenRequest] = []
            while heap:
                cnt, _, t = heapq.heappop(heap)
                lst, h = fifos[t], heads[t]
                ordered.append(lst[h][1])
                heads[t] = h + 1
                if h + 1 < len(lst):
                    heapq.heappush(heap, (cnt + 1, lst[h + 1][0], t))
            eligible = ordered
        admit = eligible[:free]
        left = set(map(id, capped)) | set(map(id, eligible[free:]))
        requeue = [r for r in pending if id(r) in left]  # arrival order
        return admit, requeue, len(capped)

    def _lean_decode_ok(self) -> bool:
        """True when no active slot uses repetition penalties — the
        lean decode program samples bit-identical tokens (zero
        penalties add exactly 0.0 per logit). Only consulted for
        plain-decode dispatches (draft rung 0)."""
        return all(
            s is None
            or (s.req.sampling.frequency_penalty == 0.0
                and s.req.sampling.presence_penalty == 0.0)
            for s in self._slots
        )

    def _prefill_bucket(self, n: int, multiple_of: int = 1) -> int:
        """Smallest prefill-ladder rung covering ``n`` prompt tokens.
        Rungs are powers of two of min_prefill_bucket plus, with
        prefill_bucket_rungs > 1, intermediate rungs at 1.5×S (and
        1.25×/1.75×S at 4) — prefill compute scales with the padded
        length, so a tighter rung is a direct TTFT cut.

        ``multiple_of`` is the mesh divisibility guard (ISSUE 10): a
        program whose padded length an axis shards (ring attention over
        ``sp``) must divide that axis, but the 1.5×S rungs usually
        don't — the guard rounds the CHOSEN rung up to the next
        multiple instead of abandoning the intermediate ladder, so mesh
        prompts keep the sub-pow2 rungs (a 96-token prompt on sp=8
        pads to 96, not 128)."""
        cfg = self.cfg
        S = cfg.min_prefill_bucket
        while S < n:
            if cfg.prefill_bucket_rungs >= 4 and n <= S + S // 4:
                S += S // 4
                break
            if cfg.prefill_bucket_rungs >= 2 and n <= S + S // 2:
                S += S // 2
                break
            if cfg.prefill_bucket_rungs >= 4 and n <= S + 3 * S // 4:
                S += 3 * S // 4
                break
            S *= 2
        S = min(S, cfg.max_seq_len)
        if multiple_of > 1 and S % multiple_of:
            S = -(-S // multiple_of) * multiple_of
        return S

    def _bucket_rungs(self, octave: int) -> list[int]:
        """The prefill-ladder rungs of one octave (octave 0 starts at
        min_prefill_bucket), ascending, capped at max_seq_len."""
        S = self.cfg.min_prefill_bucket << octave
        quarters = {1: (4,), 2: (4, 6), 4: (4, 5, 6, 7)}[
            self.cfg.prefill_bucket_rungs]
        return sorted({
            min(S * q // 4, self.cfg.max_seq_len) for q in quarters
        })

    def _copy_page_dev(self, src: int, dst: int) -> None:
        """Clone one KV page on-device (copy-on-write for full-prefix
        hits). Dynamic slice indices: ONE compiled program for any
        (src, dst) pair; the kv_cache donation chain orders the copy
        after every already-dispatched window that reads ``src``."""
        if self._copy_page_fn is None:
            ps = self.cfg.page_size

            def _cp(kv, src_page, dst_page):
                # tree_map: the quantized pool's scale leaf pages on
                # the same slot axis, so a page copy moves its scale
                # block with it
                def cp_leaf(leaf):
                    rows = jax.lax.dynamic_slice_in_dim(
                        leaf, src_page * ps, ps, axis=2)
                    return jax.lax.dynamic_update_slice_in_dim(
                        leaf, rows, dst_page * ps, axis=2)

                return jax.tree_util.tree_map(cp_leaf, kv)

            self._copy_page_fn = self.compile_tracker.register(
                "copy_page", jax.jit(_cp, donate_argnums=(0,)))
        self.kv_cache = self._copy_page_fn(
            self.kv_cache, jnp.int32(src), jnp.int32(dst))

    def _export_page_dev(self, page: int):
        """Gather one KV page off the pool (device side of a migration
        export). Dynamic page index: ONE compiled program for any page;
        the caller starts the device→host copy asynchronously so the
        per-page transfers overlap (the async-transfer machinery)."""
        if self._export_page_fn is None:
            ps = self.cfg.page_size

            def _ex(kv, pg):
                return jax.tree_util.tree_map(
                    lambda leaf: jax.lax.dynamic_slice_in_dim(
                        leaf, pg * ps, ps, axis=2), kv)

            self._export_page_fn = self.compile_tracker.register(
                "page_export", jax.jit(_ex))
        return self._export_page_fn(self.kv_cache, jnp.int32(page))

    def _import_rungs(self) -> list[int]:
        """Page-count rungs of the batched import program: powers of
        two covering 1..max_pages_per_seq — one compiled program per
        rung for ANY destination page set."""
        rungs = []
        r = 1
        while True:
            rungs.append(r)
            if r >= self.cfg.max_pages_per_seq:
                return rungs
            r *= 2

    def _import_pages_dev(self, page_ids: list[int],
                          rows_np: list) -> None:
        """Scatter ``len(page_ids)`` host-side KV pages into the pool in
        ONE donated device call (a fori_loop of dynamic row updates).
        The page count pads to a pow2 rung by REPEATING the last
        (page, rows) pair — an idempotent rewrite, so no mask branch is
        compiled. One program per rung; all rungs pre-compiled by
        warmup(). Batching matters: per-page donated calls copy the
        whole pool once per page on backends without buffer donation."""
        k = len(page_ids)
        if k == 0:
            return
        ps = self.cfg.page_size
        if self._import_page_fn is None:

            def _im(kv, pages, rows):
                def body(i, kv):
                    return jax.tree_util.tree_map(
                        lambda leaf, r: jax.lax.dynamic_update_slice_in_dim(
                            leaf, r[i], pages[i] * ps, axis=2),
                        kv, rows)

                return jax.lax.fori_loop(0, pages.shape[0], body, kv)

            self._import_page_fn = self.compile_tracker.register(
                "page_import", jax.jit(_im, donate_argnums=(0,)))
        R = 1
        while R < k:
            R *= 2
        pages = np.full((R,), page_ids[-1], np.int32)
        pages[:k] = page_ids
        # rows_np: a LIST of host-side pages — np [L, 2, ps, Hkv, D]
        # arrays (native pools) or {"q","scale"} dicts (quantized) —
        # stacked per leaf; the pow2 rung pads with idempotent
        # rewrites of the last page
        dt = self.cfg.kv_cache_dtype
        host = list(rows_np) + [rows_np[-1]] * (R - k)
        if kvq.is_quantized_dtype(dt):
            stacked = {
                "q": jnp.asarray(np.stack([h["q"] for h in host]),
                                 kvq.compute_dtype(dt)),
                "scale": jnp.asarray(
                    np.stack([h["scale"] for h in host]), jnp.float32),
            }
        else:
            stacked = jnp.asarray(np.stack(host),
                                  kvq.compute_dtype(dt))
        self.kv_cache = self._import_page_fn(
            self.kv_cache, jnp.asarray(pages), stacked)

    # -- KV memory hierarchy: host spill tier + fleet fetch (ISSUE 11) ----
    @engine_thread_only
    def _spill_page(self, key: bytes, page: int) -> None:
        """Spill sink wired into PrefixCache eviction: copy the
        about-to-be-reclaimed page's K/V rows device→host and park them
        in the host tier under the chain key. Runs synchronously inside
        the allocator's _pop_page on the ENGINE thread — the page is
        never handed to its new owner before the copy resolves, and the
        export program is pre-compiled by warmup() (zero hot XLA
        compiles across spill churn). The evicted page is refcount-0
        with every window that could write it already drained, so its
        device rows are stable."""
        rows = self._export_page_dev(page)
        self._start_host_copy([rows])
        self.host_tier.put(key, kvq.page_to_host(rows))

    @engine_thread_only
    def _revive_chain(self, chain_keys: list) -> int:
        """Promote the longest spilled run extending the resident
        prefix back into the pool: allocate pages, scatter the host
        rows in ONE warmed batched import call, and register them in
        the prefix cache (parked evictable — the caller's probe adopts
        them like any cached prefix). Returns pages revived; 0 under
        page pressure (the rows are put back and the cold prefill path
        proceeds)."""
        tier = self.host_tier
        resident = len(self.prefix_cache.probe(chain_keys))
        take: list = []
        while (resident + len(take) < len(chain_keys)
               and tier.contains(chain_keys[resident + len(take)])):
            take.append(chain_keys[resident + len(take)])
        if not take:
            return 0
        # remove from the tier FIRST: an interleaved spill during the
        # allocation below can never LRU-drop the rows mid-revive
        rows = []
        for k in take:
            r = tier.take(k)
            if r is None:  # raced away (defensive) — revive what's left
                break
            rows.append(r)
        take = take[: len(rows)]
        if not rows:
            return 0
        seq_id = next(self._seq_ids)
        try:
            self.allocator.allocate_extra(seq_id, len(rows))
        except OutOfPagesError:
            self.allocator.free(seq_id)
            for k, r in zip(take, rows):  # hand the rows back
                tier.put(k, r)
            return 0
        page_ids = self.allocator.pages(seq_id)
        self._import_pages_dev(page_ids, rows)
        self.prefix_cache.insert(take, page_ids)
        # park evictable: the admission that triggered the revive
        # re-probes and adopts under the normal refcount discipline
        self.allocator.free(seq_id)
        logger.debug("revived %d spilled pages", len(rows))
        return len(rows)

    def _purge_spilled(self, keys: list) -> None:
        """Strict tiering: a chain that just became resident through a
        fresh prefill insert must not also occupy the host budget (a
        stale copy can linger when an earlier chain key was budget-
        dropped, so no revive fired on the re-ask)."""
        if self.host_tier is not None:
            for k in keys:
                self.host_tier.discard(k)

    def kv_chain_digest(self) -> tuple:
        """Hex digest of the chain hashes this replica can serve KV for
        (resident prefix-cache entries + host-spilled pages) — exported
        on /state, polled into the gateway's fleet index, and consumed
        by the fleet fetch's local presence probe. Lock-free: an atomic
        read of the tuple the engine thread refreshes."""
        return self._kv_digest

    #: digest size FLOOR: a replica always advertises at least this
    #: many chain keys (the pre-long-context flat bound)
    KV_DIGEST_MAX = 4096

    #: full-length chains the geometry-aware digest bound guarantees
    #: room for (kv_digest_max below)
    KV_DIGEST_MIN_CHAINS = 8

    def kv_digest_max(self) -> int:
        """Geometry-aware digest bound: ``max(KV_DIGEST_MAX,
        KV_DIGEST_MIN_CHAINS * max_pages_per_seq)``. Chain keys are
        per-PAGE hashes, so a single 128k chain at 128-token pages is
        1024 keys — the flat 4096 bound silently truncated the
        advertisement to ~4 long chains, making every later chain
        invisible to the fleet KV index (unfetchable cross-replica)
        even though this replica held its pages. The gateway-side
        mirror is KVIndex.MAX_KEYS_PER_REPLICA (gateway/kvindex.py)."""
        return max(self.KV_DIGEST_MAX,
                   self.KV_DIGEST_MIN_CHAINS * self.cfg.max_pages_per_seq)

    @engine_thread_only
    def _refresh_kv_digest(self) -> None:
        """Engine-thread digest rebuild (throttled by _refresh_stats):
        the only thread that mutates _by_key and the host tier's key
        set, so iteration here is race-free."""
        if self.prefix_cache is None:
            return
        keys = list(self.prefix_cache._by_key.keys())
        if self.host_tier is not None:
            keys.extend(self.host_tier.keys())
        out: list[str] = []
        seen: set = set()
        bound = self.kv_digest_max()
        for k in keys:
            if k not in seen:
                seen.add(k)
                out.append(k.hex())
                if len(out) >= bound:
                    break
        self._kv_digest = tuple(out)

    def kv_export_pages(self, keys: list, timeout: float = 30.0) -> list:
        """Serve KV pages by chain hash for a sibling replica's fetch
        (the /kv/pages endpoint): resident pages are pinned and gathered
        device→host through the migration export program; spilled pages
        are served straight from the host tier. Returns [(key, np f32
        rows)] for every key this replica holds — missing keys are
        simply absent (the fetcher imports the leading contiguous run).
        Engine-thread execution via the migration control queue."""
        box: dict = {"evt": threading.Event()}
        self._mig_q.put(("fetch", keys, box))
        self._wake.set()
        if not box["evt"].wait(timeout):
            raise TimeoutError("kv page fetch timed out")
        if "error" in box:
            raise MigrationError(box["error"])
        return box["result"]

    @engine_thread_only
    def _do_fetch(self, keys: list) -> list:
        if self.prefix_cache is None:
            return []
        # the wire rule for quantized pools: pages travel at NATIVE
        # dtype + their scale blocks, bit-exactly (re-rounding through
        # f32 would silently change what the importer serves); native
        # pools keep the PR 8 f32 wire
        quant = kvq.is_quantized_dtype(self.cfg.kv_cache_dtype)

        def wire(rows):
            host = kvq.page_to_host(rows)
            return host if quant else np.asarray(host, np.float32)

        out: list = []
        resident: list = []
        for k in keys:
            page = self.prefix_cache._by_key.get(k)
            if page is not None:
                resident.append((k, page))
            elif self.host_tier is not None:
                rows = self.host_tier.get(k)  # peek — the rung stays
                if rows is not None:
                    out.append((k, rows if quant
                                else np.asarray(rows, np.float32)))
        if resident:
            # pin for the duration of the device→host copy — the same
            # export discipline as migration (nothing may free/evict/
            # CoW these pages mid-transfer)
            pin = self.allocator.begin_export([p for _, p in resident])
            try:
                exported = [(k, self._export_page_dev(p))
                            for k, p in resident]
                self._start_host_copy([e for _, e in exported])
                out.extend((k, wire(e)) for k, e in exported)
            finally:
                self.allocator.end_export(pin)
        if out:
            self.stats.kv_fetches_out += 1
            self.stats.kv_fetch_pages_out += len(out)
        return out

    def kv_import_pages(self, tokens: list[int], pages: list,
                        start: int = 0, timeout: float = 30.0) -> int:
        """Adopt KV pages fetched from a sibling replica: pages hold
        chain depths [start, start+len) of ``tokens``'s page chain and
        are registered as cached (non-live) pages — exactly the
        migration-import lifecycle, counted as fleet fetches instead.
        Raises MigrationError / TimeoutError like migrate_import."""
        box: dict = {"evt": threading.Event()}
        self._mig_q.put(("import", (tokens, pages, start, "fetch"), box))
        self._wake.set()
        if not box["evt"].wait(timeout):
            raise TimeoutError("kv page import timed out")
        if "error" in box:
            raise MigrationError(box["error"])
        return box["result"]

    @property
    def kv_page_bytes(self) -> int:
        """HBM bytes of one KV page (the /state bytes-pinned signal).
        Quantized pools count the packed element bytes PLUS the page's
        f32 scale block (one scale per token row × KV head per k/v)."""
        mc = self.model_cfg
        per_elt = kvq.bytes_per_kv_element(self.cfg.kv_cache_dtype)
        scale = (4 if kvq.is_quantized_dtype(self.cfg.kv_cache_dtype)
                 else 0)
        return int(mc.n_layers * 2 * self.cfg.page_size * mc.n_kv_heads
                   * (mc.head_dim * per_elt + scale))

    def mesh_axes(self) -> dict[str, int]:
        """Mesh axis name → size ({} off-mesh) — the /state topology
        export the picker's ICI term reads."""
        if self.mesh is None:
            return {}
        return {k: int(v) for k, v in self.mesh.shape.items()}

    @property
    def migratable(self) -> bool:
        """Whether this engine serves /migrate/export|import (needs the
        refcounted prefix-cache allocator). Layout-independent: on a
        mesh the page movers gather/scatter the head-sharded pool
        through the same full-page wire format (the gather assembles
        all head shards; the scatter re-shards on write) — /state
        exports this as the ``migration`` capability flag the gateway
        _Migrator respects."""
        return isinstance(self.allocator, RefcountedAllocator)

    @staticmethod
    def _start_host_copy(tree: Any) -> None:
        """Begin the device→host copy of every array leaf now
        (copy_to_host_async): the transfer overlaps the remaining
        on-device compute instead of serializing after it."""
        for leaf in jax.tree_util.tree_leaves(tree):
            copy = getattr(leaf, "copy_to_host_async", None)
            if copy is not None:
                copy()

    def _window_ladder(self) -> list[int]:
        """Window sizes the adaptive policy may dispatch."""
        K = self.cfg.decode_steps_per_tick
        if not self.cfg.adaptive_decode_window:
            return [K]
        kmin = min(self.cfg.min_decode_steps_per_tick, K)
        return [K] if kmin == K else [kmin, K]

    @engine_thread_only
    def _choose_window(self) -> int:
        """Adaptive decode window: shrink to the small program while
        latency matters (requests waiting for admission, or a stream so
        young its first decode burst hasn't landed), regrow to the full
        throughput window after two consecutive steady ticks."""
        K = self.cfg.decode_steps_per_tick
        ladder = self._window_ladder()
        if len(ladder) == 1:
            self.stats.decode_window = K
            return K
        kmin = ladder[0]
        # pressure is an INTERACTIVE signal (ISSUE 19): batch rides its
        # own queue (never in self._queue) and a freshly admitted batch
        # stream has no TTFT stake — only interactive arrivals and
        # young interactive streams shrink the window. This is the
        # first preemption rung: a waiting interactive request cuts the
        # dispatch window under every live batch slot immediately.
        pressured = self._queue.qsize() > 0 or any(
            s is not None and s.generated <= 1
            and s.req.priority != "batch" for s in self._slots
        )
        if pressured:
            self._steady_ticks = 0
            chosen = kmin
        else:
            self._steady_ticks += 1
            chosen = K if self._steady_ticks >= 2 else self._cur_window
        if chosen < self._cur_window:
            self.stats.window_shrinks += 1
        elif chosen > self._cur_window:
            self.stats.window_grows += 1
        self._cur_window = chosen
        self.stats.decode_window = chosen
        return chosen

    # -- public API -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="tpuserve-engine", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        """Stop the loop; any still-pending requests finish with
        "error" so waiting consumers never hang."""
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._abort_all("engine stopped")

    def submit(self, req: GenRequest) -> None:
        if len(req.prompt) + req.max_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt+max_tokens {len(req.prompt)}+{req.max_tokens} exceeds "
                f"max_seq_len {self.cfg.max_seq_len}"
            )
        if req.priority == "batch":
            # the offline tier never sheds: batch work QUEUES under
            # pressure (unbounded — the /v1/batches surface bounds
            # in-flight lines host-side) instead of 429ing, and admits
            # only into slots interactive doesn't want
            self._batch_q.put(req)
            self._wake.set()
            return
        if self._queue.qsize() >= self.cfg.max_queued_requests:
            raise EngineOverloadedError(
                f"queue full ({self.cfg.max_queued_requests} waiting)"
            )
        self._queue.put(req)
        self._wake.set()

    def warmup(self) -> None:
        """Compile every decode-window program in the adaptive ladder —
        plain (lean + full) AND every nonzero draft rung of the
        speculative ladder — and, with warm_prefill_buckets > 0, the
        attention backend's prefill surface (every (bucket, group)
        rung on xla-bucketed; the handful of token-budget chunk rungs
        on pallas-ragged — fewer programs, faster cold start) — before
        traffic arrives (the first burst then pays zero XLA compiles,
        and a mid-stream draft-rung transition never compiles a verify
        program on the hot path). Records warmup_ms + the compiled
        program count on EngineStats (/state: cold-start observables)."""
        t0 = time.monotonic()
        for P in self._warm_page_buckets():
            for k in self._window_ladder():
                for lean in (True, False):
                    state = self._build_device_state(bucket=P)
                    _, _, self.kv_cache, _ = self._decode_fn_for(
                        k, lean)(
                        self.params, self.lora_params, self.kv_cache,
                        state
                    )
                for d in self._spec_rungs:
                    if d == 0:
                        continue
                    state = self._build_device_state(bucket=P)
                    _, _, self.kv_cache, _ = self._decode_fn_for(
                        k, False, d)(
                        self.params, self.lora_params, self.kv_cache,
                        state
                    )
            # the incremental row-update scatters also run on the hot
            # path (admission / EOS / rung moves) and re-trace per
            # page-bucket state shape: compile them on a throwaway
            # state at THIS bucket so the first membership change at
            # any warmed bucket pays nothing. The throwaway stays a
            # LOCAL — warmup runs on the server thread while the
            # engine loop is already live, and publishing it through
            # self._device_state raced the loop's quiesce path (no
            # active slots → _device_state = None) into the middle of
            # this warm sequence (observed as warmup crashing on a
            # None state under slow compiles).
            state = self._build_device_state(bucket=P)
            state = self._row_update_fn_built()(
                state, np.int32(0), self._row_host_values(0, P))
            if self._spec_max:
                state = self._spec_update_fn_built()(
                    state, np.int32(0), np.int32(0))
            # the constrained-decoding bias-row scatter also runs on
            # the hot path (every FSM advance of a constrained slot)
            if self.cfg.constrained_decoding:
                V = self.model_cfg.vocab_size
                state = self._cn_update_fn_built()(
                    state, np.int32(0), np.zeros((V,), np.float32))
        if self._adapter_store is not None:
            # the hot-load row scatters run on the admission path: the
            # first non-resident adapter admission (or any later mix
            # change) must not pay an XLA compile
            self._adapter_store.warm()
        self.attn.warm()
        if self.cfg.warm_prefill_buckets > 0:
            # the sequence-sharded chunked-prefill ladder is engine-
            # owned (it preempts the backend for long suffixes), so the
            # backend warm above never covers it
            self._warm_sp_prefill_shapes()
        # migration page movers: a page export (device→host gather) or
        # an import at ANY page-count rung must never compile
        # mid-traffic — round-trip page 0 through the host exactly as a
        # real migration does (idempotent rewrites of page 0's own
        # content; nothing is serving yet)
        rows = kvq.page_to_host(self._export_page_dev(0))
        for r in self._import_rungs():
            self._import_pages_dev([0] * r, [rows] * r)
        # NOTE: warm passes discard program results wholesale, so the
        # MoE routing accumulators stay at zero here — the exported
        # stats count real traffic only (folds happen at the traffic
        # call sites, on the engine thread)
        self.stats.warmup_ms = round(1e3 * (time.monotonic() - t0), 3)
        self.stats.warm_programs = self.compile_tracker.program_count()

    def _warm_page_buckets(self) -> list[int]:
        """Page buckets warmup() compiles the decode ladder at:
        [current quiesced bucket] classically, or — with
        ``warm_decode_buckets`` = N — the pow2 rungs 1, 2, …, 2^(N-1)
        capped at max_pages_per_seq, so a first admission at ANY
        covered sequence length never compiles a decode program (or
        the matching row-update scatter) on the hot path."""
        n = self.cfg.warm_decode_buckets
        if n <= 0:
            return [self._decode_bucket_pages()]
        buckets: list[int] = []
        b = 1
        for _ in range(n):
            buckets.append(min(b, self.cfg.max_pages_per_seq))
            if b >= self.cfg.max_pages_per_seq:
                break
            b *= 2
        return sorted(set(buckets))

    def _warm_prefill_shapes(self, S: int) -> None:
        """Run the prefill program for every power-of-two group size at
        prompt bucket S with all-zero seq_lens: padded-row semantics
        drop every K/V scatter, so nothing is written — the call exists
        only to populate the jit cache for that shape."""
        V = self.model_cfg.vocab_size
        P = self.cfg.max_pages_per_seq
        G2 = 1
        while G2 <= self.cfg.max_batch_size:
            _, self.kv_cache, _ = self._prefill_fn(
                self.params, self.lora_params,
                jnp.zeros((G2, S), jnp.int32),
                jnp.zeros((G2,), jnp.int32),
                self.kv_cache,
                jnp.zeros((G2, P), jnp.int32),
                jnp.zeros((G2, 2), jnp.uint32),
                jnp.zeros((G2,), jnp.float32),
                jnp.ones((G2,), jnp.float32),
                jnp.zeros((G2,), jnp.int32),
                jnp.zeros((G2, V), jnp.float32),
                jnp.full((G2,), self._base_row, jnp.int32),
            )
            G2 *= 2

    def _warm_sp_prefill_shapes(self) -> None:
        """Compile the sequence-sharded chunked-prefill surface: the
        chunk program plus every tail rung at or below it, at each warm
        page bucket large enough to ever host an sp prefill (the gather
        window covers prompt+max_tokens >= sp_prefill_min_tokens, so
        smaller buckets can never see the path). All-zero seq_lens:
        padded-row semantics drop every K/V scatter and the last-index
        gather clamps, so the calls only populate the jit cache. The
        surface stays log-sized — (tail rungs <= chunk) x (eligible
        pow2 buckets) — which is what keeps zero-hot-compile tripwires
        green at 32k-128k geometry without warming a 128k monolithic
        rung."""
        if self._prefill_sp_suffix_fn is None:
            return
        cfg = self.cfg
        sp = self._sp
        chunk = max(cfg.sp_chunk_tokens, sp)
        chunk = -(-chunk // sp) * sp
        rungs = {chunk}
        for t in range(1, chunk + 1):
            rungs.add(self._prefill_bucket(t, multiple_of=sp))
        min_need = -(-cfg.sp_prefill_min_tokens // cfg.page_size)
        V = self.model_cfg.vocab_size
        for P in self._warm_page_buckets():
            if P < min_need:
                continue
            for S in sorted(rungs):
                _, self.kv_cache, _ = self._prefill_sp_suffix_fn(
                    self.params, self.lora_params,
                    jnp.zeros((1, S), jnp.int32),
                    jnp.zeros((1,), jnp.int32),
                    jnp.zeros((1,), jnp.int32),
                    self.kv_cache,
                    jnp.zeros((1, P), jnp.int32),
                    jnp.zeros((1, 2), jnp.uint32),
                    jnp.zeros((1,), jnp.float32),
                    jnp.ones((1,), jnp.float32),
                    jnp.zeros((1,), jnp.int32),
                    jnp.zeros((1, V), jnp.float32),
                    jnp.full((1,), self._base_row, jnp.int32),
                )

    # -- prefill/decode disaggregation: KV page migration (ISSUE 8) --------
    def migrate_export(self, req: GenRequest,
                       timeout: float = 30.0) -> dict:
        """Cut a live session and serialize its page chain for transfer
        to another replica: full KV pages (device→host), the chained
        content hashes identifying them, and the slot's sampling /
        penalty / key state. Callable from any thread — the cut itself
        runs on the engine thread at the next tick, after the in-flight
        decode window settles, so the wire state is exactly a token
        boundary. Returns {"blob": <json-able dict>, "data": [np page
        arrays]}. Raises MigrationError (session untouched on failure)
        or TimeoutError."""
        box: dict = {"evt": threading.Event()}
        self._mig_q.put(("export", req, box))
        self._wake.set()
        if not box["evt"].wait(timeout):
            raise TimeoutError("migration export timed out")
        if "error" in box:
            raise MigrationError(box["error"])
        return box["result"]

    def migrate_import(self, tokens: list[int], pages: list[np.ndarray],
                       timeout: float = 30.0) -> int:
        """Adopt another replica's exported page chain: scatter the
        host-side pages into this pool and register them in the prefix
        cache under their chain hashes — the imported pages then live
        under the NORMAL refcount/CoW/eviction discipline (parked
        evictable until the continuation request adopts them; pool
        pressure can reclaim them like any cached prefix). Returns the
        number of pages imported. Raises MigrationError / TimeoutError;
        OutOfPagesError surfaces as MigrationError("…pages…") so the
        caller can requeue like admission pressure."""
        box: dict = {"evt": threading.Event()}
        self._mig_q.put(("import", (tokens, pages, 0, "migration"), box))
        self._wake.set()
        if not box["evt"].wait(timeout):
            raise TimeoutError("migration import timed out")
        if "error" in box:
            raise MigrationError(box["error"])
        return box["result"]

    @engine_thread_only
    def _process_migrations(self) -> None:
        """Run queued export/import jobs on the engine thread (the only
        thread allowed to touch kv_cache's donation chain and the slot
        table). Errors are reported to the waiting caller, never raised
        into the engine loop."""
        while True:
            try:
                kind, payload, box = self._mig_q.get_nowait()
            except queue.Empty:
                return
            try:
                if kind == "export":
                    box["result"] = self._do_export(payload)
                elif kind == "fetch":
                    box["result"] = self._do_fetch(payload)
                else:
                    box["result"] = self._do_import(*payload)
            except Exception as e:  # noqa: BLE001 — relayed to caller
                box["error"] = f"{type(e).__name__}: {e}"
            finally:
                box["evt"].set()

    @engine_thread_only
    def _do_export(self, req: GenRequest) -> dict:
        """Engine-thread half of migrate_export. Wire rule: only COMPLETE
        pages whose every row is written KV travel — k = (m-1) // page
        pages for m total tokens (the last token's K/V is the pending
        decode input and not yet written). The ≤ one-page token tail is
        recomputed by the importer's offset resume, so the imported
        pages are always safe to share under the chain-hash contract
        ("this page holds ALL of positions [i·ps, (i+1)·ps)")."""
        if not isinstance(self.allocator, RefcountedAllocator):
            raise MigrationError(
                "migration requires the prefix cache "
                "(refcounted page allocator)")
        if req.emit_lp is not None:
            raise MigrationError(
                "logprobs sessions are not migratable")
        if req.constraint is not None:
            # the wire blob carries no FSM cursor; a resumed constrained
            # stream would decode unconstrained — refuse instead
            raise MigrationError(
                "grammar-constrained sessions are not migratable")
        idx = next((i for i, s in enumerate(self._slots)
                    if s is not None and s.req is req), None)
        if idx is None:
            raise MigrationError(
                "request is not active (finished, cancelled, or not "
                "yet admitted)")
        # settle the in-flight window: it may still write this
        # sequence's pages, and its tokens must land before the cut so
        # the exported state is a clean token boundary
        self._drain_inflight()
        self._apply_frees()
        s = self._slots[idx]
        if s is None or s.req is not req:
            raise MigrationError("request finished during the export cut")
        if s.generated < 1:
            raise MigrationError("prefill not finished (no token yet)")
        # the cut: finish the slot with "migrated" — pages free under
        # the normal refcount discipline (cache-registered prompt pages
        # park evictable; the export pin already released)
        if req.trace is not None:
            req.trace.engine_finish("migrated")
        out = self._export_cut(idx)
        req.emit(-1, "migrated")
        self.stats.migrations_out += 1
        self.stats.migration_pages_out += len(out["data"])
        logger.info("exported seq %d: %d tokens, %d pages", req.id,
                    len(out["blob"]["tokens"]), len(out["data"]))
        return out

    # -- usage metering (ISSUE 20) ---------------------------------------
    #
    # One MeterRecord per request LIFETIME, emitted on the engine thread
    # strictly before the terminal emit (FIFO + the consumer's queue make
    # it visible when the finish item is dequeued). Migration/park cuts
    # never emit — the accumulated meter rides the export blob and the
    # resumed slot's terminal record covers the whole spliced stream.
    # EngineStats.meter_* counters are incremented ONLY in _meter_emit,
    # so a ledger built from the records reconciles against /state
    # token-for-token by construction.

    _METER_SUM_KEYS = ("prefill_real", "prefill_padded", "prefix_reused",
                       "decode_tokens", "spec_drafted", "spec_accepted",
                       "segments")

    @engine_thread_only
    def _meter_fold(self, s: "_Slot") -> dict:
        """Fold slot accumulators + any imported carry into one meter
        dict (no finish/schema — the terminal record adds those; the
        same dict rides an export blob as the continuation carry).
        HBM residency integrates the current dwell segment at the
        slot's PRESENT page footprint: pages × kv_page_bytes × dwell_s."""
        req = s.req
        now = time.monotonic()
        bytes_now = s.m_res_bytes
        try:
            bytes_now = (len(self.allocator.pages(req.id))
                         * self.kv_page_bytes)
        except Exception:
            pass
        hbm = s.m_hbm_pbs
        if s.m_res_t0 > 0.0:
            hbm += (now - s.m_res_t0) * bytes_now
        rec = {
            "prefill_real": s.m_prefill_real,
            "prefill_padded": s.m_prefill_padded,
            "prefix_reused": s.m_prefix_reused,
            "decode_tokens": s.generated,
            "spec_drafted": s.m_spec_drafted,
            "spec_accepted": s.m_spec_accepted,
            "hbm_page_byte_s": round(hbm, 6),
            "host_page_byte_s": 0.0,
            "segments": 1,
            "tenant": req.tenant,
            "priority": req.priority,
        }
        c = s.m_carry
        if c:
            for key in self._METER_SUM_KEYS:
                rec[key] += int(c.get(key, 0))
            rec["hbm_page_byte_s"] = round(
                rec["hbm_page_byte_s"]
                + float(c.get("hbm_page_byte_s", 0.0)), 6)
            rec["host_page_byte_s"] = round(
                float(c.get("host_page_byte_s", 0.0)), 6)
        return rec

    @engine_thread_only
    def _meter_emit(self, rec: dict, sink) -> None:
        """THE single point where meter counters move and a record
        reaches its sink — every emission path funnels here."""
        st = self.stats
        st.meter_records += 1
        st.meter_prefill_tokens += rec["prefill_real"]
        st.meter_prefill_padded_tokens += rec["prefill_padded"]
        st.meter_prefix_reused_tokens += rec["prefix_reused"]
        st.meter_decode_tokens += rec["decode_tokens"]
        st.meter_spec_drafted += rec["spec_drafted"]
        st.meter_spec_accepted += rec["spec_accepted"]
        st.meter_hbm_page_byte_s = round(
            st.meter_hbm_page_byte_s + rec["hbm_page_byte_s"], 6)
        st.meter_host_page_byte_s = round(
            st.meter_host_page_byte_s + rec["host_page_byte_s"], 6)
        if sink is not None:
            try:
                sink(rec)
            except Exception:
                logger.exception("meter sink failed")

    @engine_thread_only
    def _meter_finish(self, s: "_Slot", finish: str) -> None:
        """Terminal record for a live slot (EOS/length/cancel/error)."""
        rec = self._meter_fold(s)
        rec["schema"] = 1
        rec["finish"] = finish
        self._meter_emit(rec, s.req.meter_sink)

    @engine_thread_only
    def _meter_zero(self, req: GenRequest, finish: str) -> None:
        """Terminal record for a request that never held a slot
        (cancelled/errored in a queue, unknown adapter). Usually all
        zeros; a queued CONTINUATION still carries its segments' meter."""
        c = (req.import_state or {}).get("meter_carry") or {}
        rec = {
            "schema": 1,
            "finish": finish,
            "prefill_real": int(c.get("prefill_real", 0)),
            "prefill_padded": int(c.get("prefill_padded", 0)),
            "prefix_reused": int(c.get("prefix_reused", 0)),
            "decode_tokens": int(c.get("decode_tokens", 0)),
            "spec_drafted": int(c.get("spec_drafted", 0)),
            "spec_accepted": int(c.get("spec_accepted", 0)),
            "hbm_page_byte_s": round(float(c.get("hbm_page_byte_s", 0.0)), 6),
            "host_page_byte_s": round(
                float(c.get("host_page_byte_s", 0.0)), 6),
            "segments": int(c.get("segments", 0)),
            "tenant": req.tenant,
            "priority": req.priority,
        }
        self._meter_emit(rec, req.meter_sink)

    @engine_thread_only
    def _meter_parked(self, park: dict, finish: str) -> None:
        """Terminal record for a host-parked session that will never
        resume (cancelled while parked / engine abort): the exported
        carry plus the host-spill residency accrued while parked."""
        blob = park["blob"]
        c = dict(blob.get("meter") or {})
        now = time.monotonic()
        host = (float(c.get("host_page_byte_s", 0.0))
                + (now - park.get("parked_at", now))
                * park.get("park_bytes", 0))
        rec = {
            "schema": 1,
            "finish": finish,
            "prefill_real": int(c.get("prefill_real", 0)),
            "prefill_padded": int(c.get("prefill_padded", 0)),
            "prefix_reused": int(c.get("prefix_reused", 0)),
            "decode_tokens": int(c.get("decode_tokens", 0)),
            "spec_drafted": int(c.get("spec_drafted", 0)),
            "spec_accepted": int(c.get("spec_accepted", 0)),
            "hbm_page_byte_s": round(float(c.get("hbm_page_byte_s", 0.0)), 6),
            "host_page_byte_s": round(host, 6),
            "segments": int(c.get("segments", 0)),
            "tenant": str(blob.get("tenant", "")),
            "priority": str(blob.get("priority", "batch")),
        }
        self._meter_emit(rec, park.get("meter_sink"))

    @engine_thread_only
    def _export_cut(self, idx: int) -> dict:
        """Serialize slot ``idx``'s session at the (already settled)
        token boundary and free the slot — the shared engine-thread cut
        behind both the migration export (wire transfer to a sibling)
        and the batch-preemption park (host-side stash on THIS
        replica). Wire rule unchanged: only complete written pages
        travel; the ≤ one-page tail is recomputed by the resume's
        offset prefill. The CALLER owns emit/trace/counter semantics —
        migration finishes the stream, a park keeps the consumer
        attached. Returns {"blob": <json-able>, "data": [np pages]}."""
        s = self._slots[idx]
        assert s is not None
        req = s.req
        ps = self.cfg.page_size
        tokens = list(req.prompt) + list(s.gen_tokens)
        m = len(tokens)
        k = (m - 1) // ps
        pages = self.allocator.pages(req.id)[:k]
        # pin the chain for the duration of the device→host transfer:
        # nothing may free/evict/CoW these pages while the copy (or the
        # wire transfer the caller performs next) is in flight
        pin = self.allocator.begin_export(pages)
        try:
            outs = [self._export_page_dev(p) for p in pages]
            self._start_host_copy(outs)  # per-page copies overlap
            data = [kvq.page_to_host(o) for o in outs]
        finally:
            self.allocator.end_export(pin)
        ims = req.import_state or {}
        sp = req.sampling
        blob = {
            "tokens": tokens,
            "page_size": ps,
            "chain": [h.hex() for h in
                      page_chain_hashes(tokens, ps)[:k]],
            "kv_dtype": self.cfg.kv_cache_dtype,
            "orig_prompt_len": ims.get("orig_prompt_len",
                                       len(req.prompt)),
            "generated": ims.get("generated", 0) + s.generated,
            "max_tokens": req.max_tokens - s.generated,
            "key_seed": s.key_seed,
            "adapter": req.adapter,
            "tenant": req.tenant,
            "priority": req.priority,
            "stop_token_ids": list(req.stop_token_ids),
            "sampling": {
                "temperature": sp.temperature, "top_p": sp.top_p,
                "top_k": sp.top_k, "seed": sp.seed,
                "frequency_penalty": sp.frequency_penalty,
                "presence_penalty": sp.presence_penalty,
                "logit_bias": [[t, b] for t, b in sp.logit_bias],
            },
            # usage metering (ISSUE 20): the cut emits NO MeterRecord —
            # this carry (slot accumulators + upstream segments, HBM
            # residency integrated to the cut) rides to the resume so
            # the spliced stream meters exactly once at its real end
            "meter": self._meter_fold(s),
        }
        self._pending_frees.append(req.id)
        self._release_adapter_row(s.adapter_row)
        self._slots[idx] = None
        self._dirty_rows.add(idx)
        self._wake.set()
        return {"blob": blob, "data": data}

    @engine_thread_only
    def _park_batch_slot(self, idx: int) -> bool:
        """Preemption rung (ii): cut one live BATCH slot off the device
        through the migration export machinery and stash it host-side
        (pages + blob + the still-attached consumer callback); the
        batch tier resumes it byte-identically once interactive stops
        wanting the slot. Returns True when the slot is free afterward
        (parked, or found finished by the settle), False when the
        session is not parkable — no token yet, logprobs/constrained
        (the blob carries neither), or no refcounted allocator — and
        the caller should try another victim."""
        s = self._slots[idx]
        if s is None:
            return True
        req = s.req
        if (not isinstance(self.allocator, RefcountedAllocator)
                or req.emit_lp is not None
                or req.constraint is not None
                or s.generated < 1):
            return False
        # settle the in-flight window so the cut is a token boundary
        self._drain_inflight()
        self._apply_frees()
        s = self._slots[idx]
        if s is None or s.req is not req:
            return True  # finished during the settle — slot is free
        if req.trace is not None:
            req.trace.engine_finish("parked")
        entry = self._export_cut(idx)
        entry["emit"] = req.emit
        entry["cancelled"] = req.cancelled
        # metering: the parked dwell accrues HOST page·byte·seconds
        # (pages live in host RAM, not HBM) — folded into the carry at
        # resume, or into the terminal record if it never resumes
        entry["meter_sink"] = req.meter_sink
        entry["parked_at"] = time.monotonic()
        entry["park_bytes"] = len(entry["data"]) * self.kv_page_bytes
        self._parked_batch.append(entry)
        self.stats.batch_preemptions += 1
        logger.info("parked batch seq %d (%d pages) for interactive "
                    "admission", req.id, len(entry["data"]))
        return True

    @engine_thread_only
    def _preempt_batch(self) -> bool:
        """Park live batch slots so WAITING interactive requests can
        admit — called by _admit when every slot is taken. Parks at
        most as many sessions as requests are waiting. Returns True
        when at least one slot freed."""
        want = self._queue.qsize()
        if want <= 0:
            return False
        freed = 0
        for i, s in enumerate(self._slots):
            if freed >= want:
                break
            if (s is not None and s.req.priority == "batch"
                    and self._park_batch_slot(i)):
                freed += 1
        return freed > 0

    @engine_thread_only
    def _do_import(self, tokens: list[int],
                   pages_data: list[np.ndarray], start: int = 0,
                   source: str = "migration") -> int:
        """Engine-thread half of migrate_import / kv_import_pages:
        allocate pages, scatter the imported rows, register the chain in
        the prefix cache, then release — the pages park evictable
        (revivable) until an admission probe adopts them. No new page
        lifecycle: from here on they are ordinary cached prefix pages.
        ``start`` offsets the chain depth the pages land at (a fleet
        fetch extends an already-resident prefix); ``source`` picks the
        counters (migration vs cross-replica fetch)."""
        if self.prefix_cache is None:
            raise MigrationError(
                "migration import requires the prefix cache")
        ps = self.cfg.page_size
        k = len(pages_data)
        if k == 0:
            return 0
        if start < 0 or start + k > (len(tokens) - 1) // ps:
            raise MigrationError(
                f"pages [{start}, {start + k}) exceed the written-KV "
                f"coverage of {len(tokens)} tokens")
        mc = self.model_cfg
        want = (mc.n_layers, 2, ps, mc.n_kv_heads, mc.head_dim)
        for rows in pages_data:
            if not kvq.page_matches_dtype(rows,
                                          self.cfg.kv_cache_dtype):
                raise MigrationError(
                    "page dtype does not match this engine's "
                    f"kv_cache_dtype={self.cfg.kv_cache_dtype!r} "
                    "(quantized pages only scatter into a matching "
                    "quantized pool)")
            if not kvq.page_shape_ok(rows, want):
                raise MigrationError(
                    f"page shape != expected {want} "
                    "(mismatched model or page size)")
        keys = page_chain_hashes(tokens, ps)[start:start + k]
        seq_id = next(self._seq_ids)
        self.allocator.allocate_extra(seq_id, k)  # OutOfPages → caller
        page_ids = self.allocator.pages(seq_id)
        self._import_pages_dev(page_ids, pages_data)
        self.prefix_cache.insert(keys, page_ids)
        self._purge_spilled(keys)
        # release: registered pages park evictable (adopted by the
        # continuation's probe); pages whose chain key was ALREADY
        # cached locally were skipped by insert and return to the free
        # stack immediately
        self.allocator.free(seq_id)
        if source == "fetch":
            self.stats.kv_fetches_in += 1
            self.stats.kv_fetch_pages_in += k
        elif source == "parked":
            # batch park/resume is intra-replica: it rides the
            # batch_preemptions / batch_resumed pair, not the
            # cross-replica migration counters
            pass
        else:
            self.stats.migrations_in += 1
            self.stats.migration_pages_in += k
        logger.info("imported %d pages for a %d-token chain (%s)", k,
                    len(tokens), source)
        return k

    # -- engine loop ------------------------------------------------------
    def _run(self) -> None:
        logger.info("engine loop started (batch=%d, pages=%d×%d)",
                    self.cfg.max_batch_size, self.cfg.num_pages,
                    self.cfg.page_size)
        while not self._stop.is_set():
            try:
                self._reap_cancelled()
                self._process_migrations()
                admitted = self._admit()
                # the offline tier soaks whatever interactive left idle
                admitted |= self._admit_batch_tier()
                worked = self._decode_tick()
                if self._stop.is_set():
                    self._drain_inflight()
                    self._apply_frees()
            except Exception as e:  # never die silently: fail loudly and
                # error out every in-flight request instead of hanging them
                logger.exception("engine tick failed")
                self.healthy = False
                self.last_error = f"{type(e).__name__}: {e}"
                self._abort_all(str(e))
                return
            if not admitted and not worked:
                self._wake.wait(timeout=0.05)
                self._wake.clear()
        # deliver any tokens still in flight before exiting
        try:
            self._drain_inflight()
            self._apply_frees()
        except Exception:
            pass
        logger.info("engine loop stopped")

    @engine_thread_only
    def _abort_all(self, reason: str) -> None:
        if self._inflight is not None:
            # the in-flight window's captured frees must not leak pages
            self._pending_frees.extend(self._inflight.frees)
            self._inflight = None
        self._apply_frees()
        self._device_state = None
        self._need_rebuild = True
        self._dirty_rows.clear()
        self._spec_dirty.clear()
        for i, s in enumerate(self._slots):
            if s is not None:
                self._meter_finish(s, "error")
                s.req.emit(-1, "error")
                self.allocator.free(s.req.id)
                self._release_adapter_row(s.adapter_row)
                self._slots[i] = None
        try:
            while True:
                req = self._queue.get_nowait()
                self._meter_zero(req, "error")
                req.emit(-1, "error")
        except queue.Empty:
            pass
        # the batch tier's queue and parked sessions have waiting
        # consumers too (never-shed ≠ never-finished on engine death)
        try:
            while True:
                req = self._batch_q.get_nowait()
                self._meter_zero(req, "error")
                req.emit(-1, "error")
        except queue.Empty:
            pass
        for park in self._parked_batch:
            self._meter_parked(park, "error")
            park["emit"](-1, "error")
        self._parked_batch.clear()
        # waiting migration callers must not hang until their timeout
        try:
            while True:
                _kind, _payload, box = self._mig_q.get_nowait()
                box["error"] = f"engine aborted: {reason}"
                box["evt"].set()
        except queue.Empty:
            pass

    @engine_thread_only
    def _reap_cancelled(self) -> None:
        for i, s in enumerate(self._slots):
            if s is not None and s.req.cancelled.is_set():
                if s.req.trace is not None:
                    s.req.trace.engine_finish("cancel")
                # a cancelled stream still has a waiting consumer (the
                # batch runner's _collect, a non-streaming handler):
                # reaping the slot without a terminal event would hang
                # it forever — a /v1/batches cancel must finalize
                self._meter_finish(s, "cancelled")
                s.req.emit(-1, "cancelled")
                self._pending_frees.append(s.req.id)
                self._release_adapter_row(s.adapter_row)
                self._slots[i] = None
                self._dirty_rows.add(i)

    def _free_slot_index(self) -> int | None:
        for i, s in enumerate(self._slots):
            if s is None and i not in self._reserved_slots:
                return i
        return None

    def _free_slot_count(self) -> int:
        return sum(1 for i, s in enumerate(self._slots)
                   if s is None and i not in self._reserved_slots)

    @engine_thread_only
    def _admit(self) -> bool:
        """Admit queued requests: prefill + first token.

        Simple prompts (plain full prefill — no prefix-cache hit, not
        chunked, not sequence-parallel) that are queued together are
        prefilled in ONE batched [G, S] device call instead of G serial
        [1, S] calls: a batch-B burst's first tokens arrive after one
        large MXU-friendly pass rather than a B-step prefill ladder
        (vLLM-style batched admission, TPU-first shape discipline —
        padded rows carry seq_len 0, whose K/V scatters drop). Everything
        else takes the per-request path below."""
        admitted = False
        while True:
            free = self._free_slot_count()
            if free == 0:
                # interactive arrivals under a full batch reclaim slots
                # from the offline class (ISSUE 19): rung (i) — the
                # shrunk dispatch window — already bounded the wait;
                # rung (ii) parks batch sessions host-side
                if not self._preempt_batch():
                    break
                free = self._free_slot_count()
                if free == 0:
                    break
            pending: list[GenRequest] = []
            try:
                while len(pending) < free:
                    pending.append(self._queue.get_nowait())
            except queue.Empty:
                pass
            if not pending:
                break
            if (self.cfg.admission_coalesce_ms > 0
                    and len(pending) < free
                    and self._inflight is None
                    and all(s is None for s in self._slots)):
                # completely idle + partial burst: a batch of concurrent
                # arrivals spans a few ms of event-loop scheduling —
                # wait once so the whole burst prefills as ONE batched
                # call instead of a 1+(B-1) split. Under the first-token
                # fast path a LONE arrival does not ride the full timer:
                # it probes 1ms for burst evidence (a second queued
                # request) and otherwise goes straight to prefill —
                # single-request TTFT stops paying for burst insurance,
                # while real bursts (which surface a second submit
                # within the probe) still coalesce fully.
                wait_ms = self.cfg.admission_coalesce_ms
                if self.cfg.first_token_fast_path and len(pending) == 1:
                    probe = min(1.0, wait_ms)
                    time.sleep(probe / 1e3)
                    try:
                        while len(pending) < free:
                            pending.append(self._queue.get_nowait())
                    except queue.Empty:
                        pass
                    wait_ms = 0.0 if len(pending) == 1 else \
                        max(0.0, wait_ms - probe)
                if wait_ms > 0 and len(pending) < free:
                    time.sleep(wait_ms / 1e3)
                    try:
                        while len(pending) < free:
                            pending.append(self._queue.get_nowait())
                    except queue.Empty:
                        pass
            # fairness guard (ISSUE 7): per-tenant slot cap + deficit
            # ordering over the popped window. Deferred requests must
            # not occlude admissible tenants still queued behind them,
            # so when the cap left slots unused the scan extends over
            # the rest of the queue (bounded by max_queued_requests).
            admit, fair_requeue, capped = self._fair_admission(
                pending, free)
            if fair_requeue and len(admit) < free:
                more: list[GenRequest] = []
                try:
                    while True:
                        more.append(self._queue.get_nowait())
                except queue.Empty:
                    pass
                if more:
                    admit, fair_requeue, capped = self._fair_admission(
                        pending + more, free)
            self.stats.tenant_deferrals += capped
            pending = admit
            fair_stop = bool(fair_requeue)
            if not pending:
                # everything at cap: back to the queue head (arrival
                # order kept) until a tenant frees a slot
                self._requeue_front_many(fair_requeue)
                break
            # one coalesced-admission burst id per pass — lifecycle
            # traces carry it so a trace/flight reader can see which
            # requests shared a batched prefill
            self._cur_burst = (next(self._burst_seq), len(pending))
            # Classify once (prompt hashes computed here are reused all
            # the way to the post-prefill cache insert), then admit in
            # STRICT arrival order: contiguous runs of ≥2 simple requests
            # go through the batched prefill, everything else through the
            # per-request path — so pages are always allocated in arrival
            # order and a requeued head-of-line request can never be
            # starved by later simple arrivals grabbing its pages.
            items: list[tuple[GenRequest, bool, list]] = []
            seen_chain_heads: set = set()
            for req in pending:
                if req.cancelled.is_set():
                    # consumed without a slot — still meters (zeros)
                    self._meter_zero(req, "cancelled")
                    continue
                ok, chain = self._classify(req)
                if ok and chain:
                    head = chain[0]
                    if head in seen_chain_heads:
                        # a batch-mate shares its first prompt page: the
                        # batched path would prefill the shared prefix
                        # redundantly with its own page copies — route it
                        # through the per-request path, which adopts the
                        # pages the batch inserts in this same pass
                        ok = False
                    else:
                        seen_chain_heads.add(head)
                items.append((req, ok, chain))
            stop = False
            unhandled: list[GenRequest] = []
            i = 0
            while i < len(items):
                req, simple, chain = items[i]
                if simple:
                    j = i
                    while j < len(items) and items[j][1]:
                        j += 1
                    if j - i >= 2:
                        run = items[i:j]
                        done, leftover = self._admit_batch(
                            [it[0] for it in run],
                            {id(it[0]): it[2] for it in run})
                        admitted |= done > 0
                        if leftover is not None:  # page pressure
                            unhandled.extend(leftover)
                            unhandled.extend(it[0] for it in items[j:])
                            stop = True
                            break
                        i = j
                        continue
                r = self._admit_one(req, chain)
                if r == "admitted":
                    admitted = True
                elif r in ("stop", "stop_consumed"):
                    if r == "stop":
                        unhandled.append(req)
                    unhandled.extend(it[0] for it in items[i + 1:])
                    stop = True
                    break
                i += 1
            if unhandled or fair_requeue:
                # single requeue: page-pressure leftovers first (they
                # were at the admission head), then fairness deferrals
                self._requeue_front_many(unhandled + fair_requeue)
            if stop or fair_stop:
                # a fairness deferral must end the pass — looping would
                # re-pop the deferred head and spin until a slot frees
                break
        return admitted

    @engine_thread_only
    def _admit_interactive(self) -> bool:
        """Chunk-boundary admission (long-context decode liveness):
        called by ``sp_chunked_prefill`` between chunk steps. Pops the
        queue, admits SHORT requests — below sp_prefill_min_tokens,
        so they can never re-enter the sp chunk loop — into free slots
        through the normal per-request path, and requeues everything
        else in arrival order. An interactive request that arrives
        behind a 128k prefill gets its first token at the next chunk
        boundary (its own short prefill) and keeps streaming through
        the boundary decode ticks, instead of waiting out the whole
        long prefill. The fairness guard runs over the short subset,
        so tenant caps hold at boundaries too. Reentrancy-latched: a
        short admission's own chunked (non-sp) prefill must not admit
        again from its boundaries."""
        if self._in_chunk_admit:
            return False
        free = self._free_slot_count()
        if free == 0:
            return False
        backlog: list[GenRequest] = []
        try:
            while True:
                backlog.append(self._queue.get_nowait())
        except queue.Empty:
            pass
        if not backlog:
            return False
        shorts = [r for r in backlog
                  if len(r.prompt) < self.cfg.sp_prefill_min_tokens]
        admitted = False
        handled: set[int] = set()
        self._in_chunk_admit = True
        try:
            admit, _fair_rq, capped = self._fair_admission(shorts, free)
            self.stats.tenant_deferrals += capped
            for req in admit:
                if req.cancelled.is_set():
                    self._meter_zero(req, "cancelled")
                    handled.add(id(req))
                    continue
                _ok, chain = self._classify(req)
                r = self._admit_one(req, chain)
                if r == "stop":
                    break  # shutdown: leave it (and the rest) queued
                handled.add(id(req))
                if r == "admitted":
                    admitted = True
                    self.stats.sp_interactive_admits += 1
        finally:
            self._in_chunk_admit = False
        self._requeue_front_many(
            [r for r in backlog if id(r) not in handled])
        return admitted

    def _batch_ceiling(self) -> int:
        """Most decode slots the batch class may hold at once."""
        return max(1, int(self.cfg.batch_slot_frac
                          * self.cfg.max_batch_size))

    def _batch_active(self) -> int:
        return sum(1 for s in self._slots
                   if s is not None and s.req.priority == "batch")

    @engine_thread_only
    def _admit_batch_tier(self) -> bool:
        """Admit offline work into slots interactive doesn't want: runs
        AFTER the interactive admission pass, only while the
        interactive queue is empty, and never past the batch_slot_frac
        ceiling — the priority generalization of the deficit-weighted
        tenant scan (which still orders WITHIN the class). Parked
        (preempted) sessions resume first, oldest first: their pages
        re-import through the migration scatter path, the continuation
        admission adopts them from the prefix cache, and the resumed
        stream is byte-identical to an uninterrupted run
        (tests/test_batch_tier.py's f32 rig)."""
        admitted = False
        while True:
            if self._queue.qsize() > 0:
                break  # interactive wants the slots — yield
            room = min(self._free_slot_count(),
                       self._batch_ceiling() - self._batch_active())
            if room <= 0:
                break
            if self._parked_batch:
                park = self._parked_batch[0]
                if park["cancelled"].is_set():
                    # dropping a parked session is a cancel FINISH, not
                    # a silent vanish — its _collect is still waiting
                    self._meter_parked(park, "cancelled")
                    park["emit"](-1, "cancelled")
                    self._parked_batch.pop(0)
                    continue
                try:
                    self._do_import(
                        [int(t) for t in park["blob"]["tokens"]],
                        park["data"], 0, "parked")
                except (MigrationError, OutOfPagesError):
                    break  # pool pressure: retry at a later pass
                # close the parked dwell: host-spill residency accrued
                # while off-device joins the carry the resume inherits
                carry = park["blob"].get("meter")
                if carry is not None:
                    now = time.monotonic()
                    carry["host_page_byte_s"] = round(
                        float(carry.get("host_page_byte_s", 0.0))
                        + (now - park.get("parked_at", now))
                        * park.get("park_bytes", 0), 6)
                    # a failed admission re-parks this entry: re-anchor
                    # so the next fold never double-charges this dwell
                    park["parked_at"] = now
                req = continuation_request(park["blob"],
                                           emit=park["emit"])
                req.cancelled = park["cancelled"]
                req.meter_sink = park.get("meter_sink")
                self._parked_batch.pop(0)
                _ok, chain = self._classify(req)
                r = self._admit_one(req, chain)
                if r == "admitted":
                    admitted = True
                    self.stats.batch_resumed += 1
                elif r == "stop":
                    # page pressure mid-admission: the imported pages
                    # stay cached (evictable) — re-park, retry later
                    self._parked_batch.insert(0, park)
                    break
                elif r == "stop_consumed":
                    break
                continue
            pending: list[GenRequest] = []
            try:
                while len(pending) < room:
                    pending.append(self._batch_q.get_nowait())
            except queue.Empty:
                pass
            if not pending:
                break
            admit, requeue, capped = self._fair_admission(pending, room)
            self.stats.tenant_deferrals += capped
            stop = False
            unhandled: list[GenRequest] = []
            for j, req in enumerate(admit):
                if req.cancelled.is_set():
                    # popped from _batch_q with a consumer still
                    # draining its queue — finalize, don't drop
                    self._meter_zero(req, "cancelled")
                    req.emit(-1, "cancelled")
                    continue
                _ok, chain = self._classify(req)
                r = self._admit_one(req, chain)
                if r == "admitted":
                    admitted = True
                elif r in ("stop", "stop_consumed"):
                    if r == "stop":
                        unhandled.append(req)
                    unhandled.extend(admit[j + 1:])
                    stop = True
                    break
            if unhandled or requeue:
                self._requeue_batch_front(unhandled + requeue)
            if stop or requeue:
                break
        return admitted

    def _requeue_batch_front(self, reqs: list[GenRequest]) -> None:
        items = list(reqs)
        if not items:
            return
        try:
            while True:
                items.append(self._batch_q.get_nowait())
        except queue.Empty:
            pass
        for it in items:
            self._batch_q.put(it)

    def _classify(self, req: GenRequest) -> tuple[bool, list]:
        """(simple, chain_keys): simple = eligible for the batched
        prefill (whole-prompt, no cached prefix to adopt, below the
        sequence-parallel and chunking thresholds, resolvable adapter).
        chain_keys are the prompt's content hashes — taken from
        req.prefix_hashes when the server's tokenizer pool pre-rolled
        them during encode, else computed ONCE here — and reused by
        both paths; only the cheap cache *probe* is redone at adoption
        time (cache state moves within a pass)."""
        n = len(req.prompt)
        if n < 1:
            return False, []
        chain: list = []
        if self.prefix_cache is not None and n > 1:
            ps = self.cfg.page_size
            if (req.prefix_hashes is not None
                    and len(req.prefix_hashes) == n // ps):
                chain = req.prefix_hashes
            else:
                chain = self.prefix_cache.chain_keys(req.prompt)
            hits = len(self.prefix_cache.probe(chain))
            if min(hits, n // ps) > 0:
                return False, chain
            if (self.host_tier is not None and hits < n // ps
                    and self.host_tier.contains(chain[hits])):
                # the chain extends into the host spill tier: the
                # per-request path revives the spilled pages and
                # resumes instead of re-prefilling
                return False, chain
        if ((self._prefill_sp_fn is not None
             or self._prefill_sp_suffix_fn is not None)
                and n >= self.cfg.sp_prefill_min_tokens):
            return False, chain
        chunk = self.cfg.prefill_chunk_tokens
        if (not self.attn.packs_long_prompts
                and chunk > 0 and self.fns.prefill_suffix is not None
                and n > chunk):
            # the ragged backend packs long prompts itself (budget-split
            # calls with decode ticks interleaved), so they stay
            # batch-eligible there
            return False, chain
        if req.adapter and not self._adapter_known(req.adapter):
            return False, chain  # singleton path surfaces the error
        if req.constraint is not None:
            # constrained admissions need the grammar's initial mask in
            # their prefill bias row — the per-request path builds it
            return False, chain
        if req.import_state is not None:
            # migration continuations restore key/count state that only
            # the per-request path knows how to thread into the slot
            return False, chain
        return True, chain

    @engine_thread_only
    def _admit_batch(
        self, reqs: list[GenRequest], chain_by_req: dict[int, list],
    ) -> tuple[int, list[GenRequest] | None]:
        """Allocate + batch-prefill ``reqs`` (all simple). Returns
        (admitted count, leftover): leftover is None without pressure,
        else the unallocated tail for the CALLER to requeue (alongside
        anything else it popped, in arrival order)."""
        from aigw_tpu.tpuserve.adapters import AdapterCapacityError

        prepared: list[tuple[GenRequest, int, int, int]] = []
        leftover: list[GenRequest] | None = None
        for i, req in enumerate(reqs):
            n = len(req.prompt)
            total = min(n + req.max_tokens, self.cfg.max_seq_len)
            seq_id = next(self._seq_ids)
            try:
                self.allocator.allocate(seq_id, total)
            except OutOfPagesError:
                self.allocator.free(seq_id)
                leftover = reqs[i:]
                break
            if req.adapter:
                # pin (and hot-load, when non-resident) the adapter row
                # BEFORE the batched prefill builds its sampling rows;
                # the pin transfers to the slot. All-rows-pinned is the
                # adapter analogue of page pressure: requeue and wait
                # for a generation to finish (classify already vetted
                # the name against the zoo).
                try:
                    self._acquire_adapter(req.adapter)
                except AdapterCapacityError:
                    self.allocator.free(seq_id)
                    leftover = reqs[i:]
                    break
            req.id = seq_id
            prepared.append((req, seq_id, n, total))
        count = 0
        if prepared:
            # the attention backend owns grouping + device calls
            # (bucket groups on xla-bucketed, one token-budget pack on
            # pallas-ragged); the engine owns slots + emission
            results = self.attn.group_prefill(prepared, chain_by_req)
            t_first = time.monotonic()
            for r in results:
                slot_idx = self._free_slot_index()
                assert slot_idx is not None  # len(items) <= free slots
                chain = chain_by_req.get(id(r.req), [])
                if self.prefix_cache is not None and chain:
                    # batched path = classified with no reusable prefix
                    self.stats.prefix_cache_misses += 1
                    self.prefix_cache.insert(
                        chain, self.allocator.pages(r.seq_id),
                        tokens=r.req.prompt)
                    self._purge_spilled(chain)
                self._slots[slot_idx] = _Slot(
                    req=r.req, pos=r.n - 1, generated=0,
                    key_seed=r.req.sampling.seed or r.seq_id,
                    limit=r.total, page_row=r.page_row,
                    adapter_row=r.adapter_row,
                    ctrl=self._make_ctrl(r.req),
                    # metering: the batched path exposes no per-request
                    # padding geometry — charge the real prompt volume
                    # (padding shows up in the aggregate prefill_tokens_*
                    # pair, not the per-request record) and start the
                    # HBM residency clock at the admitted footprint
                    m_prefill_real=r.n, m_prefill_padded=r.n,
                    m_res_t0=time.monotonic(),
                    m_res_bytes=(len(self.allocator.pages(r.seq_id))
                                 * self.kv_page_bytes),
                )
                self.stats.prefills += 1
                self._mark_admitted(slot_idx)
                t_m = time.monotonic()
                self._emit_token(slot_idx, r.tok, r.first_lp)
                self.phases.observe(
                    "first_emit", 1e3 * (time.monotonic() - t_m),
                    r.req.trace.trace_id if r.req.trace is not None
                    else "")
            self.stats.first_emit_ms += 1e3 * (
                time.monotonic() - t_first)
            count = len(results)
        return count, leftover

    @engine_thread_only
    def _mark_admitted(self, i: int) -> None:
        """Mark slot i for an incremental row upload into the live
        device state — including its speculation history/lookahead
        rows, so admissions never drain the pipeline. Falls back to a
        full rebuild only when the decode page bucket must grow (new
        compiled shape)."""
        self._dirty_rows.add(i)
        self._spec_dirty.discard(i)  # the full row carries draft_len
        self._cn_dirty.discard(i)  # …and the bias row incl. the mask
        if (self._device_state is not None and not self._need_rebuild
                and self._decode_bucket_pages() > self._state_bucket):
            self._need_rebuild = True

    @engine_thread_only
    def _admit_one(self, req: GenRequest, chain: list | None = None) -> str:
        """Per-request admission (prefix-cache adoption, chunked and
        sequence-parallel prefills, adapter errors). Returns "admitted",
        "skipped" (request consumed without a slot), "stop" (page
        pressure / engine stopping — the CALLER must requeue the request
        and stop admitting), or "stop_consumed" (stop admitting; the
        request needs no requeue). ``chain`` = prompt chain keys already
        hashed by _classify (the probe below stays fresh — an earlier
        admission this pass may have inserted or evicted pages)."""
        slot_idx = self._free_slot_index()
        if slot_idx is None:  # defensive: caller bounds by free slots
            return "stop"
        # the _Slot is not installed until AFTER the prefill, and
        # sp_chunked_prefill re-enters admission (_admit_interactive)
        # at chunk boundaries: reserve the index so a nested admission
        # cannot pick it and get clobbered when this install lands.
        # The finally also covers every abort return below.
        self._reserved_slots.add(slot_idx)
        try:
            return self._admit_one_reserved(req, slot_idx, chain)
        finally:
            self._reserved_slots.discard(slot_idx)

    @engine_thread_only
    def _admit_one_reserved(self, req: GenRequest, slot_idx: int,
                            chain: list | None) -> str:
        n = len(req.prompt)
        total = min(n + req.max_tokens, self.cfg.max_seq_len)
        seq_id = next(self._seq_ids)
        ps = self.cfg.page_size

        # prefix cache: adopt the longest cached page-prefix. A FULL
        # prefix hit (every prompt page cached, prompt page-aligned)
        # adopts everything, copy-on-writes the final page into a
        # private clone, and resumes with a single-token step — the
        # prompt prefill dispatch is skipped entirely; the resume rides
        # the first-token fast path like any prefill's sampled token.
        # Partial hits must leave at least one suffix token to produce
        # first logits, which page-granular hashing gives for free.
        cached_pages: list[int] = []
        chain_keys: list = []
        full_hit = False
        if self.prefix_cache is not None and n > 1:
            chain_keys = (chain if chain is not None
                          else self.prefix_cache.chain_keys(req.prompt))
            if self.host_tier is not None:
                # KV hierarchy revive (ISSUE 11): promote any spilled
                # run extending the resident prefix back into the pool
                # BEFORE the probe — the adoption below then sees the
                # revived pages as ordinary cached prefix
                self._revive_chain(chain_keys)
            hit_pages = self.prefix_cache.probe(chain_keys)
            hits = min(len(hit_pages), n // ps)
            full_hit = hits > 0 and hits * ps == n
            cached_pages = hit_pages[:hits]
        prefix_len = len(cached_pages) * ps
        if full_hit:
            # re-run only the last prompt token: its forward pass
            # yields the first-token logits; its (bit-recomputed) K/V
            # lands in the CoW'd private page, never the shared one
            prefix_len = n - 1

        try:
            if cached_pages:
                self.allocator.adopt(seq_id, cached_pages)
                extra = self.allocator.pages_for(total) - len(cached_pages)
                if extra > 0:
                    self.allocator.allocate_extra(seq_id, extra)
                if full_hit:
                    shared_last = cached_pages[-1]
                    fresh = self.allocator.cow_page(seq_id, shared_last)
                    self._copy_page_dev(shared_last, fresh)
                    self.stats.prefix_full_hits += 1
                    self.stats.prefix_cow_copies += 1
            else:
                self.allocator.allocate(seq_id, total)
        except OutOfPagesError:
            self.allocator.free(seq_id)
            # the caller puts it back (in arrival order) to wait for
            # a slot to free pages
            return "stop"
        if self._spec_max:
            # direct speculative-safety invariant (replaces the old
            # repin-on-rebuild guard): no page overlapping the slot's
            # writable tail [n, limit) may be shared — draft K/V
            # (including rejected drafts') scatters there. Healthy
            # layouts pass by construction; a violation is CoW-repaired
            # and logged, never silently corrupted.
            trunc = getattr(self.allocator, "truncate_to", None)
            if trunc is not None:
                for old_pg, new_pg, needs_copy in trunc(seq_id, n):
                    logger.warning(
                        "speculative admission CoW'd shared tail page "
                        "%d->%d for seq %d", old_pg, new_pg, seq_id)
                    if needs_copy:
                        self._copy_page_dev(old_pg, new_pg)
                        self.stats.prefix_cow_copies += 1
        pages = self.allocator.pages(seq_id)
        req.id = seq_id

        qw = 1e3 * (time.monotonic() - req.enqueued_at)
        self.phases.observe(
            "queue_wait", qw,
            req.trace.trace_id if req.trace is not None else "")
        if req.trace is not None:
            burst_id, burst_size = self._cur_burst
            req.trace.queue_wait(qw)
            req.trace.admission(
                path="single", burst_id=burst_id, burst_size=burst_size,
                prefix=("off" if not chain_keys
                        else "full" if full_hit
                        else "partial" if cached_pages else "miss"),
                pages_adopted=len(cached_pages),
                prefix_tokens=prefix_len)

        suffix = req.prompt[prefix_len:]
        ns = len(suffix)
        # sp routing: the chunked path (ring-attention chunk steps with
        # offset resume + decode interleaving) takes every long suffix;
        # the monolithic full-rung program remains only for geometries
        # the chunked program can't shard (page_size % sp != 0) or when
        # sp_prefill_mode="monolithic" — and it still can't resume, so
        # prefix hits there fall through to the single-device loop.
        use_sp_chunked = (
            self._prefill_sp_suffix_fn is not None
            and ns >= self.cfg.sp_prefill_min_tokens
        )
        use_sp = (
            not use_sp_chunked
            and self._prefill_sp_fn is not None
            and prefix_len == 0
            and ns >= self.cfg.sp_prefill_min_tokens
        )
        pt = np.zeros((1, self.cfg.max_pages_per_seq), np.int32)
        pt[0, : len(pages)] = pages

        adapter_row = self._base_row
        if req.adapter:
            from aigw_tpu.tpuserve.adapters import (
                AdapterCapacityError,
                UnknownAdapterError,
            )

            try:
                # pins (and hot-loads, when non-resident) the row; the
                # pin transfers to the slot below and is released when
                # the slot frees
                adapter_row = self._acquire_adapter(req.adapter)
            except UnknownAdapterError:
                self._meter_zero(req, "error")
                req.emit(-1, "error")
                self.allocator.free(seq_id)
                return "skipped"
            except AdapterCapacityError:
                # every row pinned by live slots: wait like page
                # pressure (caller requeues in arrival order)
                self.allocator.free(seq_id)
                return "stop"
        # migration continuation (ISSUE 8): resume with the sampling-key
        # state the solo run would have at this position — the prefill's
        # sampled token must be the exact token the exporting replica
        # would have decoded next (key counter m-1 = the position of the
        # pending input token at the cut)
        ims = req.import_state or {}
        key_seed = int(ims.get("key_seed") or
                       (req.sampling.seed or seq_id))
        key_counter = int(ims.get("key_counter", 0))
        key = np.array([[key_seed & 0xFFFFFFFF, key_counter]], np.uint32)
        # grammar constraint (ISSUE 9): the slot's FSM cursor; its
        # initial-state token mask composes into the prefill bias row so
        # the FIRST sampled token is already grammar-valid
        cn = None
        if req.constraint is not None:
            cn = req.constraint.new_state()
        bias_row = np.zeros((1, self.model_cfg.vocab_size), np.float32)
        for tok_id, b in req.sampling.logit_bias:
            if 0 <= tok_id < self.model_cfg.vocab_size:
                bias_row[0, tok_id] = b
        if cn is not None:
            bias_row[0] += cn.mask_row()
        sampling_args = (
            jnp.asarray(key),
            jnp.asarray([req.sampling.temperature], jnp.float32),
            jnp.asarray([req.sampling.top_p], jnp.float32),
            jnp.asarray([req.sampling.top_k], jnp.int32),
            jnp.asarray(bias_row),
            jnp.asarray([adapter_row], jnp.int32),
        )
        t0 = time.monotonic()
        # pow2 page bucket covering the sequence — the gather window
        # of suffix/chunked steps, not the full max_seq_len window
        need = self.allocator.pages_for(total)
        bucket = 1
        while bucket < need:
            bucket *= 2
        bucket = min(bucket, self.cfg.max_pages_per_seq)

        if use_sp_chunked:
            # sequence-sharded chunked prefill: ring-attention chunk
            # steps resuming at the cached page-aligned offset, decode
            # ticks at the boundaries — the long-context path
            # (tpuserve/attention.sp_chunked_prefill)
            from aigw_tpu.tpuserve.attention import sp_chunked_prefill

            res = sp_chunked_prefill(
                self, req, seq_id, suffix, prefix_len, n, pt, bucket,
                sampling_args)
            if isinstance(res, str):
                self._release_adapter_row(adapter_row)
                self.allocator.free(seq_id)
                return res
            next_tok, info = res
            self.stats.sp_prefills += 1
            self.stats.sp_chunked_prefills += 1
            if prefix_len:
                self.stats.sp_resume_prefills += 1
        elif use_sp:
            # ring attention shards the padded length over sp — the
            # divisibility guard rounds the chosen rung up to a
            # multiple of sp (non-power-of-two sp like 6 must not
            # silently disable the path, and intermediate rungs stay)
            S = self._prefill_bucket(ns, multiple_of=self._sp)
            tokens = np.zeros((1, S), np.int32)
            tokens[0, :ns] = suffix
            self.stats.sp_prefills += 1
            next_tok, self.kv_cache, moe = self._prefill_sp_fn(
                self.params,
                self.lora_params,
                jnp.asarray(tokens),
                jnp.asarray([n], jnp.int32),
                self.kv_cache,
                jnp.asarray(pt),
                *sampling_args,
            )
            self._fold_moe(moe)
            self.stats.prefill_tokens_real += ns
            self.stats.prefill_tokens_padded += S
            info = {"consumed": 0, "tick_ms": 0.0, "bucket": S,
                    "chunks": 0,
                    "padded_frac": round(1.0 - ns / S, 3) if S else 0.0}
        else:
            # the attention backend runs the prompt: bucketed chunk
            # loop + padded tail on xla-bucketed, token-budget packed
            # calls on pallas-ragged — both resume at prefix_len and
            # interleave decode ticks at their boundaries
            res = self.attn.single_prefill(
                req, seq_id, suffix, prefix_len, n, total, pt, bucket,
                sampling_args)
            if isinstance(res, str):
                # cancelled / engine stopping mid-prompt: hand it back
                # like an OutOfPages retry ("stop") or consume it —
                # the adapter pin never made it to a slot
                self._release_adapter_row(adapter_row)
                self.allocator.free(seq_id)
                return res
            next_tok, info = res
        tick_ms = info["tick_ms"]
        eff_prefix = prefix_len + info["consumed"]

        if prefix_len:
            self.stats.prefix_cache_hits += 1
            self.stats.prefix_tokens_reused += prefix_len
        elif chain_keys:
            # page-eligible prompt, nothing reusable cached
            self.stats.prefix_cache_misses += 1
        if self.cfg.first_token_fast_path:
            # start token 0's host copy under the prefill's compute
            self._start_host_copy(next_tok)
        first_lp = None
        if self.cfg.logprobs_topk and isinstance(next_tok, tuple):
            next_tok, chosen, tk_ids, tk_vals = next_tok
            first_lp = (
                float(np.asarray(chosen)[0]),
                [(int(t), float(v)) for t, v in zip(
                    np.asarray(tk_ids)[0], np.asarray(tk_vals)[0])],
            )
        tok = int(next_tok[0])
        self.stats.prefills += 1
        prefill_ms = max(0.0, 1e3 * (time.monotonic() - t0) - tick_ms)
        self.stats.prefill_ms += prefill_ms
        self.stats.note_prefill_call(prefill_ms, ns)
        self.phases.observe(
            "prefill", prefill_ms,
            req.trace.trace_id if req.trace is not None else "")
        if req.trace is not None:
            req.trace.prefill(
                prefill_ms, bucket=info["bucket"],
                padded_frac=info["padded_frac"],
                chunks=info["chunks"],
                resumed_at=eff_prefix, sp=use_sp or use_sp_chunked)
        t_first = time.monotonic()
        if self.prefix_cache is not None and chain_keys:
            self.prefix_cache.insert(chain_keys, pages,
                                     tokens=req.prompt)
            self._purge_spilled(chain_keys)
        logger.debug("prefill seq=%d len=%d prefix=%d bucket=%d %.1fms",
                     seq_id, n, prefix_len, info["bucket"],
                     1e3 * (time.monotonic() - t0))

        # speculative draft sources for the new slot: the adaptive
        # controller, plus — when the radix chain remembers what
        # followed this prefix last time — one page of continuation
        # tokens as the lookahead draft buffer (repeated chat traffic's
        # free high-acceptance source)
        ctrl = self._make_ctrl(req)
        la_base = 0
        la_tokens: list[int] = []
        if (ctrl is not None and self.prefix_cache is not None
                and chain_keys):
            cont = self.prefix_cache.continuation(chain_keys)
            if cont is not None and cont[0] * ps + len(cont[1]) > n:
                la_base = cont[0] * ps
                la_tokens = cont[1]
                self.stats.spec_lookahead_slots += 1

        # migration continuation: generated-so-far tokens ride in the
        # prompt tail — they must keep counting toward the repetition
        # penalties exactly as they did on the exporting replica
        counts: dict[int, int] = {}
        for t in req.prompt[int(ims.get("orig_prompt_len", n)):]:
            counts[t] = counts.get(t, 0) + 1
        # usage metering (ISSUE 20): prefill attribution + the HBM
        # residency clock. Padded volume is geometry-derived from the
        # backend's padded_frac (= 1 - real/processed), so all three
        # prefill paths report through one formula.
        pf = float(info.get("padded_frac") or 0.0)
        m_padded = int(round(ns / (1.0 - pf))) if 0.0 < pf < 1.0 else ns
        # pos=n-1: _emit_token advances it to n, the write position of
        # the just-sampled first token.
        self._slots[slot_idx] = _Slot(
            req=req, pos=n - 1, generated=0,
            key_seed=key_seed,
            limit=total, page_row=pt[0], adapter_row=adapter_row,
            token_counts=counts,
            ctrl=ctrl, la_base=la_base, la_tokens=la_tokens,
            cn=cn,
            m_prefill_real=ns, m_prefill_padded=m_padded,
            m_prefix_reused=prefix_len,
            m_res_t0=time.monotonic(),
            m_res_bytes=len(pages) * self.kv_page_bytes,
            m_carry=ims.get("meter_carry"),
        )
        self._mark_admitted(slot_idx)
        if cn is not None:
            # counted at ADMISSION (not FSM creation): a page-pressure
            # requeue must not double-count the request
            self.stats.constraint_requests += 1
            # the prefill's sampled token is mask-guaranteed valid;
            # advance the FSM so the first decode window dispatches
            # with the POST-first-token mask (marked dirty by the full
            # row upload _mark_admitted scheduled)
            cn.advance(tok)
        self._emit_token(slot_idx, tok, first_lp)
        first_emit_ms = 1e3 * (time.monotonic() - t_first)
        self.stats.first_emit_ms += first_emit_ms
        self.phases.observe(
            "first_emit", first_emit_ms,
            req.trace.trace_id if req.trace is not None else "")
        return "admitted"

    def _requeue_front_many(self, reqs: list[GenRequest]) -> None:
        # queue.Queue has no push-front; use a tiny shim list
        items = list(reqs)
        if not items:
            return
        try:
            while True:
                items.append(self._queue.get_nowait())
        except queue.Empty:
            pass
        for it in items:
            self._queue.put(it)

    def _decode_bucket_pages(self) -> int:
        """Smallest power-of-two page count covering every active slot's
        allocation — the decode gather window shrinks to what the batch
        actually needs (short sequences don't pay max_seq_len attention).
        jax.jit compiles one program per bucket shape."""
        P = self.cfg.max_pages_per_seq
        need = 1
        for s in self._slots:
            if s is not None:
                need = max(need, -(-s.limit // self.cfg.page_size))
        bucket = 1
        while bucket < need:
            bucket *= 2
        return min(bucket, P)

    def _build_device_state(
            self, bucket: int | None = None) -> dict[str, jax.Array]:
        """Upload the FULL per-slot state (first build, page-bucket
        growth, speculation). Ordinary membership changes go through
        the incremental row update in _apply_row_updates instead.
        ``bucket`` pins the page-table width (warmup pre-compiling the
        ladder at buckets traffic hasn't reached yet).

        PURE builder — it must not publish anything through self:
        warmup() calls it from the server thread while the engine loop
        is live, and a side-effecting write here (this method used to
        set self._state_bucket) raced _mark_admitted's bucket-growth
        check into skipping a rebuild the live batch needed. The
        engine-thread caller in _decode_tick records the bucket."""
        B = self.cfg.max_batch_size
        P = bucket if bucket is not None else self._decode_bucket_pages()
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        limits = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        page_table = np.zeros((B, P), np.int32)
        keys = np.zeros((B, 2), np.uint32)
        temp = np.ones((B,), np.float32)
        top_p = np.ones((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        freq_pen = np.zeros((B,), np.float32)
        pres_pen = np.zeros((B,), np.float32)
        V = self.model_cfg.vocab_size
        counts = np.zeros((B, V), np.int32)
        bias = np.zeros((B, V), np.float32)
        adapter_idx = np.full((B,), self._base_row, np.int32)
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            tokens[i] = s.pending_token
            positions[i] = s.pos
            limits[i] = s.limit
            active[i] = True
            page_table[i] = s.page_row[:P]
            keys[i, 0] = np.uint32(s.key_seed & 0xFFFFFFFF)
            keys[i, 1] = np.uint32(s.pos)
            temp[i] = s.req.sampling.temperature
            top_p[i] = s.req.sampling.top_p
            top_k[i] = s.req.sampling.top_k
            freq_pen[i] = s.req.sampling.frequency_penalty
            pres_pen[i] = s.req.sampling.presence_penalty
            for tok_id, cnt in s.token_counts.items():
                if 0 <= tok_id < V:
                    counts[i, tok_id] = cnt
            for tok_id, b in s.req.sampling.logit_bias:
                if 0 <= tok_id < V:
                    bias[i, tok_id] = b
            if s.cn is not None:
                bias[i] += s.cn.mask_row()
            adapter_idx[i] = s.adapter_row
        state_extra: dict[str, jax.Array] = {}
        if self._spec_max:
            # speculation rows: token history (prompt + generated,
            # valid through the pending token's position), the per-slot
            # adaptive draft length, and the prefix-cache continuation
            # lookahead. The row update uploads the same fields
            # per-slot, so admissions never force this full build.
            L = self.cfg.page_size
            history = np.zeros((B, self.cfg.max_seq_len), np.int32)
            draft_len = np.zeros((B,), np.int32)
            lookahead = np.zeros((B, L), np.int32)
            la_base = np.zeros((B,), np.int32)
            la_len = np.zeros((B,), np.int32)
            for i, s in enumerate(self._slots):
                if s is None:
                    continue
                pr = s.req.prompt
                history[i, : len(pr)] = pr
                history[i, len(pr): len(pr) + len(s.gen_tokens)] = (
                    s.gen_tokens
                )
                if s.ctrl is not None:
                    draft_len[i] = s.ctrl.draft_len()
                    s.dev_draft_len = int(draft_len[i])
                if s.la_tokens:
                    lookahead[i, : len(s.la_tokens)] = s.la_tokens
                    la_base[i] = s.la_base
                    la_len[i] = len(s.la_tokens)
            state_extra["history"] = jnp.asarray(history)
            state_extra["draft_len"] = jnp.asarray(draft_len)
            state_extra["lookahead"] = jnp.asarray(lookahead)
            state_extra["la_base"] = jnp.asarray(la_base)
            state_extra["la_len"] = jnp.asarray(la_len)
        state = state_extra | {
            "tokens": jnp.asarray(tokens),
            "positions": jnp.asarray(positions),
            "limits": jnp.asarray(limits),
            "active": jnp.asarray(active),
            "page_table": jnp.asarray(page_table),
            "keys": jnp.asarray(keys),
            "temp": jnp.asarray(temp),
            "top_p": jnp.asarray(top_p),
            "top_k": jnp.asarray(top_k),
            "freq_pen": jnp.asarray(freq_pen),
            "pres_pen": jnp.asarray(pres_pen),
            "counts": jnp.asarray(counts),
            "bias": jnp.asarray(bias),
            "adapter_idx": jnp.asarray(adapter_idx),
        }
        if self._state_sharding is not None:
            # canonical placement: fresh builds and program outputs
            # (pinned by _pin_state) share ONE layout, so a dispatch is
            # never a layout-only jit-cache miss on the mesh
            state = jax.device_put(state, self._state_sharding)
        return state

    def _row_host_values(self, i: int, P: int) -> dict[str, np.ndarray]:
        """Host-side row i of the device state (cleared when the slot is
        empty). Shapes/dtypes mirror _build_device_state exactly."""
        V = self.model_cfg.vocab_size
        s = self._slots[i]
        row = {
            "tokens": np.int32(0),
            "positions": np.int32(0),
            "limits": np.int32(0),
            "active": np.bool_(False),
            "page_table": np.zeros((P,), np.int32),
            "keys": np.zeros((2,), np.uint32),
            "temp": np.float32(1.0),
            "top_p": np.float32(1.0),
            "top_k": np.int32(0),
            "freq_pen": np.float32(0.0),
            "pres_pen": np.float32(0.0),
            "counts": np.zeros((V,), np.int32),
            "bias": np.zeros((V,), np.float32),
            "adapter_idx": np.int32(self._base_row),
        }
        if self._spec_max:
            L = self.cfg.page_size
            row["history"] = np.zeros((self.cfg.max_seq_len,), np.int32)
            row["draft_len"] = np.int32(0)
            row["lookahead"] = np.zeros((L,), np.int32)
            row["la_base"] = np.int32(0)
            row["la_len"] = np.int32(0)
        if s is None:
            return row
        row["tokens"] = np.int32(s.pending_token)
        row["positions"] = np.int32(s.pos)
        row["limits"] = np.int32(s.limit)
        row["active"] = np.bool_(True)
        row["page_table"] = np.asarray(s.page_row[:P], np.int32)
        row["keys"] = np.array(
            [s.key_seed & 0xFFFFFFFF, s.pos], np.uint32)
        row["temp"] = np.float32(s.req.sampling.temperature)
        row["top_p"] = np.float32(s.req.sampling.top_p)
        row["top_k"] = np.int32(s.req.sampling.top_k)
        row["freq_pen"] = np.float32(s.req.sampling.frequency_penalty)
        row["pres_pen"] = np.float32(s.req.sampling.presence_penalty)
        for tok_id, cnt in s.token_counts.items():
            if 0 <= tok_id < V:
                row["counts"][tok_id] = cnt
        for tok_id, b in s.req.sampling.logit_bias:
            if 0 <= tok_id < V:
                row["bias"][tok_id] = b
        if s.cn is not None:
            row["bias"] += s.cn.mask_row()
        row["adapter_idx"] = np.int32(s.adapter_row)
        if self._spec_max:
            pr = s.req.prompt
            row["history"][: len(pr)] = pr
            row["history"][len(pr): len(pr) + len(s.gen_tokens)] = (
                s.gen_tokens)
            if s.ctrl is not None:
                row["draft_len"] = np.int32(s.ctrl.draft_len())
                s.dev_draft_len = int(row["draft_len"])
            if s.la_tokens:
                row["lookahead"][: len(s.la_tokens)] = s.la_tokens
                row["la_base"] = np.int32(s.la_base)
                row["la_len"] = np.int32(len(s.la_tokens))
        return row

    def _row_update_fn_built(self):
        if self._row_update_fn is None:
            def _upd(state, i, row):
                return self._pin_state({
                    k: (state[k].at[i].set(row[k]) if k in row
                        else state[k])
                    for k in state
                })

            self._row_update_fn = self.compile_tracker.register(
                "row_update", jax.jit(_upd, donate_argnums=(0,)))
        return self._row_update_fn

    @engine_thread_only
    def _apply_row_updates(self) -> None:
        """Scatter dirty slot rows into the LIVE device state — no
        pipeline drain, no full re-upload. JAX chains the update after
        the in-flight window's scan, so admission/finish no longer
        stalls the decode pipeline for a whole window."""
        self._row_update_fn_built()
        P = self._state_bucket
        for i in sorted(self._dirty_rows):
            self._device_state = self._row_update_fn(
                self._device_state, np.int32(i),
                self._row_host_values(i, P))
        self._dirty_rows.clear()

    def _spec_update_fn_built(self):
        if self._spec_update_fn is None:
            def _sup(state, i, d):
                return self._pin_state(dict(
                    state, draft_len=state["draft_len"].at[i].set(d)))

            self._spec_update_fn = self.compile_tracker.register(
                "spec_row_update", jax.jit(_sup, donate_argnums=(0,)))
        return self._spec_update_fn

    @engine_thread_only
    def _apply_spec_row_updates(self) -> None:
        """Patch live slots' on-device ``draft_len`` after an adaptive
        rung move. Unlike the full row update this touches ONLY the
        draft length — a live slot's positions/history on device run
        ahead of the host's view while a window is in flight, so
        re-uploading its full row mid-pipeline would rewind it, but
        the draft length is position-independent and safe to patch at
        any time."""
        self._spec_update_fn_built()
        for i in sorted(self._spec_dirty):
            s = self._slots[i]
            d = (s.ctrl.draft_len()
                 if s is not None and s.ctrl is not None else 0)
            self._device_state = self._spec_update_fn(
                self._device_state, np.int32(i), np.int32(d))
            if s is not None:
                s.dev_draft_len = d
        self._spec_dirty.clear()

    def _cn_bias_row(self, s: _Slot) -> np.ndarray:
        """Host-side bias row of a constrained slot: the request's
        logit_bias plus the FSM state's token mask."""
        V = self.model_cfg.vocab_size
        row = np.zeros((V,), np.float32)
        for tok_id, b in s.req.sampling.logit_bias:
            if 0 <= tok_id < V:
                row[tok_id] = b
        row += s.cn.mask_row()
        return row

    def _cn_update_fn_built(self):
        if self._cn_update_fn is None:
            def _bup(state, i, row):
                return self._pin_state(dict(
                    state, bias=state["bias"].at[i].set(row)))

            self._cn_update_fn = self.compile_tracker.register(
                "cn_mask_update", jax.jit(_bup, donate_argnums=(0,)))
        return self._cn_update_fn

    @engine_thread_only
    def _apply_cn_row_updates(self) -> None:
        """Patch live constrained slots' on-device bias rows after an
        FSM advance. Like the draft_len patch, the bias row is
        position-independent — safe to scatter mid-pipeline; a full row
        upload (_apply_row_updates) already carries the mask, so rows
        in _dirty_rows are skipped here."""
        fn = self._cn_update_fn_built()
        for i in sorted(self._cn_dirty):
            s = self._slots[i]
            if s is None or s.cn is None or i in self._dirty_rows:
                continue
            self._device_state = fn(
                self._device_state, np.int32(i), self._cn_bias_row(s))
            self.stats.constraint_mask_updates += 1
        self._cn_dirty.clear()

    @engine_thread_only
    def _cn_verify(self, i: int, s: _Slot, tok: int,
                   dispatch_mask) -> bool:
        """Verify + advance slot i's constraint FSM with ``tok``, which
        the window sampled under ``dispatch_mask``. True = emit.

        Acceptance rule: a token counts only while the slot's CURRENT
        FSM state demands exactly the mask the window was dispatched
        with — then the on-device sample was drawn from precisely the
        distribution a per-step-masked decode would have used (same
        bias row, same per-position key), so accepted streams are
        bit-identical to true single-step constrained decoding. The
        moment the FSM advance changes the mask, the window is cut and
        the slot ROLLED BACK to its last accepted token, exactly as a
        rejected speculative draft: the host state never advanced, so
        re-uploading the row (position / key / counts / history / mask)
        restores the device to the cut point, and the epoch bump makes
        the drain of the one window already in flight discard this
        slot's tokens. Stale KV past the cut is rewritten by subsequent
        decode steps — the spec-decode rejection discipline."""
        cur = s.cn.mask_row()
        if cur is not dispatch_mask and not np.array_equal(
                cur, dispatch_mask):
            self._cn_rollback(i, s)
            return False
        if s.cn.advance(tok):
            if tok not in self.eos:
                self._cn_dirty.add(i)
            return True
        # defensive: a mask-allowed token must be grammar-valid; treat
        # any disagreement as a cut rather than corrupting the stream
        self._cn_rollback(i, s)
        return False

    @engine_thread_only
    def _cn_rollback(self, i: int, s: _Slot) -> None:
        s.cn_epoch += 1
        self._dirty_rows.add(i)
        self._cn_dirty.discard(i)
        self.stats.constraint_rollbacks += 1
        if s.req.trace is not None:
            s.req.trace.constraint_rollback()

    def _make_ctrl(self, req: GenRequest):
        """Adaptive draft controller for a fresh slot — or None when
        the request is ineligible (sampling / penalties: those slots
        fall back to plain decode and never lift the dispatch width)."""
        sp = req.sampling
        if (not self._spec_max or sp.temperature > 0.0
                or sp.frequency_penalty != 0.0
                or sp.presence_penalty != 0.0):
            return None
        return speculation.DraftController(
            self._spec_rungs, self._accept_prior, self.cfg.spec_adaptive)

    @engine_thread_only
    def _choose_draft_len(self) -> int:
        """Dispatch draft width: the max of the active eligible slots'
        adaptive rungs. 0 dispatches the PLAIN decode program —
        default-on speculation costs nothing once every ladder has
        collapsed. Ticking the controllers here also runs the rung-0
        re-probe policy; any rung move is patched on device before the
        dispatch that follows."""
        if not self._spec_max:
            return 0
        d = 0
        for i, s in enumerate(self._slots):
            if s is None or s.ctrl is None:
                continue
            before = s.ctrl.draft_len()
            nd = s.ctrl.tick()
            if nd > before:
                self.stats.spec_rung_ups += 1  # rung-0 re-probe
            if nd != s.dev_draft_len and i not in self._dirty_rows:
                self._spec_dirty.add(i)
            d = max(d, nd)
        self.stats.spec_draft_len = d
        return d

    @engine_thread_only
    def _process_window(self, toks: np.ndarray, lp,
                        members: tuple,
                        cn_epochs: dict | None = None) -> None:
        """Distribute one decode window's host-side tokens. Only slots
        that were members of the window at DISPATCH time (and still hold
        the same request) receive tokens — rows admitted after dispatch
        carry junk samples for this window and are skipped; a
        constrained slot whose rollback epoch moved past the window's
        captured epoch is skipped the same way (the window computed
        past a grammar violation)."""
        K = toks.shape[0]
        ce = cn_epochs or {}
        self.stats.decode_steps += K
        for k in range(K):
            for i, req in members:
                s = self._slots[i]
                if s is None or s.req is not req:
                    continue  # finished earlier in this window / re-used
                if s.cn is not None:
                    ent = ce.get(i)
                    if ent is None or ent[0] != s.cn_epoch:
                        continue  # stale window for a rolled-back slot
                    if not self._cn_verify(i, s, int(toks[k, i]),
                                           ent[1]):
                        continue  # mask boundary: rolled back here
                step_lp = None
                if lp is not None:
                    chosen, tk_ids, tk_vals = lp
                    step_lp = (
                        float(chosen[k, i]),
                        [(int(t), float(v))
                         for t, v in zip(tk_ids[k, i], tk_vals[k, i])],
                    )
                self._emit_token(i, int(toks[k, i]), step_lp)

    @engine_thread_only
    def _process_spec_window(self, toks: np.ndarray, counts: np.ndarray,
                             props: np.ndarray, members: tuple,
                             draft_lens: tuple = (),
                             cn_epochs: dict | None = None) -> None:
        """Speculative window: sampled [K, B, D+1], n_emit [K, B],
        n_prop [K, B] — the leading n_emit tokens of each row are
        model-exact; the rest are conditioned on rejected drafts and
        discarded. Afterwards each surviving slot's adaptive controller
        observes the window's proposed/accepted counts and may move its
        rung (patched on device by the draft_len-only row update before
        the next dispatch)."""
        K = toks.shape[0]
        ce = cn_epochs or {}
        self.stats.decode_steps += K
        dl = dict(draft_lens)
        proposed = dict.fromkeys(dl, 0)
        accepted = dict.fromkeys(dl, 0)
        live = dict.fromkeys(dl, False)
        for k in range(K):
            for i, req in members:
                s = self._slots[i]
                if s is None or s.req is not req:
                    continue
                if s.cn is not None:
                    ent = ce.get(i)
                    if ent is None or ent[0] != s.cn_epoch:
                        continue  # stale window for a rolled-back slot
                n = int(counts[k, i])
                if n > 0:
                    proposed[i] = proposed.get(i, 0) + int(props[k, i])
                    live[i] = True
                    # meter attribution BEFORE the emit loop: a slot
                    # that finishes mid-step carries this step's drafts
                    # in its terminal record
                    s.m_spec_drafted += int(props[k, i])
                emitted = 0
                for d in range(n):
                    cur = self._slots[i]
                    if cur is None or cur.req is not req:
                        break  # EOS/stop consumed the slot mid-burst
                    if cur.cn is not None and not self._cn_verify(
                            i, cur, int(toks[k, i, d]), ce[i][1]):
                        break  # mask boundary: rolled back here
                    if emitted > 0:
                        # every token past the first is a landed draft;
                        # credited before its emit so a finish on the
                        # accepted token itself still meters it
                        cur.m_spec_accepted += 1
                    self._emit_token(i, int(toks[k, i, d]))
                    emitted += 1
                if emitted > 1:
                    self.stats.spec_accepted += emitted - 1
                    accepted[i] = accepted.get(i, 0) + emitted - 1
        for i, req in members:
            # only slots that decoded under a nonzero draft width this
            # window carry a controller signal
            if not live.get(i, False) or dl.get(i, 0) <= 0:
                continue
            self.stats.spec_drafted += proposed.get(i, 0)
            if req.trace is not None:
                req.trace.spec_window(proposed.get(i, 0),
                                      accepted.get(i, 0))
            s = self._slots[i]
            if s is None or s.req is not req or s.ctrl is None:
                continue
            move = s.ctrl.observe_window(proposed.get(i, 0),
                                         accepted.get(i, 0))
            if move:
                if move > 0:
                    self.stats.spec_rung_ups += 1
                else:
                    self.stats.spec_rung_downs += 1
                if i not in self._dirty_rows:
                    self._spec_dirty.add(i)

    @engine_thread_only
    def _drain_inflight(self) -> None:
        """Settle the in-flight window: resolve its (already started,
        under async_transfers) device→host copy, emit tokens, and apply
        the page frees it was carrying."""
        w, self._inflight = self._inflight, None
        if w is None:
            return
        t0 = time.monotonic()
        host = jax.tree_util.tree_map(np.asarray, w.sampled)
        t1 = time.monotonic()
        tr_ms = 1e3 * (t1 - t0)
        self.stats.transfer_ms += tr_ms
        ex = ""
        for _i, _req in w.members:
            if _req.trace is not None:
                _req.trace.transfer(tr_ms)
                ex = ex or _req.trace.trace_id
        self.phases.observe("transfer", tr_ms, ex)
        ce = ({i: (ep, m) for i, ep, m in w.cn_epochs}
              if w.cn_epochs else None)
        if w.draft:
            self._process_spec_window(host[0], host[1], host[2],
                                      w.members, w.draft_lens, ce)
        elif isinstance(host, tuple):  # logprobs window
            toks, chosen, tk_ids, tk_vals = host
            self._process_window(toks, (chosen, tk_ids, tk_vals),
                                 w.members, ce)
        else:
            self._process_window(host, None, w.members, ce)
        self.stats.emit_ms += 1e3 * (time.monotonic() - t1)
        # the window's routing-stats leaf settles with the window — a
        # dispatch-time read would sync against the running program
        self._fold_moe(w.moe)
        for seq_id in w.frees:
            self.allocator.free(seq_id)

    @engine_thread_only
    def _fold_moe(self, moe) -> None:
        """Fold one program's [L, E+1] routing-stats leaf (per-expert
        placed counts + capacity drops per layer) into the numpy
        accumulators behind the /state MoE surface. No-op (None) on
        dense families — call sites stay uniform."""
        if moe is None:
            return
        arr = np.asarray(moe, np.int64)
        self._moe_expert_tokens += arr[:, :-1].sum(axis=0)
        self._moe_layer_drops += arr[:, -1]

    def moe_expert_load(self) -> list[int]:
        """Per-expert placed-token totals [E] for /state and the
        labeled /metrics twins; [] on dense families. Read-only
        snapshot — safe off the engine thread (int64 element reads are
        GIL-atomic; a torn read is one fold stale, like every gauge)."""
        if not self._moe:
            return []
        return [int(x) for x in self._moe_expert_tokens]

    def moe_layer_drops(self) -> list[int]:
        """Per-layer capacity-drop totals [L]; [] on dense families."""
        if not self._moe:
            return []
        return [int(x) for x in self._moe_layer_drops]

    @engine_thread_only
    def _apply_frees(self) -> None:
        """Recycle pages of finished sequences. Only safe with NO window
        in flight (callers drain first): an in-flight window dispatched
        while the sequence was active may still write into its pages."""
        assert self._inflight is None
        for seq_id in self._pending_frees:
            self.allocator.free(seq_id)
        self._pending_frees.clear()

    @engine_thread_only
    def _decode_tick(self) -> bool:
        """Pipelined: dispatch window N+1, then process window N while
        the device runs. Membership changes are scattered into the live
        device state as row updates (chained asynchronously after the
        in-flight window), so admissions and completions no longer drain
        the pipeline; only page-bucket growth / speculation force a full
        drain + state rebuild."""
        active_idx = [i for i, s in enumerate(self._slots) if s is not None]
        if not active_idx:
            self._drain_inflight()
            self._apply_frees()
            # quiesced: drop the state so the next admission rebuilds it
            # right-sized (free here — nothing in flight — and an
            # oversized page bucket a departed long sequence forced is
            # released instead of taxing the next batch's gathers)
            self._device_state = None
            self._dirty_rows.clear()
            self._cn_dirty.clear()
            self.stats.active_slots = 0
            self._refresh_stats()
            return False

        if self._need_rebuild or self._device_state is None:
            if self._need_rebuild and self._device_state is not None:
                # a LIVE pipeline is drained for a full rebuild — only
                # page-bucket growth lands here now; the speculative
                # path must never (the zero-rebuild acceptance
                # criterion asserts on this counter)
                self.stats.state_rebuilds += 1
            # finish the window computed under the old state first
            self._drain_inflight()
            self._apply_frees()
            # that drain may have emitted stop/length finishes: rebuild
            # membership from the slots that actually survived (a stale
            # tick-entry index here dereferenced a freed slot and threw
            # the whole engine into _abort_all)
            active_idx = [i for i, s in enumerate(self._slots)
                          if s is not None]
            if not active_idx:
                self._device_state = None
                self._dirty_rows.clear()
                self._spec_dirty.clear()
                self._cn_dirty.clear()
                self.stats.active_slots = 0
                self._refresh_stats()
                return True
            P = self._decode_bucket_pages()
            self._device_state = self._build_device_state(bucket=P)
            self._state_bucket = P
            self._need_rebuild = False
            self._dirty_rows.clear()
            self._spec_dirty.clear()
            self._cn_dirty.clear()  # the full build carried the masks
        elif self._dirty_rows:
            self._apply_row_updates()
        if self._cn_dirty:
            # constrained slots whose FSM advanced since the last
            # dispatch: patch their bias rows (user bias + new mask)
            # before this dispatch samples under them
            self._apply_cn_row_updates()

        if self._inflight is not None:
            # Zombie-window guard: when every member slot reaches its
            # token limit within the window already in flight, another
            # dispatch would compute K junk steps against slots that are
            # all about to finish — junk that delays the next admission
            # by a full window (and burns K chip-steps per batch drain).
            # Drain instead; the loop admits or re-dispatches right
            # after. Slots admitted after the in-flight dispatch are not
            # advanced by it, so they block the guard (they need a
            # dispatch). Conservative under speculation (slots may
            # finish even sooner than +K; the guard then fires one
            # window later).
            K = self._inflight.k
            in_window = {i: req for i, req in self._inflight.members}
            if all(
                s is None
                or (in_window.get(i) is s.req
                    and (s.generated + K >= s.req.max_tokens
                         or s.pos + K >= min(s.limit, self.cfg.max_seq_len)))
                for i, s in enumerate(self._slots)
            ):
                self._drain_inflight()
                self._apply_frees()
                self.stats.active_slots = sum(
                    s is not None for s in self._slots)
                self._refresh_stats()
                return True

        # speculative dispatch width (and any rung-move patches) must
        # settle before the program choice below
        draft = self._choose_draft_len()
        if self._spec_dirty:
            self._apply_spec_row_updates()
        k = self._choose_window()
        members = tuple(
            (i, self._slots[i].req) for i in active_idx
        )
        draft_lens: tuple = ()
        if draft:
            draft_lens = tuple(
                (i, self._slots[i].ctrl.draft_len())
                for i in active_idx
                if self._slots[i].ctrl is not None
            )
        cn_epochs = tuple(
            (i, self._slots[i].cn_epoch,
             self._slots[i].cn.mask_row()) for i in active_idx
            if self._slots[i].cn is not None
        )
        frees, self._pending_frees = self._pending_frees, []
        lean = draft == 0 and self._lean_decode_ok()
        decode_fn = self._decode_fn_for(k, lean, draft)
        sampled, self._device_state, self.kv_cache, moe = decode_fn(
            self.params, self.lora_params, self.kv_cache, self._device_state
        )
        if self.cfg.async_transfers:
            # start the device→host token copy now; it overlaps this
            # window's on-device compute and is resolved at drain time
            self._start_host_copy(sampled)
        # process the PREVIOUS window while this one runs on-device
        self._drain_inflight()
        self._inflight = _Window(sampled=sampled, members=members, k=k,
                                 frees=frees, draft=draft,
                                 draft_lens=draft_lens,
                                 cn_epochs=cn_epochs, moe=moe)
        for _i, _req in members:
            if _req.trace is not None:
                _req.trace.decode_window(k, lean, draft)
        self.stats.active_slots = sum(s is not None for s in self._slots)
        self._refresh_stats()
        return True

    @engine_thread_only
    def _emit_token(self, i: int, tok: int, lp=None) -> None:
        """Record one generated token for slot i; finish if stopping.
        ``lp`` = (chosen_logprob, [(top_id, top_logprob)]) when the
        engine runs with logprobs_topk > 0."""
        s = self._slots[i]
        assert s is not None
        req = s.req

        def _send(t: int, f: str | None) -> None:
            if req.emit_lp is not None:
                if lp is None or t < 0:
                    req.emit_lp(t, f, None, None)
                else:
                    req.emit_lp(t, f, lp[0], lp[1])
            else:
                req.emit(t, f)

        s.generated += 1
        if s.generated == 1:
            s.first_emit_at = time.monotonic()
            # engine-side TTFT: arrival → first sampled token available
            # (queue wait + prefill + first-emit residual). Batch
            # streams are EXCLUDED — the histogram feeds the SLO
            # burn-rate monitor and the gateway's predicted-TTFT
            # pricing, both of which must see only interactive latency
            # (offline work queuing for minutes is by design, not burn)
            if req.priority != "batch":
                self.phases.observe(
                    "ttft", 1e3 * (s.first_emit_at - req.enqueued_at),
                    req.trace.trace_id if req.trace is not None else "")
            if req.trace is not None:
                req.trace.first_token()
        finish: str | None = None
        send_tok = tok
        if tok in self.eos or tok in req.stop_token_ids:
            finish = "stop"
            send_tok = -1
        else:
            s.pos += 1  # where `tok` will be written by the next decode
            if s.generated >= req.max_tokens or s.pos >= self.cfg.max_seq_len:
                finish = "length"
        if finish is not None:
            # MeterRecord BEFORE the terminal emit: the consumer that
            # dequeues the finish item observes the record (engine
            # thread posts both; call_soon_threadsafe keeps FIFO order)
            self._meter_finish(s, finish)
        _send(send_tok, finish)
        self.stats.tokens_generated += 1
        if req.priority == "batch":
            self.stats.batch_tokens += 1
        if finish is not None:
            if s.generated > 1 and s.first_emit_at:
                self.phases.observe(
                    "decode_per_token",
                    1e3 * (time.monotonic() - s.first_emit_at)
                    / (s.generated - 1),
                    req.trace.trace_id if req.trace is not None else "")
            if req.trace is not None:
                req.trace.engine_finish(finish)
            self._pending_frees.append(req.id)
            self._release_adapter_row(s.adapter_row)
            self._slots[i] = None
            self._dirty_rows.add(i)
            self._wake.set()  # maybe admit a queued request
        else:
            # the sampled token is the input of the next decode step
            s.pending_token = tok
            s.token_counts[tok] = s.token_counts.get(tok, 0) + 1
            s.gen_tokens.append(tok)

    @engine_thread_only
    def _refresh_stats(self) -> None:
        # ``queued`` is INTERACTIVE depth only — the picker's
        # predicted_ttft_ms and the controller's idle predicate price
        # it; offline backlog rides the batch_* pair below
        self.stats.queued = self._queue.qsize()
        self.stats.batch_queued = (self._batch_q.qsize()
                                   + len(self._parked_batch))
        self.stats.batch_active = self._batch_active()
        if self.stats.prefill_tokens_padded:
            self.stats.prefill_padded_frac = round(
                1.0 - self.stats.prefill_tokens_real
                / self.stats.prefill_tokens_padded, 4)
        self.stats.xla_compiles = self.compile_tracker.compiles()
        self.stats.xla_compile_ms = round(
            self.compile_tracker.compiles_total_ms(), 3)
        self.stats.kv_pages_free = self.allocator.free_pages
        self.stats.kv_occupancy = self.allocator.occupancy
        # adapter residency + tenant fairness gauges (ISSUE 7)
        if self._adapter_store is not None:
            self.stats.adapter_loads = self._adapter_store.loads
            self.stats.adapter_evictions = self._adapter_store.evictions
            self.stats.adapter_resident = (
                self._adapter_store.resident_count)
        else:
            self.stats.adapter_resident = len(self.adapter_rows)
        self.stats.adapter_slots = sum(
            1 for s in self._slots
            if s is not None and s.adapter_row != self._base_row)
        # grammar-constrained decoding surface (ISSUE 9)
        self.stats.constrained_slots = sum(
            1 for s in self._slots if s is not None and s.cn is not None)
        self.stats.constraint_grammars = constrain.grammar_cache_size()
        # measured per-device memory (satellite): throttled — the
        # native memory_stats() call is cheap but pointless per tick
        now_m = time.monotonic()
        if now_m >= self._mem_next:
            self._mem_next = now_m + 0.5
            used, limit = device_memory_stats()
            self.stats.device_bytes_in_use = used
            self.stats.device_bytes_limit = limit
            self.stats.device_memory_frac = (
                round(used / limit, 4) if limit else 0.0)
            self.stats.kv_pool_bytes = (
                self.cfg.num_pages * self.kv_page_bytes)
            self.stats.kv_bytes_in_use = round(
                self.stats.kv_pool_bytes * self.allocator.occupancy)
            # mesh serving (ISSUE 10): EVERY local device, not just
            # device 0 — per-device memory_stats, the device's real
            # share of the (head-sharded) KV pool, and its share of the
            # model weights, plus the worst-device memory fraction the
            # picker scores
            occ = self.allocator.occupancy
            kv_by_dev = _per_device_bytes(self.kv_cache)
            # only devices this ENGINE occupies (its param/KV shards):
            # a single-chip engine in a multi-device process reports
            # one device, not the process's whole population
            mine = set(self.param_bytes_by_device) | set(kv_by_dev)
            devs: list[dict] = []
            worst = 0.0
            for did, platform, used_d, limit_d in \
                    device_memory_stats_all():
                if mine and did not in mine:
                    continue
                frac = round(used_d / limit_d, 4) if limit_d else 0.0
                worst = max(worst, frac)
                devs.append({
                    "id": did,
                    "platform": platform,
                    "bytes_in_use": used_d,
                    "bytes_limit": limit_d,
                    "memory_frac": frac,
                    "kv_pool_bytes": kv_by_dev.get(did, 0),
                    "kv_bytes_in_use": round(
                        kv_by_dev.get(did, 0) * occ),
                    "kv_occupancy": round(occ, 4),
                    "param_bytes":
                        self.param_bytes_by_device.get(did, 0),
                })
            self.device_stats = devs
            self.stats.device_count = max(1, len(devs))
            self.stats.device_memory_frac_worst = worst
        self.stats.ici_bytes_total = (
            self.ici_bytes_per_token * self.stats.tokens_generated)
        young = self.cfg.migration_young_tokens
        self.stats.migratable_slots = sum(
            1 for s in self._slots
            if s is not None and s.generated >= 1
            and (young <= 0 or s.generated <= young))
        tenants = self._tenant_slots()
        self.stats.tenants_active = len(tenants)
        self.stats.tenant_max_slots = max(tenants.values(), default=0)
        # MoE routing surface (ISSUE 18): scalars derived from the
        # per-expert / per-layer accumulators _fold_moe maintains. The
        # imbalance is hottest-expert / mean — the PR 10 worst-device
        # discipline (an ep-sharded replica steps at its hottest
        # expert's pace), priced by the gateway picker off /state.
        if self._moe:
            placed = float(self._moe_expert_tokens.sum())
            dropped = float(self._moe_layer_drops.sum())
            self.stats.moe_tokens_routed = int(placed)
            self.stats.moe_tokens_dropped = int(dropped)
            self.stats.moe_dropped_frac = round(
                dropped / (placed + dropped), 6) if placed + dropped \
                else 0.0
            mean = placed / max(self._moe_experts, 1)
            self.stats.moe_expert_imbalance = round(
                float(self._moe_expert_tokens.max()) / mean, 4) \
                if mean > 0 else 0.0
        self.stats.spec_accept_rate = (
            self.stats.spec_accepted / self.stats.spec_drafted
            if self.stats.spec_drafted else 0.0)
        if self.prefix_cache is not None:
            self.stats.prefix_cache_evictions = self.prefix_cache.evictions
            self.stats.prefix_pages_resident = (
                self.prefix_cache.resident_entries)
            self.stats.prefix_pages_pinned = (
                self.allocator.pinned_cached_pages)
            hm = (self.stats.prefix_cache_hits
                  + self.stats.prefix_cache_misses)
            self.stats.prefix_cache_hit_rate = (
                self.stats.prefix_cache_hits / hm if hm else 0.0)
        # KV memory hierarchy (ISSUE 11): host-tier occupancy/churn and
        # the resident+spilled chain digest the fleet index polls
        # (throttled — the digest walk is O(resident chains))
        if self.host_tier is not None:
            tier = self.host_tier
            self.stats.kv_spills = tier.spills
            self.stats.kv_revives = tier.revives
            self.stats.kv_spill_evictions = tier.evictions
            self.stats.kv_spilled_pages = tier.count
            self.stats.kv_spill_bytes = tier.bytes_used
            self.stats.kv_host_bytes = tier.max_bytes
        now_d = time.monotonic()
        if self.prefix_cache is not None and now_d >= self._kv_digest_next:
            self._kv_digest_next = now_d + 0.5
            self._refresh_kv_digest()
        # age of the oldest waiting request — the picker's queue-latency
        # term. Peeking the underlying deque is safe here: entries are
        # only appended by other threads, and a request popped between
        # the qsize check and the peek just yields a fresher head.
        try:
            head = self._queue.queue[0]
            self.stats.queue_wait_ms = 1e3 * (
                time.monotonic() - head.enqueued_at)
        except IndexError:
            self.stats.queue_wait_ms = 0.0


def continuation_request(blob: dict,
                         emit: Callable[[int, str | None], None]
                         = lambda t, f: None,
                         trace: Any = None) -> GenRequest:
    """Build the GenRequest that RESUMES a migrated session from an
    export blob (the wire half of migrate_export). The prompt is the
    full token history (original prompt + everything generated at the
    cut); import_state restores the sampling-key/penalty state so the
    resumed stream is byte-identical to a solo-served run. One builder
    shared by the /migrate/import endpoint and the migration tests —
    the wire format has exactly one consumer-side interpretation."""
    sp = blob.get("sampling") or {}
    sampling = SamplingParams(
        temperature=float(sp.get("temperature", 1.0)),
        top_p=float(sp.get("top_p", 1.0)),
        top_k=int(sp.get("top_k", 0)),
        seed=int(sp.get("seed", 0)),
        frequency_penalty=float(sp.get("frequency_penalty", 0.0)),
        presence_penalty=float(sp.get("presence_penalty", 0.0)),
        logit_bias=tuple((int(t), float(b))
                         for t, b in (sp.get("logit_bias") or ())),
    )
    tokens = [int(t) for t in blob["tokens"]]
    return GenRequest(
        prompt=tokens,
        max_tokens=int(blob["max_tokens"]),
        sampling=sampling,
        stop_token_ids=tuple(int(t) for t in
                             (blob.get("stop_token_ids") or ())),
        emit=emit,
        adapter=str(blob.get("adapter", "")),
        tenant=str(blob.get("tenant", "")),
        priority=str(blob.get("priority", "interactive")),
        import_state={
            "orig_prompt_len": int(blob.get("orig_prompt_len",
                                            len(tokens))),
            "generated": int(blob.get("generated", 0)),
            "key_seed": int(blob.get("key_seed", 0)),
            # the pending input token at the cut sat at position m-1 —
            # the resume's first sample must use its key
            "key_counter": len(tokens) - 1,
            # usage metering (ISSUE 20): the meter accumulated by the
            # exporting segment(s) — the resumed slot folds it into its
            # single terminal MeterRecord so a spliced stream meters once
            "meter_carry": blob.get("meter"),
        },
        trace=trace,
    )
