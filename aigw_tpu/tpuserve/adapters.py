"""Adapter serving subsystem: the model zoo's LoRA rows, hot-loaded.

``models/lora.py`` gives the engine batched per-slot adapter math over
stacked ``[n_slots+1, r, d]`` device arrays — this module gives those
arrays a *lifecycle*. The zoo (every adapter the replica can serve) is
registered host-side; only ``n_slots`` adapters are resident on device
at a time, each occupying one row of every stacked tensor. Rows are
managed under the same discipline ``kvcache.py`` uses for KV pages:

- **refcounted**: every live engine slot serving an adapter holds one
  reference to its row; a row is NEVER reassigned while referenced
  (the invariant the adapter property test asserts).
- **LRU-parked**: a row whose refcount drops to zero stays resident
  (revivable for free by the next request for that adapter) until a
  non-resident adapter needs the row — then the least-recently-parked
  row is evicted and rewritten.
- **hot load**: loading scatters the adapter's tensors into the row
  with one jitted dynamic-index row update per tensor — the stacked
  arrays are donated through, so a load is a row-sized write, not a
  stack-sized copy, and it composes with the engine's in-flight decode
  windows through the normal JAX dependency order (no pipeline drain,
  no rebuild). One compiled program per tensor shape regardless of
  which row is written; ``warm()`` pre-compiles them so the first
  adapter admission pays zero XLA compiles.

Row ``n_slots`` (the last row) is the all-zeros base-model row and is
never allocated: base-model requests point there and are bit-exact
base-model output by construction.
"""

from __future__ import annotations

import logging
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from aigw_tpu.models.lora import validate_adapter_params

logger = logging.getLogger(__name__)


class UnknownAdapterError(KeyError):
    """Adapter name not registered in the zoo (→ 404 at the server)."""


class AdapterCapacityError(Exception):
    """Every device row is pinned by a live slot — the request must
    wait for a generation to finish (admission requeues it, exactly
    like KV OutOfPagesError)."""


class AdapterStore:
    """Registry + device residency manager for the stacked LoRA arrays.

    ``register()`` adds adapters to the zoo (host memory only);
    ``acquire()``/``release()`` manage device rows. All registered
    adapters must share tensor keys and shapes (one compiled program
    serves any mix — shape divergence would be a recompile per mix).
    """

    def __init__(self, n_slots: int, dtype: Any = jnp.bfloat16):
        if n_slots < 1:
            raise ValueError("AdapterStore needs at least one row")
        self.n_slots = n_slots
        self.dtype = dtype
        # zoo: name → host param dict (np arrays, template-validated)
        self._zoo: dict[str, dict[str, np.ndarray]] = {}
        self._template: dict[str, tuple] | None = None  # key → shape
        # device residency
        self._row_of: dict[str, int] = {}
        self._name_of: dict[int, str] = {}
        self._refs: dict[int, int] = {}
        # refcount-0 resident rows, insertion-ordered = LRU
        self._parked: dict[int, str] = {}
        self._free: list[int] = list(range(n_slots - 1, -1, -1))
        #: stacked device arrays [n_slots+1, ...]; row n_slots = zeros.
        #: Replaced (donated through) on every load — readers must
        #: fetch fresh per dispatch (the engine's lora_params property).
        self.params: dict[str, jax.Array] = {}
        # monotonic counters (EngineStats / /state surface)
        self.loads = 0
        self.evictions = 0
        self._load_fn = None

    # -- zoo ---------------------------------------------------------------
    def register(self, name: str, adapter: dict) -> None:
        """Add an adapter to the zoo (host-side; no device traffic).
        Validates pairing/rank (models/lora.py) and shape agreement with
        previously registered adapters."""
        validate_adapter_params(adapter, name)
        host = {k: np.asarray(v, np.float32) for k, v in adapter.items()}
        shapes = {k: v.shape for k, v in host.items()}
        if self._template is None:
            self._template = shapes
            self.params = {
                k: jnp.zeros((self.n_slots + 1, *shape), self.dtype)
                for k, shape in shapes.items()
            }
        elif shapes != self._template:
            raise ValueError(
                f"adapter {name!r} tensors {shapes} do not match the "
                f"zoo template {self._template} (all adapters must "
                "target the same modules at the same rank)")
        self._zoo[name] = host

    def names(self) -> tuple[str, ...]:
        return tuple(self._zoo)

    def knows(self, name: str) -> bool:
        return name in self._zoo

    @property
    def base_row(self) -> int:
        return self.n_slots

    # -- telemetry ---------------------------------------------------------
    @property
    def resident_count(self) -> int:
        return len(self._row_of)

    def resident_names(self) -> list[str]:
        return sorted(self._row_of)

    def refcount(self, name: str) -> int:
        row = self._row_of.get(name)
        return self._refs.get(row, 0) if row is not None else 0

    # -- residency ---------------------------------------------------------
    def row_of(self, name: str) -> int:
        """Device row of a RESIDENT adapter (callers hold a reference
        from acquire(); asking for a non-resident name is a caller
        bug — fail loudly, never silently serve the wrong row)."""
        return self._row_of[name]

    def acquire(self, name: str) -> int:
        """Pin ``name``'s row for one live slot, hot-loading it into a
        free (or LRU-evicted) row when not resident. Raises
        UnknownAdapterError / AdapterCapacityError."""
        if name not in self._zoo:
            raise UnknownAdapterError(name)
        row = self._row_of.get(name)
        if row is not None:
            self._refs[row] = self._refs.get(row, 0) + 1
            self._parked.pop(row, None)  # back in active use
            return row
        row = self._pop_row()
        self._load(row, name)
        self._row_of[name] = row
        self._name_of[row] = name
        self._refs[row] = 1
        return row

    def release(self, row: int) -> None:
        """Drop one slot's reference; the last reference parks the row
        in the LRU pool (still resident, revivable for free)."""
        if row == self.base_row:
            return
        name = self._name_of.get(row)
        if name is None:  # defensive: double release must not corrupt
            return
        refs = self._refs.get(row, 1) - 1
        if refs > 0:
            self._refs[row] = refs
            return
        self._refs.pop(row, None)
        self._parked[row] = name

    def _pop_row(self) -> int:
        if self._free:
            return self._free.pop()
        if self._parked:
            row, name = next(iter(self._parked.items()))
            del self._parked[row]
            del self._row_of[name]
            del self._name_of[row]
            self.evictions += 1
            logger.info("adapter %r evicted from row %d", name, row)
            return row
        raise AdapterCapacityError(
            f"all {self.n_slots} adapter rows pinned by live slots")

    # -- device load -------------------------------------------------------
    def _make_load_fn(self):
        def _set_row(stack: jax.Array, row: jax.Array,
                     value: jax.Array) -> jax.Array:
            return stack.at[row].set(value.astype(stack.dtype))

        # donate the stack: a load writes one row in place instead of
        # copying [n_slots+1, ...]; the dynamic row index keeps it ONE
        # compiled program per tensor shape for any destination row.
        # Factory only — the Engine registers the returned callable
        # with its CompileTracker ("adapter_load") and warm() compiles
        # it; declared in analysis/registry.py JIT_WARM_SURFACE (rule
        # jit-registry).
        return jax.jit(_set_row, donate_argnums=(0,))

    def _load(self, row: int, name: str) -> None:
        if self._load_fn is None:
            self._load_fn = self._make_load_fn()
        host = self._zoo[name]
        r = jnp.int32(row)
        for k, v in host.items():
            self.params[k] = self._load_fn(self.params[k], r,
                                           jnp.asarray(v))
        self.loads += 1
        logger.info("adapter %r loaded into row %d", name, row)

    def warm(self) -> None:
        """Pre-compile the per-tensor row-scatter programs by rewriting
        the base row with its own zeros (content no-op) — after this,
        the first hot adapter load adds ZERO XLA compiles."""
        if not self.params:
            return
        if self._load_fn is None:
            self._load_fn = self._make_load_fn()
        r = jnp.int32(self.base_row)
        for k, stack in list(self.params.items()):
            zero = jnp.zeros(stack.shape[1:], np.float32)
            self.params[k] = self._load_fn(stack, r, zero)

    # -- invariants (property-test surface) --------------------------------
    def check_invariants(self) -> None:
        """Bookkeeping consistency: referenced rows are exactly the
        resident-minus-parked rows, no row appears in two pools, and
        the base row is never tracked."""
        resident_rows = set(self._name_of)
        assert resident_rows == set(self._row_of.values())
        assert set(self._refs) | set(self._parked) == resident_rows
        assert not (set(self._refs) & set(self._parked))
        assert not (set(self._free) & resident_rows)
        assert self.base_row not in resident_rows
        assert len(self._free) + len(resident_rows) == self.n_slots
        for row, refs in self._refs.items():
            assert refs > 0, (row, refs)
