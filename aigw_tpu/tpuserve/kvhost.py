"""Host-RAM KV spill tier — the second rung of the KV memory hierarchy.

HBM holds the pages live sequences decode against plus whatever the
prefix cache can keep parked; everything beyond that used to be dropped
on eviction and recomputed on the next hit. This tier catches those
evictions instead: when the refcounted allocator reclaims a parked
cache-registered page under pool pressure, the engine copies the page's
K/V rows device→host and parks them HERE, keyed by the same content
chain hash the prefix cache used. A later prefix hit on a spilled chain
*revives* the pages through the warmed batched import scatters
(tpuserve/engine.py `_import_pages_dev` — the PR 8 migration machinery)
instead of re-prefilling, and the cross-replica fetch endpoint
(`/kv/pages`) serves spilled chains straight from host memory without
touching the device at all.

Discipline:

- **Strict tiering**: the budget holds only NON-resident chains. A
  revive removes the host copy (the page moved back up the hierarchy);
  a re-eviction re-spills it. No entry is ever both resident and
  counted against the host budget.
- **Byte-for-byte**: pages are stored in the pool's native KV dtype
  exactly as exported — a revived chain is bit-identical to the chain
  that was never evicted (property-tested in
  tests/test_kvcache_eviction.py, f32-rig-tested in
  tests/test_kvtier.py).
- **Bounded**: ``max_bytes`` (the ``--kv-host-bytes`` knob) is a hard
  LRU budget. Oversized single pages are refused (counted as
  evictions), never stored.
- **Thread-safe**: spills and revives happen on the engine thread, but
  `/kv/pages` and the `/state` digest read from server threads — every
  operation takes the tier lock.
"""

from __future__ import annotations

import collections
import threading
from typing import Any


def _size(rows: Any) -> int:
    """Byte size of a stored page: np arrays expose nbytes; quantized
    {"q","scale"} pages charge their packed device size (models/kvq);
    plain byte blobs (the property tests' model device) their
    length."""
    from aigw_tpu.models import kvq

    return kvq.page_nbytes(rows)


class HostKVTier:
    """Bounded LRU of chain-hash → one host-side KV page."""

    def __init__(self, max_bytes: int):
        if max_bytes <= 0:
            raise ValueError(f"max_bytes must be > 0 (got {max_bytes})")
        self.max_bytes = int(max_bytes)
        # chain key (bytes) → np page rows; insertion order = LRU
        self._pages: "collections.OrderedDict[bytes, Any]" = (
            collections.OrderedDict()
        )
        self._bytes = 0
        self._lock = threading.Lock()
        #: cumulative pages spilled into the tier
        self.spills = 0
        #: cumulative pages revived out of the tier (take())
        self.revives = 0
        #: pages dropped by the LRU budget (or refused as oversized)
        self.evictions = 0

    # -- capacity ---------------------------------------------------------
    @property
    def count(self) -> int:
        with self._lock:
            return len(self._pages)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    # -- spill ------------------------------------------------------------
    def put(self, key: bytes, rows: Any) -> bool:
        """Spill one page's rows under its chain key. Returns False when
        the page alone exceeds the budget (refused, counted evicted).
        Re-spilling an existing key replaces the entry (refreshing its
        LRU position); LRU entries drop until the budget holds."""
        nbytes = _size(rows)
        with self._lock:
            if nbytes > self.max_bytes:
                self.evictions += 1
                return False
            old = self._pages.pop(key, None)
            if old is not None:
                self._bytes -= _size(old)
            self._pages[key] = rows
            self._bytes += nbytes
            self.spills += 1
            while self._bytes > self.max_bytes:
                _, dropped = self._pages.popitem(last=False)
                self._bytes -= _size(dropped)
                self.evictions += 1
            return True

    # -- lookup / revive --------------------------------------------------
    def contains(self, key: bytes) -> bool:
        """Presence probe; touches the entry (a chain about to be
        revived must not be the next LRU victim of an interleaved
        spill)."""
        with self._lock:
            if key not in self._pages:
                return False
            self._pages.move_to_end(key)
            return True

    def get(self, key: bytes):
        """Peek (cross-replica fetch serving): the page stays in the
        tier — the sibling gets a copy, this replica keeps its rung."""
        with self._lock:
            rows = self._pages.get(key)
            if rows is not None:
                self._pages.move_to_end(key)
            return rows

    def take(self, key: bytes):
        """Revive: remove and return the page's rows (None = miss). The
        chain is moving back into HBM — strict tiering frees the host
        copy."""
        with self._lock:
            rows = self._pages.pop(key, None)
            if rows is not None:
                self._bytes -= _size(rows)
                self.revives += 1
            return rows

    def discard(self, key: bytes) -> None:
        """Drop a stale host copy of a chain that just became resident
        AGAIN through a cold prefill (possible when an earlier chain
        key was budget-dropped, so no revive fired). Content-addressing
        makes the copy harmless, but strict tiering spends the host
        budget only on chains HBM does not hold. Not a revive (nothing
        moved up) and not an eviction (nothing was lost) — uncounted."""
        with self._lock:
            rows = self._pages.pop(key, None)
            if rows is not None:
                self._bytes -= _size(rows)

    def keys(self) -> tuple:
        """Snapshot of resident chain keys (the /state digest's spilled
        half)."""
        with self._lock:
            return tuple(self._pages.keys())
