"""tpuserve HTTP server — the OpenAI-compatible surface over the engine.

Endpoints: /v1/chat/completions (stream + non-stream), /v1/completions,
/v1/embeddings, /tokenize (vLLM-compatible, reference mainlib/main.go:326),
/v1/models, /health, /metrics, and /state — the KV-occupancy/queue-depth
telemetry consumed by the gateway's endpoint picker (the reference's EPP
protocol speaks ext_proc; ours is a plain JSON poll + the same
``x-gateway-destination-endpoint`` contract, internalapi.go:76).

Observability (ISSUE 5): the gateway's ``traceparent`` no longer dies at
the replica hop — each request opens a child span here and the engine
emits lifecycle spans/events under it (queue-wait, admission, prefill,
first-token, decode windows); every request is also recorded in the
in-process flight recorder, served at ``/debug/requests[/{id}]`` with no
tracing backend required, and ``/debug/profile?seconds=N`` captures an
on-demand ``jax.profiler`` trace when enabled. The response carries
``x-aigw-request-id`` so gateway access-log lines join against both.
"""

from __future__ import annotations

import asyncio
import functools
import json
import logging
import os
import tempfile
import time
import uuid
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from aiohttp import web

from aigw_tpu.gateway.costs import TokenUsage, meter_to_tuple
from aigw_tpu.models import llama
from aigw_tpu.models.registry import family_fns, get_model_spec
from aigw_tpu.obs.flight import FlightRecorder, RequestTrace
from aigw_tpu.obs.metrics import (
    GenAIMetrics,
    RequestMetrics,
    render_device_gauges,
    render_engine_gauges,
    render_moe_gauges,
)
from aigw_tpu.obs.tracing import SpanContext, Tracer, genai_attributes
from aigw_tpu.schemas import openai as oai
from aigw_tpu.translate.sse import SSEEvent
from aigw_tpu.translate.structured import (
    JSONSchemaError,
    parse_response_format,
)
from aigw_tpu.tpuserve import constrain
from aigw_tpu.utils.net import set_tcp_nodelay
from aigw_tpu.tpuserve.engine import (
    Engine,
    EngineConfig,
    EngineOverloadedError,
    GenRequest,
    MigrationError,
    continuation_request,
)
from aigw_tpu.tpuserve.kvcache import page_chain_hashes
from aigw_tpu.tpuserve.sampling import SamplingParams
from aigw_tpu.tpuserve.tokenizer import (
    StreamingDecoder,
    apply_chat_template,
    load_tokenizer,
)

logger = logging.getLogger(__name__)


def encode_wire_page(d) -> dict:
    """Host-side KV page → JSON-able wire dict. Native pages keep the
    PR 8 f32 wire ({b64, shape}); quantized {"q","scale"} pages (ISSUE
    13) add ``dtype`` + ``scale_b64``/``scale_shape`` and travel
    BIT-exactly at native dtype + scales — no re-rounding through f32.
    (int4 serializes one element per byte on the wire — the JSON
    transport is not the packed HBM layout.)"""
    import base64

    if isinstance(d, dict):
        q = np.ascontiguousarray(d["q"])
        s = np.ascontiguousarray(d["scale"], dtype=np.float32)
        return {
            "b64": base64.b64encode(q.tobytes()).decode(),
            "shape": list(q.shape),
            "dtype": str(q.dtype),
            "scale_b64": base64.b64encode(s.tobytes()).decode(),
            "scale_shape": list(s.shape),
        }
    arr = np.asarray(d, np.float32)
    return {"b64": base64.b64encode(arr.tobytes()).decode(),
            "shape": list(arr.shape)}


def decode_wire_page(p: dict):
    """Inverse of :func:`encode_wire_page` (raises KeyError/ValueError
    on malformed input — callers map that to 400)."""
    import base64

    dt = p.get("dtype")
    if dt:
        import ml_dtypes

        np_dt = {"int8": np.int8, "int4": ml_dtypes.int4}[str(dt)]
        q = np.frombuffer(base64.b64decode(p["b64"]),
                          np_dt).reshape(p["shape"])
        scale = np.frombuffer(base64.b64decode(p["scale_b64"]),
                              np.float32).reshape(p["scale_shape"])
        return {"q": q, "scale": scale}
    return np.frombuffer(base64.b64decode(p["b64"]),
                         np.float32).reshape(p["shape"])

#: tenant key header (set by clients or derived/relayed by the gateway
#: from the model's adapter suffix) — feeds the engine's fairness guard
#: and joins the gateway's per-tenant cost/quota accounting
TENANT_HEADER = "x-aigw-tenant"

#: priority class header (ISSUE 19): ``batch`` rides the engine's
#: offline tier — admitted only into slots interactive doesn't want,
#: preempted (window shrink, then host-side park) under interactive
#: pressure, and NEVER 429-shed (the engine's batch queue is
#: unbounded). Anything else (absent, "", "interactive") is the
#: default interactive class.
PRIORITY_HEADER = "x-aigw-priority"

#: sibling replicas ("host:port", comma-separated) the gateway believes
#: hold KV for this request's prompt chain (ISSUE 11): on a prefix miss
#: the server fetches the missing leading pages from them over
#: POST /kv/pages (the PR 8 byte-identical page wire) and imports them
#: as cached chains before admission — Mooncake-style KV-centric
#: serving. Absent/empty = no fetch (cold prefill as before).
KV_PEERS_HEADER = "x-aigw-kv-peers"

#: response header: the first page-chain hash of the served prompt —
#: the gateway learns (prefix-head → chain) from it and prices
#: fleet-hit locality / orders fetch peers on later requests sharing
#: the same prefix head
KV_CHAIN_HEADER = "x-aigw-kv-chain"

#: fleet-fetch bounds: peers tried per request, pages per fetch, and
#: the per-peer HTTP budget — a slow sibling must delay admission by a
#: bounded amount, never hang it (the cold prefill path is always the
#: fallback)
KV_PEERS_MAX = 3
KV_FETCH_MAX_PAGES = 64
KV_FETCH_TIMEOUT_S = 10.0


def _push_all(decoder: StreamingDecoder, toks: list[int]) -> list[str]:
    """Detokenize a burst (runs on the tokenizer pool: a K-token decode
    window lands K tokens at once, and their detokenization must not
    stall every other connection's IO on the event loop)."""
    return [decoder.push(t) for t in toks]


@functools.lru_cache(maxsize=1)
def _device_topology_cached() -> tuple[str, tuple[int, ...]]:
    try:
        d = jax.devices()[0]
    except Exception:  # backend init failure must not break /state
        return "", ()
    # TPU devices expose slice_index on multislice deployments and
    # coords (the chip's position in the ICI torus); CPU/GPU have
    # neither — they report an empty slice, and the picker falls back
    # to the statically configured slice label.
    slice_idx = getattr(d, "slice_index", None)
    coords = getattr(d, "coords", None)
    slice_name = (
        f"{d.platform}-slice-{slice_idx}" if slice_idx is not None else ""
    )
    return slice_name, tuple(coords) if coords is not None else ()


def device_topology() -> dict[str, Any]:
    """ICI topology of this server's devices for /state: the slice the
    chips belong to and the first chip's torus coords, straight from
    jax.devices(). The gateway picker keys its same-slice preference
    (KV/ICI locality on failover) on the ``slice`` field."""
    slice_name, coords = _device_topology_cached()
    return {"slice": slice_name, "device_coords": list(coords)}


def _find_stop(text: str, stop_strs: list[str]) -> int | None:
    """Earliest index where a stop sequence begins, or None."""
    best = None
    for s in stop_strs:
        if not s:
            continue
        i = text.find(s)
        if i >= 0 and (best is None or i < best):
            best = i
    return best


class TPUServeServer:
    def __init__(
        self,
        model: str,
        engine_cfg: EngineConfig,
        metrics: GenAIMetrics | None = None,
        tp: int = 1,
        ep: int = 1,  # expert parallel (MoE families)
        sp: int = 1,  # sequence parallel (ring-attention long prefill)
        quantize: str = "",  # "" | "int8" | "int4" (llama-family only)
        # name → adapter param dict (un-stacked [r,in]/[out,r] per target);
        # served when a request's model == "<base>:<adapter>" or the bare
        # adapter name. The dict is the ZOO — only lora_slots adapters
        # are device-resident at a time (tpuserve/adapters.py hot
        # load/evict); the rest load on first request.
        lora_adapters: dict[str, dict] | None = None,
        # device rows for resident adapters; 0 = one row per registered
        # adapter (everything fits, loads are lazy, no eviction churn)
        lora_slots: int = 0,
        # per-tenant in-flight decode-slot cap (engine fairness guard);
        # 0 = off
        tenant_slot_cap: int = 0,
        tracer: Tracer | None = None,
        # flight recorder ring size (per-request lifecycle timelines on
        # /debug/requests — always on; the entries are compact)
        flight_entries: int = 256,
        # /debug/profile?seconds=N jax.profiler capture — opt-in: a
        # profiler endpoint on the data port is a DoS/inspection surface
        enable_profile_endpoint: bool = False,
    ):
        self.model_name = model
        spec = get_model_spec(model)
        self.fns = family_fns(spec.family)
        self.model_cfg = spec.config
        self.tokenizer = load_tokenizer(spec.tokenizer)
        self.chat_template = spec.chat_template
        self.metrics = metrics or GenAIMetrics()
        # env-driven (OTEL_*); service name distinguishes replica spans
        # from the gateway's in a shared collector
        self.tracer = tracer or Tracer(
            service_name=os.environ.get("OTEL_SERVICE_NAME", "")
            or "tpuserve")
        self.flight = FlightRecorder(capacity=flight_entries)
        self._enable_profile = enable_profile_endpoint
        self._profile_lock = asyncio.Lock()
        # replica identity for the gateway's fleet aggregator (ISSUE
        # 12): a fresh id per process boot — the same address with a
        # NEW id is a restart (counters reset), which the fleet health
        # ring records as an event instead of mistaking the zeroed
        # counters for a quiet replica
        self.replica_id = uuid.uuid4().hex[:16]
        self._started_at = time.time()
        # graceful drain (ISSUE 14): when set, NEW generation work is
        # refused with 503+Retry-After while live slots finish or
        # migrate off; /state reports it so the gateway's fleet health
        # machine (and its controller) see the drain on the next poll.
        # Flipped by POST /drain (the controller's retire protocol) or
        # the SIGTERM/SIGINT handler (install_signal_drain).
        self.draining = False

        mesh = None
        if tp > 1 or ep > 1 or sp > 1:
            from aigw_tpu.parallel import MeshSpec, make_mesh

            if ep > 1:
                n_experts = getattr(spec.config, "n_experts", 0)
                if not n_experts:
                    raise ValueError(
                        f"--ep requires a MoE model family; {model!r} "
                        "has no experts")
                if n_experts % ep != 0:
                    raise ValueError(
                        f"n_experts {n_experts} not divisible by ep={ep}")
            if tp > 1 and spec.config.n_kv_heads % tp != 0:
                raise ValueError(
                    f"n_kv_heads {spec.config.n_kv_heads} not divisible "
                    f"by tp={tp}")
            if sp > 1 and self.fns.prefill_sp is None:
                raise ValueError(
                    f"--sp requires a model family with a "
                    f"sequence-parallel prefill; {spec.family!r} has none "
                    "(devices on the sp axis would sit idle)")
            mesh = make_mesh(MeshSpec(dp=1, tp=tp, sp=sp, ep=ep))
            logger.info(
                "parallel serving: tp=%d ep=%d sp=%d over %s", tp, ep, sp,
                [str(d) for d in mesh.devices.flat])
        if quantize and quantize not in ("int8", "int4"):
            raise ValueError(f"unknown quantization {quantize!r}")
        if quantize and spec.family not in ("llama", "mixtral"):
            raise ValueError(
                "weight quantization supports the llama and mixtral "
                "families"
            )
        params = self._load_params(spec)
        if quantize:
            from aigw_tpu.models.quant import quantize_params

            params = quantize_params(params, consume=True,
                                     mode=quantize)
            logger.info("weights quantized to %s (W%sA16)", quantize,
                        quantize[-1])
        adapter_store = None
        if lora_adapters:
            if spec.family != "llama":
                raise ValueError("LoRA serving supports the llama family")
            from aigw_tpu.tpuserve.adapters import AdapterStore

            adapter_store = AdapterStore(
                n_slots=lora_slots or len(lora_adapters))
            for name, adapter in lora_adapters.items():
                adapter_store.register(name, adapter)
        self.adapter_store = adapter_store
        engine_cfg.tenant_slot_cap = (
            tenant_slot_cap or engine_cfg.tenant_slot_cap)
        self.engine = Engine(
            params,
            self.model_cfg,
            engine_cfg,
            eos_token_ids=(self.tokenizer.eos_id,),
            mesh=mesh,
            fns=self.fns,
            adapter_store=adapter_store,
        )
        # jitted embeddings path (bucketed like prefill)
        hidden = self.fns.hidden_states
        self._hidden_fn = jax.jit(
            lambda p, t, l: hidden(p, self.model_cfg, t, l)
        )

        # host-overlap: encode/template/decode run on a worker pool, not
        # the event loop — a long prompt's tokenization (or a big final
        # detokenize) must not stall every other connection's IO. The HF
        # tokenizer is native and releases the GIL, so this is true
        # parallelism for real checkpoints.
        from concurrent.futures import ThreadPoolExecutor

        self._tok_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="tpuserve-tok"
        )

        # live streaming sessions by response id — the lookup surface of
        # the migration export endpoint (ISSUE 8): the gateway quotes
        # the x-aigw-request-id it already relays
        self._live: dict[str, tuple[GenRequest, dict]] = {}

        # lazy aiohttp session for cross-replica /kv/pages fetches
        # (ISSUE 11) — one pooled session per server, closed on cleanup
        self._kv_session = None

        # offline batch tier (ISSUE 19): in-memory file store (JSONL in,
        # JSONL out) + batch objects and their runner tasks. Batch lines
        # run through the normal submit path at priority="batch" — the
        # engine's unbounded batch queue absorbs any backlog, so the
        # tier never 429-sheds.
        self._files: dict[str, bytes] = {}
        self._batches: dict[str, dict] = {}
        self._batch_lines: dict[str, list] = {}
        self._batch_tasks: dict[str, asyncio.Task] = {}
        self._batch_live: dict[str, list[GenRequest]] = {}

        # body cap sized for /migrate/import: a migrated page chain is
        # megabytes of KV by design (page_bytes × pages on the wire)
        self.app = web.Application(client_max_size=256 * 1024 * 1024)
        # callers holding only the AppRunner (run_tpuserve) reach the
        # server through the app, e.g. to install the drain handler
        self.app["tpuserve_server"] = self
        self.app.router.add_post("/v1/chat/completions", self._chat)
        self.app.router.add_post("/v1/completions", self._completions)
        self.app.router.add_post("/v1/embeddings", self._embeddings)
        self.app.router.add_post("/tokenize", self._tokenize)
        self.app.router.add_get("/v1/models", self._models)
        self.app.router.add_get("/health", self._health)
        self.app.router.add_get("/state", self._state)
        self.app.router.add_get("/metrics", self._metrics)
        self.app.router.add_post("/drain", self._drain)
        # offline batch tier (ISSUE 19): file upload + batch lifecycle
        self.app.router.add_post("/v1/files", self._file_upload)
        self.app.router.add_get("/v1/files/{fid}/content",
                                self._file_content)
        self.app.router.add_post("/v1/batches", self._batch_create)
        self.app.router.add_get("/v1/batches", self._batch_list)
        self.app.router.add_get("/v1/batches/{bid}", self._batch_get)
        self.app.router.add_post("/v1/batches/{bid}/cancel",
                                 self._batch_cancel)
        self.app.router.add_post("/migrate/export", self._migrate_export)
        self.app.router.add_post("/migrate/import", self._migrate_import)
        self.app.router.add_post("/kv/pages", self._kv_pages)
        self.app.router.add_get("/debug/requests", self._debug_requests)
        self.app.router.add_get("/debug/requests/{rid}",
                                self._debug_request)
        self.app.router.add_get("/debug/profile", self._debug_profile)
        self.app.on_startup.append(self._on_start)
        self.app.on_cleanup.append(self._on_stop)

    def _load_params(self, spec) -> dict[str, jax.Array]:
        if spec.weights == "random":
            logger.info("initializing random weights for %s", spec.name)
            return self.fns.init_params(jax.random.PRNGKey(0), self.model_cfg)
        if spec.weights.startswith("orbax:"):
            from aigw_tpu.models.checkpoint import restore_checkpoint

            path = spec.weights[len("orbax:") :]
            logger.info("restoring orbax checkpoint %s", path)
            like = jax.eval_shape(
                lambda: self.fns.init_params(jax.random.PRNGKey(0),
                                             self.model_cfg)
            )
            return restore_checkpoint(path, like)
        raise ValueError(f"unsupported weight source {spec.weights}")

    @property
    def adapter_names(self) -> tuple[str, ...]:
        """The served zoo (registered adapters, resident or not)."""
        if self.adapter_store is None:
            return ()
        return self.adapter_store.names()

    def _resolve_adapter(self, model: str) -> str:
        """`<base>:<adapter>` or bare adapter name → adapter name.
        Raises SchemaError for an unknown colon-suffixed adapter (a typo
        must not silently serve base-model output)."""
        if model.startswith(self.model_name + ":"):
            cand = model[len(self.model_name) + 1 :]
            if cand not in self.adapter_names:
                raise oai.SchemaError(
                    f"unknown LoRA adapter {cand!r}; loaded: "
                    f"{sorted(self.adapter_names)}"
                )
            return cand
        return model if model in self.adapter_names else ""

    async def _on_start(self, _app) -> None:
        # compile the decode program off the request path — and BEFORE
        # the engine loop exists: warmup donates kv_cache through
        # dozens of jit calls, and a live engine thread reading
        # self.kv_cache between a donated dispatch and its reassignment
        # (the idle tick's _refresh_stats does exactly that) hits a
        # deleted array and kills the loop. The startup hook runs
        # before the listener accepts, so nothing is serving yet either
        # way; to_thread only keeps the event loop's signal handling
        # live during the (long) compile.
        await asyncio.to_thread(self.engine.warmup)
        self.engine.start()

    async def _on_stop(self, _app) -> None:
        for task in self._batch_tasks.values():
            task.cancel()
        if self._kv_session is not None:
            await self._kv_session.close()
            self._kv_session = None
        self.engine.stop()
        self._tok_pool.shutdown(wait=False)

    # -- helpers ----------------------------------------------------------
    def _check_logprobs(self, body: dict[str, Any]) -> int:
        """Request logprobs knobs → top-k alternates to return per token
        (-1 = logprobs off, 0 = chosen-token only). Raises SchemaError
        (→400) on unservable asks. Two request dialects (OpenAI parity):
        chat sends `logprobs: bool` + `top_logprobs: int`; legacy
        /v1/completions sends `logprobs: int` (the alternate count,
        0 meaning chosen-only). Caps: min(server --logprobs, 20)."""
        raw = body.get("logprobs")
        try:
            if isinstance(raw, bool) or raw is None:
                want = bool(raw)
                top_n = int(body.get("top_logprobs") or 0)
                if top_n and not want:
                    raise oai.SchemaError(
                        "top_logprobs requires logprobs: true")
            else:  # legacy integer form
                want = True
                top_n = int(raw)
        except (TypeError, ValueError):
            raise oai.SchemaError(
                "logprobs must be a boolean (chat) or integer (legacy); "
                "top_logprobs must be an integer") from None
        if top_n < 0:
            raise oai.SchemaError("logprobs count must be >= 0")
        if not want:
            return -1
        cap = self.engine.cfg.logprobs_topk
        if cap <= 0:
            raise oai.SchemaError(
                "this server was started without --logprobs; "
                "per-token logprobs are unavailable")
        if top_n > min(cap, 20):
            raise oai.SchemaError(
                f"top_logprobs {top_n} exceeds the served maximum "
                f"{min(cap, 20)}")
        return top_n

    def _check_constraints(
        self, body: dict[str, Any], chat: bool, lp_top_n: int, n: int,
    ) -> tuple[Any, dict[str, Any] | None]:
        """Grammar-constrained decoding intake (ISSUE 9): normalize
        ``response_format`` + ``tools``/``tool_choice`` into a compiled
        TokenFSM (or None) and a response-assembly mode. Every
        unsupported or malformed ask raises oai.SchemaError → a clear
        400 — never the old silent free-text 200."""
        try:
            rf = parse_response_format(body)
        except JSONSchemaError as e:
            raise oai.SchemaError(str(e)) from None
        if rf is not None and rf.kind == "text":
            rf = None
        tools = body.get("tools")
        choice = body.get("tool_choice")
        tools_active = bool(tools) and choice != "none"
        if rf is None and not tools_active:
            return None, None
        if not chat:
            raise oai.SchemaError(
                "response_format and tools are only supported on "
                "/v1/chat/completions")
        if not self.engine.cfg.constrained_decoding:
            raise oai.SchemaError(
                "this server was started with --no-constrained-decoding; "
                "response_format json modes and tool calling are "
                "unavailable")
        if lp_top_n >= 0:
            raise oai.SchemaError(
                "logprobs cannot be combined with response_format json "
                "modes or tools (the grammar mask reshapes the "
                "distribution the logprobs would describe)")
        if rf is not None and tools_active:
            raise oai.SchemaError(
                "response_format json modes cannot be combined with "
                "tools on this backend; send one or the other")
        eos = (self.tokenizer.eos_id,)
        V = self.model_cfg.vocab_size
        try:
            if tools_active:
                if n > 1:
                    raise oai.SchemaError(
                        "n > 1 is not supported with tools on this "
                        "backend")
                specs = constrain.parse_tools(tools)
                names = [nm for nm, _s in specs]
                named = ""
                if isinstance(choice, dict):
                    named = str(choice["function"]["name"])
                    if named not in names:
                        raise oai.SchemaError(
                            f"tool_choice names unknown tool {named!r}; "
                            f"tools declare {names}")
                    specs = [t for t in specs if t[0] == named]
                mode = ("named" if named
                        else "required" if choice == "required"
                        else "auto")
                if mode == "auto":
                    # unconstrained generation; the server detects a
                    # tool-call envelope in the output stream (a
                    # grammar that admits ALL text would mask nothing)
                    return None, {"mode": "tool", "choice": "auto",
                                  "names": names}
                fsm = constrain.compile_constraint(
                    self.tokenizer, V, eos, constrain.spec_for_tools(specs))
                return fsm, {"mode": "tool", "choice": mode,
                             "names": [t[0] for t in specs]}
            if rf.kind == "json_schema" and rf.schema is None:
                raise oai.SchemaError(
                    "response_format.json_schema.schema is required for "
                    "constrained decoding")
            fsm = constrain.compile_constraint(
                self.tokenizer, V, eos,
                constrain.spec_for_response_format(rf.kind, rf.schema))
            return fsm, {"mode": "json"}
        except (JSONSchemaError,
                constrain.UnsupportedConstraintError) as e:
            raise oai.SchemaError(str(e)) from None

    def _prefix_hashes_for(self, prompt: list[int]) -> list | None:
        """Roll the prompt's page-chain prefix hashes at the engine's
        page size — called on the tokenizer pool right after encode, so
        the engine's prefix-cache lookup costs no extra prompt pass on
        the admission thread."""
        if self.engine.prefix_cache is None:
            return None
        return page_chain_hashes(prompt, self.engine.cfg.page_size)

    @staticmethod
    def _kv_chain_header(prefix_hashes: list | None) -> dict[str, str]:
        """x-aigw-kv-chain response header (ISSUE 11): the prompt's
        first page-chain hash. The gateway learns (prefix-head → chain)
        from it — its fleet index then knows WHICH chain later requests
        with the same prefix head need, pricing fleet-hit locality into
        the picker and ordering fetch peers. Empty dict for prompts
        without a full page (nothing shareable)."""
        if not prefix_hashes:
            return {}
        return {KV_CHAIN_HEADER: prefix_hashes[0].hex()}

    def _encode_chat(self, msgs) -> tuple[list[int], list | None]:
        """Template+encode a chat AND roll its prefix hashes (one pool
        job — the hash pass rides the encode's executor hop)."""
        prompt = apply_chat_template(msgs, self.tokenizer,
                                     self.chat_template)
        return prompt, self._prefix_hashes_for(prompt)

    def _encode_text(self, text: str) -> tuple[list[int], list | None]:
        prompt = [self.tokenizer.bos_id] + self.tokenizer.encode(text)
        return prompt, self._prefix_hashes_for(prompt)

    def _submit(self, prompt: list[int], body: dict[str, Any],
                lp_top_n: int = -1, prefix_hashes: list | None = None,
                trace: RequestTrace | None = None, tenant: str = "",
                constraint: Any = None, priority: str = "interactive"):
        """Submit to the engine; returns (queue, req, meter_box) — the
        queue yields (token_id, finish_reason, lp) tuples, lp is None
        without logprobs, else (chosen_logprob, [(top_id, top_logprob)]).
        ``lp_top_n`` is the already-validated _check_logprobs value
        (validated once per request; >= 0 attaches logprobs).

        ``meter_box`` is a plain dict the engine fills with the
        request's MeterRecord strictly BEFORE posting the terminal emit
        (same engine thread, same loop.call_soon_threadsafe FIFO), so a
        consumer that dequeued the finish item reads a complete box —
        the engine-truth usage the response's ``aigw_meter`` carries."""
        loop = asyncio.get_running_loop()
        out: asyncio.Queue = asyncio.Queue()
        meter_box: dict[str, Any] = {}

        def emit(tok: int, finish: str | None) -> None:
            loop.call_soon_threadsafe(out.put_nowait, (tok, finish, None))

        def emit_lp(tok: int, finish: str | None, chosen, top) -> None:
            lp = None if chosen is None else (chosen, top)
            loop.call_soon_threadsafe(out.put_nowait, (tok, finish, lp))

        max_tokens = int(
            body.get("max_completion_tokens") or body.get("max_tokens") or 256
        )
        stop_ids: tuple[int, ...] = ()
        adapter = self._resolve_adapter(str(body.get("model", "")))
        req = GenRequest(
            prompt=prompt,
            max_tokens=max_tokens,
            sampling=SamplingParams.from_request(body),
            stop_token_ids=stop_ids,
            emit=emit,
            emit_lp=emit_lp if lp_top_n >= 0 else None,
            adapter=adapter,
            # a tenant header wins; adapter-suffixed traffic without one
            # defaults to per-adapter tenancy (each adapter ≈ a tenant)
            tenant=tenant or adapter,
            priority=priority,
            prefix_hashes=prefix_hashes,
            constraint=constraint,
            trace=trace,
            meter_sink=meter_box.update,
        )
        self.engine.submit(req)
        return out, req, meter_box

    def _usage_from_meter(self, n_prompt: int, n_out: int,
                          box: dict[str, Any] | None) -> TokenUsage:
        """Response usage from the stream-observed counts plus the
        engine's MeterRecord: cached_tokens is the prefix-cache reuse
        the engine actually skipped (satellite: the gateway reads
        cached_input_tokens off self-hosted responses at last), and the
        record itself rides ``usage.aigw_meter``. An empty box (stream
        ended before its record — e.g. a stop-string cancel races the
        engine reap) degrades to plain counts."""
        if not box:
            return TokenUsage(input_tokens=n_prompt, output_tokens=n_out,
                              total_tokens=n_prompt + n_out)
        return TokenUsage(
            input_tokens=n_prompt, output_tokens=n_out,
            total_tokens=n_prompt + n_out,
            cached_input_tokens=int(box.get("prefix_reused", 0) or 0),
            meter=meter_to_tuple(box),
        )

    @staticmethod
    def _merge_meter_boxes(boxes: list[dict]) -> dict[str, Any]:
        """Field-wise sum of the n>1 fan-out's per-choice MeterRecords:
        n choices are n engine requests and n records; the response's
        single usage object carries their total (numeric fields summed,
        identity fields from the first record)."""
        merged: dict[str, Any] = {}
        for b in boxes:
            if not b:
                continue
            for k, v in b.items():
                if k == "schema":
                    merged[k] = v
                elif isinstance(v, bool) or not isinstance(v, (int, float)):
                    merged.setdefault(k, v)
                else:
                    merged[k] = round(merged.get(k, 0) + v, 6)
        return merged

    def _begin_trace(
        self, request: web.Request, rid: str, model: str,
        prompt: list[int], body: dict[str, Any], stream: bool, chat: bool,
    ) -> RequestTrace:
        """Open the replica's request span (child of the caller's trace
        context when a ``traceparent``/B3 header arrived — the gateway
        injects one) and the flight-recorder entry. With tracing
        disabled the caller's trace id is still recorded on the entry,
        so /debug/requests joins against external traces either way."""
        headers = {k.lower(): v for k, v in request.headers.items()}
        parent = self.tracer.propagators.extract(headers)
        span = None
        if self.tracer.enabled:
            op = "chat" if chat else "text_completion"
            span = self.tracer.start_span(f"tpuserve.{op} {model}",
                                          parent)
            span.attributes.update(genai_attributes(
                operation=op, request_model=model,
                response_model=self.model_name, backend="tpuserve",
                streaming=stream))
            span.set("tpuserve.request_id", rid)
        entry = self.flight.begin(
            rid, model=model, prompt_tokens=len(prompt),
            max_tokens=int(body.get("max_completion_tokens")
                           or body.get("max_tokens") or 256),
            stream=stream,
            trace_id=(span.context.trace_id if span is not None
                      else parent.trace_id if parent is not None else ""),
            span_id=(span.context.span_id if span is not None else ""),
        )
        return RequestTrace(entry=entry, tracer=self.tracer, span=span)

    def _end_trace(self, trace: RequestTrace, finish: str, n_out: int,
                   n_prompt: int = 0, error: str = "") -> None:
        self._live.pop(trace.entry.rid, None)  # no longer exportable
        self.flight.finish(trace.entry, finish, n_out)
        span = trace.span
        if span is not None:
            span.set("gen_ai.usage.input_tokens", n_prompt)
            span.set("gen_ai.usage.output_tokens", n_out)
            span.set("tpuserve.finish_reason", finish)
            if error:
                span.record_error(error)
            span.end()

    @staticmethod
    def _legacy_logprobs(entries: list[dict[str, Any]]) -> dict[str, Any]:
        """OpenAI legacy /v1/completions logprobs shape from the chat
        content entries (single source for all three response paths)."""
        return {
            "tokens": [e["token"] for e in entries],
            "token_logprobs": [e["logprob"] for e in entries],
            "top_logprobs": [
                {t["token"]: t["logprob"] for t in e["top_logprobs"]}
                for e in entries],
        }

    def _lp_entry(self, piece: str, lp, top_n: int) -> dict[str, Any]:
        """One OpenAI logprobs content entry for an emitted token."""
        chosen, top = lp
        entry: dict[str, Any] = {
            "token": piece,
            "logprob": float(chosen),
            "bytes": list(piece.encode("utf-8")),
        }
        tops = []
        for tid, tval in (top or [])[:top_n]:
            ttext = self.tokenizer.decode([int(tid)])
            tops.append({"token": ttext, "logprob": float(tval),
                         "bytes": list(ttext.encode("utf-8"))})
        entry["top_logprobs"] = tops
        return entry

    # -- endpoints --------------------------------------------------------
    async def _chat(self, request: web.Request) -> web.StreamResponse:
        try:
            body = oai.parse_json_body(await request.read())
            oai.validate_chat_request(body)
        except oai.SchemaError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        msgs = body["messages"]
        if self._small_text(msgs):
            # first-token fast path: a short prompt's template+encode is
            # microseconds — the executor round-trip would cost more
            # than it hides AND spread a burst's submits across extra
            # event-loop turns (admission coalescing then waits on the
            # stragglers). Long prompts keep the pool hop. Both paths
            # also roll the prompt's prefix-cache chain hashes here, so
            # engine admission never re-reads the prompt to probe.
            prompt, hashes = self._encode_chat(msgs)
        else:
            prompt, hashes = await self._off(self._encode_chat, msgs)
        return await self._generate(request, body, prompt, chat=True,
                                    prefix_hashes=hashes)

    #: request text below this many chars tokenizes inline on the event
    #: loop (HF tokenizer throughput is ~MB/s; 4KiB is ~ms)
    _INLINE_TOKENIZE_CHARS = 4096

    @classmethod
    def _small_text(cls, msgs) -> bool:
        total = 0
        for m in msgs if isinstance(msgs, list) else [msgs]:
            content = m.get("content") if isinstance(m, dict) else m
            total += len(content) if isinstance(content, str) else \
                len(str(content))
            if total >= cls._INLINE_TOKENIZE_CHARS:
                return False
        return True

    async def _off(self, fn, *args):
        """Run a tokenization-bound callable off the event loop."""
        return await asyncio.get_running_loop().run_in_executor(
            self._tok_pool, fn, *args
        )

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = oai.parse_json_body(await request.read())
            oai.request_model(body)
        except oai.SchemaError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        prompt_text = body.get("prompt", "")
        if isinstance(prompt_text, list):
            prompt_text = "".join(prompt_text)
        if len(prompt_text) < self._INLINE_TOKENIZE_CHARS:
            prompt, hashes = self._encode_text(prompt_text)
        else:
            prompt, hashes = await self._off(self._encode_text,
                                             prompt_text)
        return await self._generate(request, body, prompt, chat=False,
                                    prefix_hashes=hashes)

    async def _generate(
        self,
        request: web.Request,
        body: dict[str, Any],
        prompt: list[int],
        chat: bool,
        prefix_hashes: list | None = None,
    ) -> web.StreamResponse:
        if self.draining:
            # graceful drain (ISSUE 14): no NEW sessions while retiring
            # — live ones keep streaming below until they finish or the
            # gateway migrates them off
            return self._drain_refusal()
        stream = bool(body.get("stream", False))
        try:
            # logprobs knobs validate to a client 400 up front — every
            # branch below (incl. n>1) relies on it (the SchemaError
            # catch around _submit is reserved for unknown-adapter → 404)
            lp_top_n = self._check_logprobs(body)
        except oai.SchemaError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        tenant = request.headers.get(TENANT_HEADER, "")
        # priority class (ISSUE 19): "batch" rides the engine's offline
        # tier (never 429-shed — its queue is unbounded); anything else
        # is interactive
        priority = ("batch"
                    if request.headers.get(PRIORITY_HEADER, "") == "batch"
                    else "interactive")
        n = int(body.get("n") or 1)
        try:
            # grammar-constrained decoding intake (ISSUE 9): malformed
            # or unsupported response_format/tools asks 400 here — the
            # old behavior (silently serving free text with a 200) is
            # gone on every path below
            constraint, cmode = self._check_constraints(
                body, chat, lp_top_n, n)
        except oai.SchemaError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        # fleet KV fetch (ISSUE 11): named siblings may hold this
        # prompt's chain — import their pages before admission so the
        # prefill becomes a resume (covers the n>1 fan-out too: the
        # shared prompt is fetched once)
        await self._maybe_fleet_fetch(request, prompt, prefix_hashes)
        if n > 1:
            if n > self.engine.cfg.max_batch_size:
                return web.Response(
                    status=400,
                    body=oai.error_body(
                        f"n={n} exceeds max_batch_size "
                        f"{self.engine.cfg.max_batch_size}"),
                    content_type="application/json")
            if stream:
                return await self._generate_n_stream(
                    request, body, prompt, chat, n, lp_top_n,
                    prefix_hashes, tenant, constraint, priority)
            return await self._generate_n(body, prompt, chat, n,
                                          lp_top_n, prefix_hashes,
                                          tenant, constraint, priority)
        include_usage = oai.include_stream_usage(body)
        rid = (
            f"chatcmpl-{uuid.uuid4().hex[:24]}"
            if chat
            else f"cmpl-{uuid.uuid4().hex[:24]}"
        )
        created = int(time.time())
        rm = RequestMetrics(
            metrics=self.metrics,
            operation="chat" if chat else "text_completion",
            provider="tpuserve",
            request_model=body.get("model", self.model_name),
            response_model=self.model_name,
        )
        stops = body.get("stop")
        stop_strs: list[str] = (
            [stops] if isinstance(stops, str) else list(stops or [])
        )
        trace = self._begin_trace(request, rid,
                                  str(body.get("model", self.model_name)),
                                  prompt, body, stream, chat)
        try:
            out, gen_req, meter_box = self._submit(
                prompt, body, lp_top_n, prefix_hashes, trace, tenant,
                constraint, priority)
        except EngineOverloadedError as e:
            self._end_trace(trace, "rejected", 0, len(prompt),
                            error=str(e))
            return web.Response(
                status=429,
                body=oai.error_body(str(e), type_="rate_limit_error"),
                headers={"retry-after": "1"},
                content_type="application/json")
        except oai.SchemaError as e:
            self._end_trace(trace, "rejected", 0, len(prompt),
                            error=str(e))
            return web.Response(
                status=404,
                body=oai.error_body(str(e), type_="model_not_found"),
                content_type="application/json")
        except ValueError as e:
            self._end_trace(trace, "rejected", 0, len(prompt),
                            error=str(e))
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        # exportable until a terminal _end_trace: the gateway can hand
        # this session to a decode replica via POST /migrate/export
        # (streaming only — a buffered response has nothing to splice;
        # constrained/tool sessions carry FSM or detector state no wire
        # blob restores, so they stay put)
        if stream and lp_top_n < 0 and constraint is None \
                and cmode is None:
            self._live[rid] = (gen_req, {
                "response_id": rid,
                "model": self.model_name,
                "created": created,
                "chat": chat,
                "include_usage": include_usage,
                "stop_strs": stop_strs,
            })

        n_prompt = len(prompt)
        want_lp = lp_top_n >= 0
        if not stream:
            try:
                text, n_out, finish, lp_content = await self._collect(
                    out, stop_strs, lp_top_n)
            except asyncio.CancelledError:
                gen_req.cancelled.set()
                self._end_trace(trace, "cancelled", 0, n_prompt)
                raise
            usage = self._usage_from_meter(n_prompt, n_out, meter_box)
            rm.finish(usage, error_type="engine" if finish == "error"
                      else "")
            self._end_trace(trace, finish, n_out, n_prompt,
                            error="engine failure"
                            if finish == "error" else "")
            if finish == "error":
                return web.Response(
                    status=500,
                    body=oai.error_body("engine failure", type_="server_error"),
                    content_type="application/json",
                )
            tool_calls = None
            if cmode is not None and cmode["mode"] == "tool":
                env = constrain.parse_tool_envelope(text, cmode["names"])
                if env is not None:
                    name, args = env
                    tool_calls = [{
                        "id": f"call_{uuid.uuid4().hex[:24]}",
                        "type": "function",
                        "function": {"name": name, "arguments": args},
                    }]
                    text = ""
                    if finish == "stop":
                        finish = "tool_calls"
                # auto mode with no envelope: plain content, finish
                # stays as the engine reported; required/named with no
                # envelope only happens on a length truncation — the
                # partial text is returned as content with finish
                # "length" (the OpenAI truncation contract)
            if chat:
                resp = oai.chat_completion_response(
                    model=self.model_name, content=text,
                    finish_reason=finish, usage=usage, response_id=rid,
                    tool_calls=tool_calls,
                )
                if lp_content is not None:
                    resp["choices"][0]["logprobs"] = {
                        "content": lp_content}
            else:
                resp = {
                    "id": rid,
                    "object": "text_completion",
                    "created": created,
                    "model": self.model_name,
                    "choices": [
                        {"index": 0, "text": text, "finish_reason": finish}
                    ],
                    "usage": oai.usage_dict(usage),
                }
                if lp_content is not None:
                    # legacy completions carry token_logprobs/tokens
                    resp["choices"][0]["logprobs"] = \
                        self._legacy_logprobs(lp_content)
            return web.json_response(
                resp, headers={"x-aigw-request-id": rid,
                               **self._kv_chain_header(prefix_hashes)})

        # streaming
        resp = web.StreamResponse(
            status=200,
            headers={"content-type": "text/event-stream",
                     "cache-control": "no-cache",
                     # joins the gateway access log / client against the
                     # flight recorder (/debug/requests/{id}) and spans
                     "x-aigw-request-id": rid,
                     **self._kv_chain_header(prefix_hashes)},
        )
        # first-token fast path: the role frame and the first content
        # delta are two small writes back to back — Nagle must not hold
        # the second until the first is ACKed
        set_tcp_nodelay(request.transport)
        await resp.prepare(request)
        decoder = StreamingDecoder(self.tokenizer)
        emitted = ""
        n_out = 0
        finish = "stop"
        # Pre-serialized SSE chunk envelope: everything except the
        # content string is constant for the request's lifetime, so the
        # hot loop pays one json.dumps of the piece instead of
        # serializing the whole chunk dict per frame. Built by
        # splitting a real stream_chunk_sse frame on a sentinel, so the
        # bytes are identical to the non-template path by construction.
        tmpl_head = tmpl_tail = b""
        if chat:
            sentinel = "\x00aigw-delta-slot\x00"
            tmpl_head, tmpl_tail = oai.stream_chunk_sse(
                response_id=rid, model=self.model_name, created=created,
                delta={"content": sentinel},
            ).split(json.dumps(sentinel).encode())

        # tool-call streaming (ISSUE 9): required/named generations are
        # grammar-forced envelopes — split incrementally into OpenAI
        # tool_calls deltas; auto buffers only while the text is still a
        # viable envelope prefix, then streams as content or tool call
        tool_stream: Any = None
        auto_detect: Any = None
        if cmode is not None and cmode["mode"] == "tool":
            if cmode["choice"] == "auto":
                auto_detect = constrain.AutoToolDetector(cmode["names"])
            else:
                tool_stream = constrain.ToolCallParser()
        tool_call_id = f"call_{uuid.uuid4().hex[:24]}"

        async def write_tool_events(events) -> None:
            for ev in events:
                if ev[0] == "name":
                    await resp.write(oai.stream_chunk_sse(
                        response_id=rid, model=self.model_name,
                        created=created,
                        delta={"tool_calls": [{
                            "index": 0, "id": tool_call_id,
                            "type": "function",
                            "function": {"name": ev[1],
                                         "arguments": ""},
                        }]}))
                elif ev[0] == "args" and ev[1]:
                    await resp.write(oai.stream_chunk_sse(
                        response_id=rid, model=self.model_name,
                        created=created,
                        delta={"tool_calls": [{
                            "index": 0,
                            "function": {"arguments": ev[1]},
                        }]}))

        async def write_piece(piece: str, lp_entries=None) -> None:
            # an empty piece (mid-UTF-8 token) still carries its logprob
            # entries so the streamed list aligns 1:1 with completion
            # tokens; without logprobs, empty pieces emit nothing
            if not piece and not lp_entries:
                return
            if chat:
                if not lp_entries:
                    await resp.write(
                        tmpl_head + json.dumps(piece).encode()
                        + tmpl_tail)
                    return
                await resp.write(
                    oai.stream_chunk_sse(
                        response_id=rid, model=self.model_name,
                        created=created, delta={"content": piece},
                        logprobs={"content": lp_entries},
                    )
                )
            else:
                choice: dict[str, Any] = {"index": 0, "text": piece,
                                          "finish_reason": None}
                if lp_entries:
                    choice["logprobs"] = self._legacy_logprobs(lp_entries)
                await resp.write(
                    SSEEvent(
                        data=json.dumps(
                            {
                                "id": rid,
                                "object": "text_completion",
                                "created": created,
                                "model": self.model_name,
                                "choices": [choice],
                            }
                        )
                    ).encode()
                )

        async def emit_text(piece: str, lp_entries=None) -> None:
            """Route one detokenized burst: content deltas normally,
            tool_calls deltas for grammar-forced envelopes, buffered
            while a tool_choice=auto stream is still ambiguous."""
            nonlocal tool_stream
            if tool_stream is not None:
                await write_tool_events(tool_stream.feed(piece))
                return
            if auto_detect is not None and auto_detect.decided is None:
                decision, text_out = auto_detect.feed(piece)
                if decision is None:
                    return  # still a viable envelope prefix: buffer
                if decision == "tool":
                    tool_stream = constrain.ToolCallParser()
                    await write_tool_events(tool_stream.feed(text_out))
                    return
                piece = text_out  # diverged: flush the buffer as content
            await write_piece(piece, lp_entries)

        try:
            if chat:
                await resp.write(
                    oai.stream_chunk_sse(
                        response_id=rid, model=self.model_name,
                        created=created,
                        delta={"role": "assistant", "content": ""},
                    )
                )
            done_streaming = False

            async def handle_burst(burst: list, inline_detok: bool) -> None:
                """Detokenize + emit one burst as one SSE frame. Big
                bursts detokenize off the event loop (the HF tokenizer
                releases the GIL); tiny ones — and the latency-critical
                FIRST frame (inline_detok) — stay inline: the executor
                hop would cost more than it hides. The decoder is
                stateful per request, so pre-decoding the whole burst
                is safe: tokens past a stop hit are discarded below and
                the decoder is never reused after."""
                nonlocal emitted, n_out, finish, done_streaming
                toks = [t for t, _f, _lp in burst if t >= 0]
                predecoded = (
                    iter(await self._off(_push_all, decoder, toks))
                    if len(toks) >= 4 and not inline_detok else None
                )
                pieces: list[str] = []
                lp_entries: list[dict[str, Any]] = []
                for tok, fin, lp in burst:
                    if tok >= 0:
                        n_out += 1
                        rm.record_tokens_emitted(1)
                        piece = (next(predecoded) if predecoded is not None
                                 else decoder.push(tok))
                        lp_entry = (self._lp_entry(piece, lp, lp_top_n)
                                    if want_lp and lp is not None else None)
                        if piece:
                            emitted += piece
                            hit = _find_stop(emitted, stop_strs)
                            if hit is not None:
                                # trim to just before the stop sequence;
                                # the truncated final token keeps its lp
                                # entry (1:1 token/entry alignment)
                                keep = hit - (len(emitted) - len(piece))
                                pieces.append(piece[:max(keep, 0)])
                                if lp_entry is not None:
                                    lp_entries.append(lp_entry)
                                finish = "stop"
                                gen_req.cancelled.set()
                                done_streaming = True
                                break
                            pieces.append(piece)
                            if lp_entry is not None:
                                lp_entries.append(lp_entry)
                        elif lp_entry is not None:
                            lp_entries.append(lp_entry)
                    if fin is not None:
                        finish = fin
                        if fin not in ("error", "migrated"):
                            # migrated: any held-back partial text is
                            # re-derived by the importing replica's
                            # primed decoder — flushing it here would
                            # duplicate it across the seam
                            pieces.append(decoder.flush())
                        done_streaming = True
                        break
                await emit_text("".join(pieces), lp_entries)

            while not done_streaming:
                # keepalive comments while queued behind prefills so
                # intermediaries don't drop an apparently-idle stream
                while True:
                    try:
                        first = await asyncio.wait_for(
                            out.get(), timeout=10.0)
                        break
                    except asyncio.TimeoutError:
                        await resp.write(b": ping\n\n")
                # Coalesce the burst: a decode window lands K tokens per
                # slot on the queue at once; one SSE frame per burst
                # instead of one per token cuts event-loop wakeups,
                # json dumps, and syscalls ~K× in the serving hot loop
                # (OpenAI deltas are arbitrary strings; logprob entries
                # stay 1:1 with tokens inside the frame's content list).
                burst = [first]
                while True:
                    try:
                        burst.append(out.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                if n_out == 0 and len(burst) > 1:
                    # first-token fast path: the stream's FIRST token
                    # rides its own frame — detokenized inline and on
                    # the wire before the rest of the burst is even
                    # decoded — so a request that waited out a decode
                    # window doesn't pay the whole burst's detokenize/
                    # serialize cost before its first byte
                    await handle_burst(burst[:1], inline_detok=True)
                    if not done_streaming:
                        await handle_burst(burst[1:],
                                           inline_detok=False)
                else:
                    await handle_burst(burst, inline_detok=n_out == 0)
            if auto_detect is not None and tool_stream is None:
                # stream ended while the auto detector was still
                # ambiguous: the held-back prefix was content after all
                decision, text_rem = auto_detect.finish()
                if decision == "content" and text_rem:
                    await write_piece(text_rem)
        except (asyncio.CancelledError, ConnectionResetError):
            # client went away: stop generating, free the slot
            gen_req.cancelled.set()
            self._end_trace(trace, "cancelled", n_out, n_prompt)
            raise
        if tool_stream is not None and tool_stream.completed \
                and finish == "stop":
            finish = "tool_calls"
        usage = self._usage_from_meter(n_prompt, n_out, meter_box)
        rm.finish(usage)
        self._end_trace(trace, finish, n_out, n_prompt)
        if finish == "migrated":
            # the session moved to another replica mid-stream: end THIS
            # stream with no finish frame and no [DONE] — the importing
            # replica's continuation stream (spliced by the gateway)
            # carries the terminal frames under the same response id
            await resp.write_eof()
            return resp
        await resp.write(self._final_stream_frame(
            chat, rid, created, finish,
            usage if include_usage else None))
        await resp.write(SSEEvent(data="[DONE]").encode())
        await resp.write_eof()
        return resp

    def _final_stream_frame(self, chat: bool, rid: str, created: int,
                            finish: str,
                            usage: TokenUsage | None) -> bytes:
        """Terminal SSE frame carrying finish_reason (+ usage when
        requested) in the FRONT schema's chunk shape. Legacy
        /v1/completions streams previously ended with a chat-shaped
        chunk here — the gateway's typed stream validator (correctly)
        rejected it and replaced the stream tail with an error event."""
        if chat:
            return oai.stream_chunk_sse(
                response_id=rid, model=self.model_name, created=created,
                delta={}, finish_reason=finish, usage=usage)
        ev: dict[str, Any] = {
            "id": rid, "object": "text_completion", "created": created,
            "model": self.model_name,
            "choices": [{"index": 0, "text": "",
                         "finish_reason": finish}],
        }
        if usage is not None:
            ev["usage"] = oai.usage_dict(usage)
        return SSEEvent(data=json.dumps(ev)).encode()

    def _submit_n(self, body: dict[str, Any], prompt: list[int], n: int,
                  lp_top_n: int, prefix_hashes: list | None = None,
                  tenant: str = "", constraint: Any = None,
                  priority: str = "interactive"):
        """Fan out n engine submissions with per-choice seeds (shared by
        the buffered and streaming n>1 paths — one copy of the seed
        derivation, overload cleanup, and error mapping). Returns the
        list of (queue, request, meter_box) triples, or an error
        web.Response."""
        sampling = SamplingParams.from_request(body)
        outs: list = []
        try:
            for i in range(n):
                # distinct seeds per choice so samples differ
                # deterministically
                per_choice = dict(body)
                per_choice["seed"] = (sampling.seed or 0) + i if (
                    sampling.seed or sampling.temperature > 0
                ) else 0
                outs.append(self._submit(prompt, per_choice, lp_top_n,
                                         prefix_hashes, tenant=tenant,
                                         constraint=constraint,
                                         priority=priority))
        except EngineOverloadedError as e:
            for _q, req, _b in outs:  # don't orphan already-queued choices
                req.cancelled.set()
            return web.Response(
                status=429,
                body=oai.error_body(str(e), type_="rate_limit_error"),
                headers={"retry-after": "1"},
                content_type="application/json")
        except oai.SchemaError as e:  # unknown adapter → 404, like n=1
            for _q, req, _b in outs:
                req.cancelled.set()
            return web.Response(
                status=404,
                body=oai.error_body(str(e), type_="model_not_found"),
                content_type="application/json")
        except ValueError as e:  # bad sampling params → 400, like n=1
            for _q, req, _b in outs:
                req.cancelled.set()
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        return outs

    async def _generate_n(
        self, body: dict[str, Any], prompt: list[int], chat: bool, n: int,
        lp_top_n: int = -1, prefix_hashes: list | None = None,
        tenant: str = "", constraint: Any = None,
        priority: str = "interactive",
    ) -> web.Response:
        """n>1 choices: fan out n engine requests (continuous batching
        runs them concurrently — same prompt pages shared by the prefix
        cache) and assemble a multi-choice response."""
        stops = body.get("stop")
        stop_strs = [stops] if isinstance(stops, str) else list(stops or [])
        outs = self._submit_n(body, prompt, n, lp_top_n, prefix_hashes,
                              tenant, constraint, priority)
        if isinstance(outs, web.Response):
            return outs
        results = await asyncio.gather(
            *(self._collect(q, stop_strs, lp_top_n)
              for q, _req, _b in outs)
        )
        # single-metering on fan-out (satellite): each choice is one
        # engine request with exactly one MeterRecord; the response's
        # one usage object carries their field-wise sum
        merged_meter = self._merge_meter_boxes([b for _q, _r, b in outs])
        usage = TokenUsage(
            input_tokens=len(prompt),
            output_tokens=sum(r[1] for r in results),
            total_tokens=len(prompt) + sum(r[1] for r in results),
            cached_input_tokens=int(
                merged_meter.get("prefix_reused", 0) or 0),
            meter=meter_to_tuple(merged_meter) if merged_meter else (),
        )
        rid = (f"chatcmpl-{uuid.uuid4().hex[:24]}" if chat
               else f"cmpl-{uuid.uuid4().hex[:24]}")
        if chat:
            choices = []
            for i, (text, _n, finish, lp_content) in enumerate(results):
                c: dict[str, Any] = {
                    "index": i,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": finish}
                if lp_content is not None:
                    c["logprobs"] = {"content": lp_content}
                choices.append(c)
            resp = {
                "id": rid, "object": "chat.completion",
                "created": int(time.time()), "model": self.model_name,
                "choices": choices, "usage": oai.usage_dict(usage),
            }
        else:
            resp = {
                "id": rid, "object": "text_completion",
                "created": int(time.time()), "model": self.model_name,
                "choices": [
                    {"index": i, "text": text, "finish_reason": finish,
                     **({"logprobs": self._legacy_logprobs(lp_content)}
                        if lp_content is not None else {})}
                    for i, (text, _n, finish, lp_content)
                    in enumerate(results)
                ],
                "usage": oai.usage_dict(usage),
            }
        return web.json_response(resp)

    async def _generate_n_stream(
        self, request: web.Request, body: dict[str, Any],
        prompt: list[int], chat: bool, n: int, lp_top_n: int = -1,
        prefix_hashes: list | None = None, tenant: str = "",
        constraint: Any = None, priority: str = "interactive",
    ) -> web.StreamResponse:
        """Streaming n>1 (OpenAI parity; previously 400): fan out n
        engine requests, merge their token streams, and emit one SSE
        chunk per (choice, burst) carrying that choice's index —
        clients see the standard interleaved multi-choice stream. The
        continuous-batching engine runs the choices concurrently; the
        prefix cache shares their prompt pages."""
        stops = body.get("stop")
        stop_strs = [stops] if isinstance(stops, str) else list(stops or [])
        include_usage = oai.include_stream_usage(body)
        outs = self._submit_n(body, prompt, n, lp_top_n, prefix_hashes,
                              tenant, constraint, priority)
        if isinstance(outs, web.Response):
            return outs

        rid = (f"chatcmpl-{uuid.uuid4().hex[:24]}" if chat
               else f"cmpl-{uuid.uuid4().hex[:24]}")
        created = int(time.time())
        rm = RequestMetrics(
            metrics=self.metrics,
            operation="chat" if chat else "text_completion",
            provider="tpuserve",
            request_model=body.get("model", self.model_name),
            response_model=self.model_name,
        )
        resp = web.StreamResponse(
            status=200,
            headers={"content-type": "text/event-stream",
                     "cache-control": "no-cache"},
        )
        await resp.prepare(request)

        merged: asyncio.Queue = asyncio.Queue()

        async def pump(i: int, q: asyncio.Queue) -> None:
            while True:
                item = await q.get()
                await merged.put((i, item))
                if item[1] is not None:  # finish marker
                    return

        pumps = [asyncio.create_task(pump(i, q))
                 for i, (q, _req, _b) in enumerate(outs)]
        decoders = [StreamingDecoder(self.tokenizer) for _ in range(n)]
        emitted = [""] * n
        counts = [0] * n
        done = [False] * n
        want_lp = lp_top_n >= 0

        async def write_chunk(i: int, piece: str, lp_entries=None,
                              finish: str | None = None) -> None:
            if chat:
                delta = {"content": piece} if finish is None else {}
                await resp.write(oai.stream_chunk_sse(
                    response_id=rid, model=self.model_name,
                    created=created, delta=delta, index=i,
                    finish_reason=finish,
                    logprobs={"content": lp_entries}
                    if lp_entries else None,
                ))
            else:
                choice: dict[str, Any] = {"index": i, "text": piece,
                                          "finish_reason": finish}
                if lp_entries:
                    choice["logprobs"] = self._legacy_logprobs(lp_entries)
                await resp.write(SSEEvent(data=json.dumps({
                    "id": rid, "object": "text_completion",
                    "created": created, "model": self.model_name,
                    "choices": [choice],
                })).encode())

        try:
            if chat:
                for i in range(n):
                    await resp.write(oai.stream_chunk_sse(
                        response_id=rid, model=self.model_name,
                        created=created,
                        delta={"role": "assistant", "content": ""},
                        index=i,
                    ))
            while not all(done):
                while True:
                    try:
                        first = await asyncio.wait_for(merged.get(),
                                                       timeout=10.0)
                        break
                    except asyncio.TimeoutError:
                        await resp.write(b": ping\n\n")
                burst = [first]
                while True:
                    try:
                        burst.append(merged.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                # coalesce per choice within the burst
                pieces: dict[int, list[str]] = {}
                lps: dict[int, list] = {}
                fins: dict[int, str] = {}
                for i, (tok, fin, lp) in burst:
                    if done[i] or i in fins:
                        # post-finish tokens in the same burst (e.g.
                        # after a stop-string hit) must not count
                        # toward usage — the n=1 path breaks there too
                        continue
                    if tok >= 0:
                        counts[i] += 1
                        rm.record_tokens_emitted(1)
                        piece = decoders[i].push(tok)
                        if want_lp and lp is not None:
                            lps.setdefault(i, []).append(
                                self._lp_entry(piece, lp, lp_top_n))
                        if piece:
                            emitted[i] += piece
                            hit = _find_stop(emitted[i], stop_strs)
                            if hit is not None:
                                keep = hit - (len(emitted[i])
                                              - len(piece))
                                pieces.setdefault(i, []).append(
                                    piece[:max(keep, 0)])
                                fins[i] = "stop"
                                outs[i][1].cancelled.set()
                                continue
                            pieces.setdefault(i, []).append(piece)
                    if fin is not None and i not in fins:
                        fins[i] = fin
                        if fin != "error":
                            tail = decoders[i].flush()
                            if tail:
                                pieces.setdefault(i, []).append(tail)
                for i in sorted(set(pieces) | set(lps) | set(fins)):
                    text = "".join(pieces.get(i, ()))
                    if text or lps.get(i):
                        await write_chunk(i, text, lps.get(i))
                    if i in fins:
                        done[i] = True
                        await write_chunk(i, "", None,
                                          finish=fins[i] or "stop")
        except (asyncio.CancelledError, ConnectionResetError):
            for _q, req, _b in outs:
                req.cancelled.set()
            raise
        finally:
            for p in pumps:
                p.cancel()
        merged_meter = self._merge_meter_boxes([b for _q, _r, b in outs])
        usage = TokenUsage(
            input_tokens=len(prompt),
            output_tokens=sum(counts),
            total_tokens=len(prompt) + sum(counts),
            cached_input_tokens=int(
                merged_meter.get("prefix_reused", 0) or 0),
            meter=meter_to_tuple(merged_meter) if merged_meter else (),
        )
        rm.finish(usage)
        if include_usage:
            if chat:
                await resp.write(oai.stream_chunk_sse(
                    response_id=rid, model=self.model_name,
                    created=created, delta=None, usage=usage,
                ))
            else:
                # legacy completions: the usage chunk must keep the
                # text_completion shape (choices present, possibly
                # empty) or the gateway's typed validator drops it
                await resp.write(SSEEvent(data=json.dumps({
                    "id": rid, "object": "text_completion",
                    "created": created, "model": self.model_name,
                    "choices": [],
                    "usage": oai.usage_dict(usage),
                })).encode())
        await resp.write(SSEEvent(data="[DONE]").encode())
        await resp.write_eof()
        return resp

    async def _collect(
        self, out: asyncio.Queue, stop_strs: list[str],
        lp_top_n: int = -1,
    ) -> tuple[str, int, str, list | None]:
        """Drain a generation to completion (non-streaming path).
        ``lp_top_n >= 0`` also collects OpenAI logprobs content entries
        (engine must run with logprobs_topk > 0)."""
        decoder = StreamingDecoder(self.tokenizer)
        text = ""
        n_out = 0
        finish = "stop"
        lp_content: list | None = [] if lp_top_n >= 0 else None
        while True:
            tok, fin, lp = await out.get()
            if tok >= 0:
                n_out += 1
                piece = decoder.push(tok)
                text += piece
                if lp_content is not None and lp is not None:
                    lp_content.append(
                        self._lp_entry(piece, lp, lp_top_n))
                hit = _find_stop(text, stop_strs)
                if hit is not None:
                    return text[:hit], n_out, "stop", lp_content
            if fin is not None:
                finish = fin
                if fin != "error":
                    text += decoder.flush()
                return text, n_out, finish, lp_content

    async def _embeddings(self, request: web.Request) -> web.Response:
        try:
            body = oai.parse_json_body(await request.read())
        except oai.SchemaError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        raw = body.get("input")
        if isinstance(raw, str):
            items: list = [raw]
        elif isinstance(raw, list) and raw and all(
            isinstance(x, int) for x in raw
        ):
            items = [raw]  # a single pre-tokenized input
        elif isinstance(raw, list):
            items = list(raw)
        else:
            items = []
        if not items:
            return web.Response(
                status=400,
                body=oai.error_body(
                    "input must be a string, array of strings, or array of "
                    "token ids"
                ),
                content_type="application/json",
            )
        max_len = self.engine.cfg.max_seq_len
        # encode all string items concurrently on the tokenizer pool
        str_jobs = {
            idx: self._off(self.tokenizer.encode, it)
            for idx, it in enumerate(items) if isinstance(it, str)
        }
        str_results = dict(zip(
            str_jobs, await asyncio.gather(*str_jobs.values())
        ))
        encoded = []
        for idx, it in enumerate(items):
            if isinstance(it, str):
                encoded.append(str_results[idx][:max_len])
            elif isinstance(it, list) and all(isinstance(x, int) for x in it):
                encoded.append([x % self.model_cfg.vocab_size for x in it][:max_len])
            else:
                return web.Response(
                    status=400,
                    body=oai.error_body("invalid embeddings input element"),
                    content_type="application/json",
                )
        S = max(8, max(len(e) for e in encoded))
        S = 1 << (S - 1).bit_length()  # pow2 bucket to bound compiles
        toks = np.zeros((len(encoded), S), np.int32)
        lens = np.zeros((len(encoded),), np.int32)
        for i, e in enumerate(encoded):
            toks[i, : len(e)] = e
            lens[i] = len(e)
        hidden = await asyncio.to_thread(
            lambda: np.asarray(
                self._hidden_fn(self.engine.params, jnp.asarray(toks),
                                jnp.asarray(lens))
            )
        )
        n_tokens = int(lens.sum())
        usage = TokenUsage(input_tokens=n_tokens, total_tokens=n_tokens)
        return web.json_response(
            oai.embeddings_response(
                model=self.model_name,
                vectors=[h.tolist() for h in hidden],
                usage=usage,
            )
        )

    async def _tokenize(self, request: web.Request) -> web.Response:
        try:
            body = oai.parse_json_body(await request.read())
        except oai.SchemaError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        if isinstance(body.get("messages"), list):
            ids = await self._off(apply_chat_template, body["messages"],
                                  self.tokenizer, self.chat_template)
        else:
            ids = await self._off(self.tokenizer.encode,
                                  str(body.get("prompt", "")))
        return web.json_response(
            {
                "count": len(ids),
                "max_model_len": self.engine.cfg.max_seq_len,
                "tokens": ids,
            }
        )

    async def _models(self, _request: web.Request) -> web.Response:
        # capability flags (ISSUE 9): clients (and the gateway's merged
        # /v1/models) discover which structured-output / tool-calling
        # workloads this replica enforces natively
        caps = (dict(constrain.CAPABILITIES)
                if self.engine.cfg.constrained_decoding else None)
        extra = {"capabilities": caps} if caps else None
        entries: list[tuple] = [(self.model_name, "tpuserve", 0, extra)]
        entries += [
            (f"{self.model_name}:{a}", "tpuserve-lora", 0, extra)
            for a in self.adapter_names
        ]
        return web.json_response(oai.models_response(entries))

    # -- offline batch tier (ISSUE 19) ------------------------------------
    #: request lines accepted per batch file (a replica-local in-memory
    #: store, not a durable object store — bound the blast radius)
    _BATCH_MAX_LINES = 10_000

    async def _file_upload(self, request: web.Request) -> web.Response:
        """POST /v1/files — accept a raw JSONL batch input body and
        return a file id. Intentionally raw-body (not multipart): the
        gateway forwards bytes verbatim and the batch surface is the
        only consumer."""
        if self.draining:
            return self._drain_refusal()
        raw = await request.read()
        if not raw.strip():
            return web.Response(
                status=400,
                body=oai.error_body("empty file body; POST the JSONL "
                                    "batch input as the request body"),
                content_type="application/json")
        fid = f"file-{uuid.uuid4().hex[:24]}"
        self._files[fid] = raw
        return web.json_response({
            "id": fid, "object": "file", "bytes": len(raw),
            "created_at": int(time.time()), "purpose": "batch",
        })

    async def _file_content(self, request: web.Request) -> web.Response:
        raw = self._files.get(request.match_info["fid"])
        if raw is None:
            return web.Response(
                status=404, body=oai.error_body("unknown file id"),
                content_type="application/json")
        return web.Response(body=raw,
                            content_type="application/jsonl")

    def _parse_batch_lines(self, raw: bytes,
                           endpoint: str) -> list[tuple[str, dict]]:
        """Validate the whole JSONL input up front — every malformed
        shape is a 400 naming its line BEFORE any engine work runs (a
        half-executed batch that then 400s would strand its output).
        Raises oai.SchemaError."""
        lines: list[tuple[str, dict]] = []
        seen: set[str] = set()
        for i, ln in enumerate(raw.splitlines(), start=1):
            if not ln.strip():
                continue
            try:
                obj = json.loads(ln)
            except ValueError:
                raise oai.SchemaError(
                    f"line {i}: not valid JSON") from None
            if not isinstance(obj, dict):
                raise oai.SchemaError(
                    f"line {i}: each line must be a JSON object")
            cid = obj.get("custom_id")
            if not isinstance(cid, str) or not cid:
                raise oai.SchemaError(
                    f"line {i}: custom_id must be a non-empty string")
            if cid in seen:
                raise oai.SchemaError(
                    f"line {i}: duplicate custom_id {cid!r}")
            seen.add(cid)
            if obj.get("method", "POST") != "POST":
                raise oai.SchemaError(
                    f"line {i}: method must be POST")
            url = obj.get("url", endpoint)
            if url != endpoint:
                raise oai.SchemaError(
                    f"line {i}: url {url!r} does not match the batch "
                    f"endpoint {endpoint!r}")
            body = obj.get("body")
            if not isinstance(body, dict):
                raise oai.SchemaError(
                    f"line {i}: body must be a JSON object")
            if body.get("stream"):
                raise oai.SchemaError(
                    f"line {i}: stream is not supported in batches")
            lines.append((cid, body))
        if not lines:
            raise oai.SchemaError("batch input has no request lines")
        if len(lines) > self._BATCH_MAX_LINES:
            raise oai.SchemaError(
                f"batch input has {len(lines)} lines; this replica "
                f"caps a batch at {self._BATCH_MAX_LINES}")
        return lines

    async def _batch_create(self, request: web.Request) -> web.Response:
        """POST /v1/batches — validate the input file, register the
        batch object, and start the runner. Batch work is NEVER
        429-shed: lines enter the engine's unbounded batch queue and
        soak idle decode slots at strict low priority."""
        if self.draining:
            return self._drain_refusal()
        try:
            body = oai.parse_json_body(await request.read())
        except oai.SchemaError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        endpoint = str(body.get("endpoint", ""))
        if endpoint not in ("/v1/chat/completions", "/v1/completions"):
            return web.Response(
                status=400,
                body=oai.error_body(
                    "endpoint must be /v1/chat/completions or "
                    "/v1/completions"),
                content_type="application/json")
        fid = str(body.get("input_file_id", ""))
        raw = self._files.get(fid)
        if raw is None:
            return web.Response(
                status=404,
                body=oai.error_body(f"unknown input_file_id {fid!r}"),
                content_type="application/json")
        try:
            lines = self._parse_batch_lines(raw, endpoint)
        except oai.SchemaError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        bid = f"batch_{uuid.uuid4().hex[:24]}"
        self._batches[bid] = {
            "id": bid, "object": "batch", "endpoint": endpoint,
            "input_file_id": fid, "status": "in_progress",
            "output_file_id": None, "created_at": int(time.time()),
            "request_counts": {"total": len(lines), "completed": 0,
                               "failed": 0},
        }
        self._batch_lines[bid] = lines
        self._batch_live[bid] = []
        self._batch_tasks[bid] = asyncio.create_task(
            self._run_batch(bid))
        return web.json_response(self._batches[bid])

    async def _batch_list(self, _request: web.Request) -> web.Response:
        return web.json_response({
            "object": "list",
            "data": sorted(self._batches.values(),
                           key=lambda b: b["created_at"]),
        })

    async def _batch_get(self, request: web.Request) -> web.Response:
        b = self._batches.get(request.match_info["bid"])
        if b is None:
            return web.Response(
                status=404, body=oai.error_body("unknown batch id"),
                content_type="application/json")
        return web.json_response(b)

    async def _batch_cancel(self, request: web.Request) -> web.Response:
        """POST /v1/batches/{id}/cancel — stop submitting new lines and
        cancel the in-flight ones; the runner finalizes to
        ``cancelled`` with the lines that DID finish in the output."""
        bid = request.match_info["bid"]
        b = self._batches.get(bid)
        if b is None:
            return web.Response(
                status=404, body=oai.error_body("unknown batch id"),
                content_type="application/json")
        if b["status"] == "in_progress":
            b["status"] = "cancelling"
            for req in self._batch_live.get(bid, ()):
                req.cancelled.set()
        return web.json_response(b)

    async def _batch_one(self, bid: str, body: dict[str, Any],
                         chat: bool) -> tuple[int, dict[str, Any]]:
        """Run ONE batch line through the normal submit path at
        priority="batch" (non-streaming). Returns (status_code,
        response body) — per-line failures are output lines, never a
        batch-level error."""
        try:
            if chat:
                oai.validate_chat_request(body)
                prompt, hashes = await self._off(self._encode_chat,
                                                 body["messages"])
            else:
                oai.request_model(body)
                text_in = body.get("prompt", "")
                if isinstance(text_in, list):
                    text_in = "".join(text_in)
                prompt, hashes = await self._off(self._encode_text,
                                                 text_in)
            lp_top_n = self._check_logprobs(body)
            tenant = str(body.get("user", ""))
            out, gen_req, meter_box = self._submit(
                prompt, body, lp_top_n, hashes,
                tenant=tenant, priority="batch")
        except oai.SchemaError as e:
            return 400, json.loads(oai.error_body(str(e)))
        except ValueError as e:
            return 400, json.loads(oai.error_body(str(e)))
        self._batch_live[bid].append(gen_req)
        stops = body.get("stop")
        stop_strs = ([stops] if isinstance(stops, str)
                     else list(stops or []))
        try:
            text, n_out, finish, lp_content = await self._collect(
                out, stop_strs, lp_top_n)
        finally:
            self._batch_live[bid].remove(gen_req)
        if finish == "error":
            return 500, json.loads(oai.error_body(
                "engine failure", type_="server_error"))
        # /v1/batches output lines carry full usage incl. the engine
        # meter (satellite) — a parked/resumed line's record spans the
        # whole spliced session including host-spill residency
        usage = self._usage_from_meter(len(prompt), n_out, meter_box)
        if chat:
            resp = oai.chat_completion_response(
                model=self.model_name, content=text,
                finish_reason=finish, usage=usage,
                response_id=f"chatcmpl-{uuid.uuid4().hex[:24]}")
            if lp_content is not None:
                resp["choices"][0]["logprobs"] = {"content": lp_content}
        else:
            resp = {
                "id": f"cmpl-{uuid.uuid4().hex[:24]}",
                "object": "text_completion",
                "created": int(time.time()),
                "model": self.model_name,
                "choices": [{"index": 0, "text": text,
                             "finish_reason": finish}],
                "usage": oai.usage_dict(usage),
            }
            if lp_content is not None:
                resp["choices"][0]["logprobs"] = \
                    self._legacy_logprobs(lp_content)
        return 200, resp

    async def _run_batch(self, bid: str) -> None:
        """The batch runner: drive every line at priority="batch" with
        bounded concurrency (one engine's worth — backlog beyond that
        sits in the replica, not as thousands of parked asyncio
        queues), assemble the JSONL output file, finalize the batch
        object."""
        b = self._batches[bid]
        lines = self._batch_lines.pop(bid)
        chat = b["endpoint"] == "/v1/chat/completions"
        sem = asyncio.Semaphore(max(2, self.engine.cfg.max_batch_size))
        out_lines: list[bytes | None] = [None] * len(lines)

        async def one(i: int, cid: str, body: dict[str, Any]) -> None:
            async with sem:
                if b["status"] != "in_progress":
                    return  # cancelled before this line started
                status, resp = await self._batch_one(bid, body, chat)
                entry = {
                    "id": f"batch_req_{uuid.uuid4().hex[:16]}",
                    "custom_id": cid,
                    "response": {"status_code": status, "body": resp},
                    "error": None,
                }
                if status == 200:
                    b["request_counts"]["completed"] += 1
                else:
                    b["request_counts"]["failed"] += 1
                out_lines[i] = json.dumps(entry).encode()

        try:
            await asyncio.gather(*(one(i, cid, body)
                                   for i, (cid, body) in enumerate(lines)))
        except asyncio.CancelledError:
            for req in self._batch_live.get(bid, ()):
                req.cancelled.set()
            raise
        ofid = f"file-{uuid.uuid4().hex[:24]}"
        self._files[ofid] = b"\n".join(
            ln for ln in out_lines if ln is not None) + b"\n"
        b["output_file_id"] = ofid
        b["status"] = ("cancelled" if b["status"] == "cancelling"
                       else "completed")
        self._batch_tasks.pop(bid, None)

    # -- graceful drain (ISSUE 14) ----------------------------------------
    def _drain_refusal(self) -> web.Response:
        """503 + Retry-After for new work on a draining replica: the
        gateway's pre-first-byte failover retries the next-ranked
        sibling; a direct client backs off and re-resolves."""
        return web.Response(
            status=503,
            body=oai.error_body(
                "replica is draining (shutting down or being retired); "
                "retry against another replica",
                type_="server_error"),
            headers={"retry-after": "2", "x-aigw-draining": "1"},
            content_type="application/json")

    async def _drain(self, request: web.Request) -> web.Response:
        """POST /drain — the control plane's retire protocol: flips the
        draining flag (``{"on": false}`` un-drains, e.g. a cancelled
        rolling update) and reports what's still live. Admissions are
        refused from the moment the flag is up; live slots keep
        serving until they finish or the gateway migrates them off."""
        try:
            raw = await request.read()
            body = oai.parse_json_body(raw) if raw.strip() else {}
        except oai.SchemaError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        self.draining = bool(body.get("on", True))
        s = self.engine.stats
        return web.json_response({
            "draining": self.draining,
            "active_slots": s.active_slots,
            "queued": s.queued,
            "batch_queued": s.batch_queued,
            "batch_active": s.batch_active,
            "live_streams": len(self._live),
            "migratable_slots": s.migratable_slots,
        })

    async def drain(self, timeout_s: float = 60.0,
                    poll_s: float = 0.1) -> bool:
        """Drain to empty: refuse new admissions and wait until the
        engine holds zero active slots and an empty queue (sessions
        finish naturally or the gateway migrates them away). Returns
        True when fully drained within the budget — the graceful-exit
        criterion (exit 0 with zero live slots)."""
        self.draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            s = self.engine.stats
            # batch backlog (queued + parked) must clear too: a retired
            # replica's in-memory batch state is gone — scale-in waits
            # for the soak to finish before pulling the plug
            if (s.active_slots == 0 and s.queued == 0
                    and s.batch_queued == 0):
                return True
            await asyncio.sleep(poll_s)
        s = self.engine.stats
        return (s.active_slots == 0 and s.queued == 0
                and s.batch_queued == 0)

    def install_signal_drain(self, stop_event: asyncio.Event,
                             grace_s: float = 30.0) -> None:
        """SIGTERM/SIGINT → graceful drain, then set ``stop_event`` so
        the caller can cleanup + exit 0. A second signal skips the
        drain (operator insisting). Call from within the running
        loop."""
        loop = asyncio.get_running_loop()

        def _handle() -> None:
            if self.draining:
                stop_event.set()  # second signal: immediate
                return

            async def _go() -> None:
                drained = await self.drain(grace_s)
                logger.info("drain %s; shutting down",
                            "complete" if drained else "timed out")
                stop_event.set()

            logger.info("signal received: draining (grace %.0fs)",
                        grace_s)
            self._drain_task = loop.create_task(_go())

        import signal as _signal

        for sig in (_signal.SIGTERM, _signal.SIGINT):
            loop.add_signal_handler(sig, _handle)

    async def _health(self, _request: web.Request) -> web.Response:
        if not self.engine.healthy:
            return web.json_response(
                {"status": "error", "model": self.model_name,
                 "error": self.engine.last_error},
                status=503,
            )
        return web.json_response({"status": "ok", "model": self.model_name})

    async def _state(self, _request: web.Request) -> web.Response:
        """Endpoint-picker telemetry (KV occupancy, queue depth, and the
        queue-latency / adaptive-window signals the picker scores).

        Drift contract (rule ``gauge-drift``, make lint): every literal
        key below must be an ENGINE_GAUGES attr or carry a STATE_ONLY
        exemption in analysis/manifest.py, and every non-exempt gauge
        attr must appear here — keep new fields literal string keys so
        the static pass sees them (** spreads carry only the dynamic
        topology surface)."""
        s = self.engine.stats
        store = self.adapter_store
        tenant_slots = self.engine._tenant_slots()
        return web.json_response(
            {
                "model": self.model_name,
                # replica identity/uptime (ISSUE 12): the fleet
                # aggregator keys restart detection on replica_id and
                # displays uptime per replica
                "replica_id": self.replica_id,
                "started_at": round(self._started_at, 3),
                "uptime_s": round(time.time() - self._started_at, 3),
                # graceful drain (ISSUE 14): the gateway's fleet health
                # machine honors this as the control-plane overlay —
                # the picker stops routing here on the next poll
                "draining": self.draining,
                # cumulative TTFT histogram buckets — the gateway's
                # live SLO burn-rate monitor (obs/slomon.py) computes
                # windowed goodput from the deltas of this field, off
                # the /state poll the picker already makes
                "ttft_hist_buckets":
                    self.engine.phases.hists["ttft"].cumulative(),
                # adapter serving subsystem (ISSUE 7): the zoo, device
                # residency, load/evict churn, and in-flight adapter
                # slots — the gateway picker's adapter-affinity signal
                # and the capacity dashboard for row sizing
                "adapters_registered": sorted(self.adapter_names),
                "adapters_resident": (store.resident_names()
                                      if store is not None else []),
                "adapter_rows": (store.n_slots if store is not None
                                 else 0),
                "adapter_loads": s.adapter_loads,
                "adapter_evictions": s.adapter_evictions,
                "adapter_slots": s.adapter_slots,
                # multi-tenant fairness surface: who holds decode slots
                # right now, and how often the per-tenant cap deferred
                # an admission
                "tenant_slots": {t or "(anonymous)": c
                                 for t, c in sorted(tenant_slots.items())},
                "tenants_active": s.tenants_active,
                "tenant_max_slots": s.tenant_max_slots,
                "tenant_deferrals": s.tenant_deferrals,
                "tenant_slot_cap": self.engine.cfg.tenant_slot_cap,
                # prefill/decode disaggregation (ISSUE 8): sessions
                # moved in/out, the KV pages that traveled with them,
                # and the live migration-eligibility count (prefill
                # done, decode young) the gateway's orchestrator reads
                "migrations_out": s.migrations_out,
                "migrations_in": s.migrations_in,
                "migration_pages_out": s.migration_pages_out,
                "migration_pages_in": s.migration_pages_in,
                "migratable_slots": s.migratable_slots,
                # KV memory hierarchy (ISSUE 11): host-spill-tier
                # occupancy/churn, cross-replica fetch traffic, and the
                # resident+spilled chain digest the gateway's fleet
                # index polls (chain-hash → replica routing)
                "kv_spills": s.kv_spills,
                "kv_revives": s.kv_revives,
                "kv_spill_evictions": s.kv_spill_evictions,
                "kv_spilled_pages": s.kv_spilled_pages,
                "kv_spill_bytes": s.kv_spill_bytes,
                "kv_host_bytes": s.kv_host_bytes,
                "kv_fetches_out": s.kv_fetches_out,
                "kv_fetches_in": s.kv_fetches_in,
                "kv_fetch_pages_out": s.kv_fetch_pages_out,
                "kv_fetch_pages_in": s.kv_fetch_pages_in,
                "kv_chains": list(self.engine.kv_chain_digest()),
                # grammar-constrained decoding (ISSUE 9): the
                # capability flag the gateway merges into /v1/models,
                # live constrained slots, window rollbacks (grammar
                # cuts), device mask patches, and the compiled-grammar
                # cache size
                "constrained_decoding":
                    self.engine.cfg.constrained_decoding,
                "capabilities": (dict(constrain.CAPABILITIES)
                                 if self.engine.cfg.constrained_decoding
                                 else {}),
                "constrained_slots": s.constrained_slots,
                "constraint_requests": s.constraint_requests,
                "constraint_rollbacks": s.constraint_rollbacks,
                "constraint_mask_updates": s.constraint_mask_updates,
                "constraint_grammars": s.constraint_grammars,
                # measured per-device memory (ISSUE 9 satellite): live
                # jax memory_stats() bytes (0 off-TPU) + KV-pool byte
                # occupancy — with `slice` below, the picker's
                # per-slice memory signal
                "device_bytes_in_use": s.device_bytes_in_use,
                "device_bytes_limit": s.device_bytes_limit,
                "device_memory_frac": s.device_memory_frac,
                "kv_pool_bytes": s.kv_pool_bytes,
                "kv_bytes_in_use": s.kv_bytes_in_use,
                # quantized KV pages (ISSUE 13): bits per stored
                # element, bytes one cached token costs across layers
                # (scales included), and the configured pool dtype —
                # the capacity math behind int8 ≈ 0.52x / int4 ≈ 0.27x
                # of the bf16 pool at head_dim 128
                "kv_quant_bits": s.kv_quant_bits,
                "kv_bytes_per_token": s.kv_bytes_per_token,
                "kv_cache_dtype": self.engine.cfg.kv_cache_dtype,
                "decode_backend": self.engine.cfg.decode_backend,
                # MoE serving surface (ISSUE 18): router placement /
                # capacity-drop scalars plus the per-expert token list
                # the picker prices (worst-expert discipline — a
                # replica is as fast as its hottest expert shard) and
                # the per-layer drop list. All-zero / empty on dense
                # families
                "moe_tokens_routed": s.moe_tokens_routed,
                "moe_tokens_dropped": s.moe_tokens_dropped,
                "moe_dropped_frac": s.moe_dropped_frac,
                "moe_expert_imbalance": s.moe_expert_imbalance,
                "moe_expert_load": self.engine.moe_expert_load(),
                "moe_layer_drops": self.engine.moe_layer_drops(),
                # mesh serving (ISSUE 10): real per-device signals —
                # the mesh topology (axis → size; {} off-mesh), EVERY
                # local device's memory/KV/param share (not just
                # device 0), the worst-device memory fraction the
                # picker scores, the measured per-device parameter
                # bytes (≈ total/tp under tensor parallelism — the
                # bench's memory-split claim), and the analytical ICI
                # collective volume per decoded token
                # long-context serving surface: the advertised context
                # length + sp axis (the gateway picker's over-length
                # filter rejects prompts no replica can hold, and its
                # predicted-TTFT model prices prompt length with the
                # measured per-token prefill rate), the sp prefill mode
                # actually routing, and the chunked/resume counters
                "max_seq_len": self.engine.cfg.max_seq_len,
                "sp": self.engine._sp,
                "sp_prefill_mode": (
                    "chunked"
                    if self.engine._prefill_sp_suffix_fn is not None
                    else "monolithic"
                    if self.engine._prefill_sp_fn is not None
                    else "off"),
                "sp_chunked_prefills": s.sp_chunked_prefills,
                "sp_resume_prefills": s.sp_resume_prefills,
                "sp_interactive_admits": s.sp_interactive_admits,
                "prefill_ms_per_token": round(
                    s.prefill_ms_per_token(), 4),
                "mesh_axes": self.engine.mesh_axes(),
                "mesh_devices": s.device_count,
                "devices": self.engine.device_stats,
                "device_count": s.device_count,
                "device_memory_frac_worst": s.device_memory_frac_worst,
                "param_bytes_total": sum(
                    self.engine.param_bytes_by_device.values()),
                "param_bytes_per_device": {
                    str(k): v for k, v in sorted(
                        self.engine.param_bytes_by_device.items())},
                "ici_bytes_per_token": s.ici_bytes_per_token,
                "ici_bytes_total": s.ici_bytes_total,
                # the resolved attention choices + WHY (the fallback
                # matrix, tpuserve/attention.py) and the migration
                # capability flag the gateway _Migrator respects
                "attention_backend_reason": getattr(
                    self.engine, "attn_reason", ""),
                "decode_attn_impl": self.engine.decode_attn_impl,
                "decode_attn_reason": self.engine.decode_attn_reason,
                "migration": self.engine.migratable,
                "active_slots": s.active_slots,
                "max_slots": self.engine.cfg.max_batch_size,
                "queued": s.queued,
                # priority-tiered serving (ISSUE 19): the offline class's
                # footprint. ``queued``/``queue_wait_ms`` above stay
                # interactive-only by construction (batch rides its own
                # engine queue) — the picker's predicted_ttft_ms never
                # prices batch backlog; its batch routing and the
                # controller's retire-drain read these instead
                "batch_queued": s.batch_queued,
                "batch_active": s.batch_active,
                "batch_preemptions": s.batch_preemptions,
                "batch_resumed": s.batch_resumed,
                "batch_tokens": s.batch_tokens,
                "batch_slot_frac": self.engine.cfg.batch_slot_frac,
                "queue_wait_ms": round(s.queue_wait_ms, 3),
                "kv_pages_free": s.kv_pages_free,
                "kv_occupancy": s.kv_occupancy,
                "tokens_generated": s.tokens_generated,
                "decode_steps": s.decode_steps,
                "decode_window": s.decode_window,
                "prefill_ms": round(s.prefill_ms, 3),
                "transfer_ms": round(s.transfer_ms, 3),
                "emit_ms": round(s.emit_ms, 3),
                "first_emit_ms": round(s.first_emit_ms, 3),
                # prefill attention backend + its padding tax (ISSUE 6):
                # real prompt tokens vs tokens the padded program
                # geometry processed; the ragged backend's claim is
                # padded_frac ≈ chunk residue instead of bucket residue
                "attention_backend": self.engine.attn.name,
                "prefill_tokens_real": s.prefill_tokens_real,
                "prefill_tokens_padded": s.prefill_tokens_padded,
                "prefill_padded_frac": s.prefill_padded_frac,
                # cold-start observables: wall time of warmup() and the
                # compiled hot-path program count it left behind
                "warmup_ms": s.warmup_ms,
                "warm_programs": s.warm_programs,
                # prefix-cache surface: the picker's prefix-affinity
                # scoring and capacity dashboards read these
                "prefix_cache_hit_rate": round(s.prefix_cache_hit_rate, 4),
                "prefix_pages_resident": s.prefix_pages_resident,
                "prefix_pages_pinned": s.prefix_pages_pinned,
                "prefix_bytes_pinned": (
                    s.prefix_pages_pinned * self.engine.kv_page_bytes),
                "prefix_cache_hits": s.prefix_cache_hits,
                "prefix_cache_misses": s.prefix_cache_misses,
                "prefix_cache_evictions": s.prefix_cache_evictions,
                # speculative decoding surface: acceptance telemetry
                # for dashboards and the bench --ab spec_decode leg
                "spec_accepted": s.spec_accepted,
                "spec_drafted": s.spec_drafted,
                "spec_accept_rate": round(s.spec_accept_rate, 4),
                "spec_draft_len": s.spec_draft_len,
                "spec_rung_ups": s.spec_rung_ups,
                "spec_rung_downs": s.spec_rung_downs,
                "spec_lookahead_slots": s.spec_lookahead_slots,
                # engine-truth usage metering (ISSUE 20): cumulative
                # MeterRecord totals — the gateway's usage ledger
                # reconciles its per-tenant sums against these counters
                # token-for-token (they only move inside _meter_emit,
                # the single record funnel)
                "meter_records": s.meter_records,
                "meter_prefill_tokens": s.meter_prefill_tokens,
                "meter_prefill_padded_tokens": s.meter_prefill_padded_tokens,
                "meter_prefix_reused_tokens": s.meter_prefix_reused_tokens,
                "meter_decode_tokens": s.meter_decode_tokens,
                "meter_spec_drafted": s.meter_spec_drafted,
                "meter_spec_accepted": s.meter_spec_accepted,
                "meter_hbm_page_byte_s": s.meter_hbm_page_byte_s,
                "meter_host_page_byte_s": s.meter_host_page_byte_s,
                "state_rebuilds": s.state_rebuilds,
                # XLA compile tracker (obs/xla_events.py): nonzero
                # growth after warmup = a hot-path compile regression
                "xla_compiles": s.xla_compiles,
                "xla_compile_ms": s.xla_compile_ms,
                # serving-phase latency distributions (p50/p95/p99 per
                # ENGINE_HISTOGRAMS phase; -1 = no observations yet) —
                # the bench reads TTFT/per-token spreads from here
                # instead of recomputing them client-side
                "phase_percentiles": self.engine.phases.percentiles(),
                # ICI topology: the picker's same-slice preference term
                # (gateway/picker.py) keys on this
                **device_topology(),
            }
        )

    async def _metrics(self, _request: web.Request) -> web.Response:
        # info-style gauge for the RESOLVED decode rung (the fallback
        # matrix outcome is a string; dashboards select on the label)
        impl_info = (
            "# TYPE tpuserve_decode_attn_impl gauge\n"
            f'tpuserve_decode_attn_impl{{impl='
            f'"{self.engine.decode_attn_impl}"}} 1\n').encode()
        body = (self.metrics.export()
                + render_engine_gauges(self.engine.stats)
                + impl_info
                + render_device_gauges(self.engine.device_stats)
                + render_moe_gauges(self.engine.moe_expert_load(),
                                    self.engine.moe_layer_drops())
                + self.engine.phases.render())
        return web.Response(body=body, content_type="text/plain")

    # -- KV memory hierarchy: cross-replica page fetch (ISSUE 11) ----------
    async def _kv_pages(self, request: web.Request) -> web.Response:
        """Serve KV pages by content chain hash to a sibling replica:
        resident pages travel through the pinned device→host export
        path, host-spilled pages straight from the spill tier — both on
        the PR 8 f32 page wire (b64 rows + shape). Keys this replica
        does not hold are simply absent from the response; the fetcher
        imports the leading contiguous run it got."""
        import base64

        try:
            body = oai.parse_json_body(await request.read())
        except oai.SchemaError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        raw_keys = body.get("keys")
        if not isinstance(raw_keys, list) or not raw_keys:
            return web.Response(
                status=400,
                body=oai.error_body("keys must be a non-empty list of "
                                    "hex chain hashes"),
                content_type="application/json")
        try:
            keys = [bytes.fromhex(str(k)) for k in
                    raw_keys[:KV_FETCH_MAX_PAGES]]
        except ValueError as e:
            return web.Response(
                status=400,
                body=oai.error_body(f"malformed chain hash: {e}"),
                content_type="application/json")
        try:
            out = await asyncio.to_thread(self.engine.kv_export_pages,
                                          keys)
        except (MigrationError, TimeoutError) as e:
            return web.Response(
                status=409, body=oai.error_body(str(e)),
                content_type="application/json")
        pages = [dict(encode_wire_page(d), key=k.hex()) for k, d in out]
        return web.json_response({
            "model": self.model_name,
            "page_size": self.engine.cfg.page_size,
            "pages": pages,
        })

    async def _maybe_fleet_fetch(self, request: web.Request,
                                 prompt: list[int],
                                 hashes: list | None) -> None:
        """Cross-replica KV fetch ahead of admission: when the gateway
        named sibling replicas that hold this prompt's chain
        (x-aigw-kv-peers) and the leading pages are missing locally,
        fetch them over /kv/pages and import them as cached chains —
        the admission probe then resumes instead of re-prefilling.
        Strictly best-effort: any failure falls back to cold prefill."""
        peers_hdr = request.headers.get(KV_PEERS_HEADER, "")
        eng = self.engine
        if (not peers_hdr or not hashes
                or eng.prefix_cache is None):
            return
        ps = eng.cfg.page_size
        # the wire rule (PR 8): only pages whose every row is written KV
        # travel — cap at the prompt's fully-written coverage
        usable = min(len(hashes), (len(prompt) - 1) // ps)
        present = set(eng.kv_chain_digest())
        miss = 0
        while miss < usable and hashes[miss].hex() in present:
            miss += 1
        if miss >= usable:
            return
        want = [h.hex() for h in hashes[miss:usable]]
        peers = [p.strip() for p in peers_hdr.split(",")
                 if p.strip()][:KV_PEERS_MAX]
        for peer in peers:
            got = await self._fetch_pages_from(peer, want)
            run: list[np.ndarray] = []
            for h in want:
                rows = got.get(h)
                if rows is None:
                    break  # leading contiguous run only
                run.append(rows)
            if not run:
                continue
            try:
                await asyncio.to_thread(eng.kv_import_pages, prompt,
                                        run, miss)
            except (MigrationError, TimeoutError) as e:
                logger.info("fleet KV import from %s failed: %s",
                            peer, e)
                return
            logger.info("fleet-fetched %d KV pages from %s", len(run),
                        peer)
            return

    async def _fetch_pages_from(self, peer: str,
                                keys_hex: list[str]) -> dict:
        """POST /kv/pages to one sibling; returns {key_hex: np rows}
        ({} on any error — the fetch is best-effort)."""
        import base64

        import aiohttp

        base = peer if "://" in peer else f"http://{peer}"
        if self._kv_session is None or self._kv_session.closed:
            self._kv_session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=KV_FETCH_TIMEOUT_S))
        try:
            async with self._kv_session.post(
                    base + "/kv/pages", json={"keys": keys_hex}) as resp:
                if resp.status != 200:
                    return {}
                data = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return {}
        out: dict = {}
        try:
            for p in data.get("pages") or ():
                out[str(p["key"])] = decode_wire_page(p)
        except (KeyError, TypeError, ValueError):
            return {}
        return out

    # -- prefill/decode disaggregation: KV page migration (ISSUE 8) --------
    async def _migrate_export(self, request: web.Request) -> web.Response:
        """Cut a live streaming session and return its wire blob: full
        KV pages (device→host via the engine's async-transfer path),
        chain hashes, and the slot's sampling/penalty/key state. The
        session's SSE stream ends without terminal frames; the caller
        splices the importing replica's continuation stream. A failed
        export leaves the session serving exactly as it was (409)."""
        import base64

        try:
            body = oai.parse_json_body(await request.read())
        except oai.SchemaError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        rid = str(body.get("request_id", ""))
        live = self._live.get(rid)
        if live is None:
            return web.Response(
                status=404,
                body=oai.error_body(
                    f"request {rid!r} is not an exportable live stream"),
                content_type="application/json")
        gen_req, meta = live
        try:
            out = await asyncio.to_thread(self.engine.migrate_export,
                                          gen_req)
        except (MigrationError, TimeoutError) as e:
            # the session keeps serving on this replica — 409 tells the
            # orchestrator "not now", not "broken"
            return web.Response(
                status=409, body=oai.error_body(str(e)),
                content_type="application/json")
        blob = out["blob"]
        blob["meta"] = meta
        pages = [encode_wire_page(d) for d in out["data"]]
        return web.json_response({"blob": blob, "pages": pages})

    async def _migrate_import(
        self, request: web.Request) -> web.StreamResponse:
        if self.draining:
            # a draining replica must not ADOPT sessions either — the
            # migration orchestrator reads 503 as "pick someone else"
            return self._drain_refusal()
        """Adopt an exported page chain and stream the session's
        continuation. The pages enter this replica's pool through the
        prefix-cache registration path (parked evictable, normal
        refcount/CoW discipline); the continuation request then admits
        as an offset resume against them — warm path end to end (the
        page scatters and resume programs are pre-compiled by
        warmup()). Frames carry the ORIGINAL response id, and usage
        counts the whole session (generated-so-far offset), so the
        gateway can splice this stream where the exporter's stopped."""
        import base64

        try:
            body = oai.parse_json_body(await request.read())
        except oai.SchemaError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        blob = body.get("blob") or {}
        try:
            tokens = [int(t) for t in blob["tokens"]]
            pages = [decode_wire_page(p)
                     for p in (body.get("pages") or ())]
        except (KeyError, TypeError, ValueError) as e:
            return web.Response(
                status=400,
                body=oai.error_body(f"malformed migration blob: {e}"),
                content_type="application/json")
        try:
            await asyncio.to_thread(self.engine.migrate_import, tokens,
                                    pages)
        except (MigrationError, TimeoutError) as e:
            if "OutOfPages" in str(e):
                # page pressure rides the normal overload contract
                return web.Response(
                    status=503, body=oai.error_body(str(e)),
                    headers={"retry-after": "1"},
                    content_type="application/json")
            return web.Response(
                status=400, body=oai.error_body(str(e)),
                content_type="application/json")

        meta = blob.get("meta") or {}
        rid = str(meta.get("response_id")
                  or f"chatcmpl-{uuid.uuid4().hex[:24]}")
        chat = bool(meta.get("chat", True))
        created = int(meta.get("created") or time.time())
        stop_strs = [s for s in (meta.get("stop_strs") or ())
                     if isinstance(s, str)]
        include_usage = bool(meta.get("include_usage", False))
        n_prev = int(blob.get("generated", 0))
        orig_len = int(blob.get("orig_prompt_len", len(tokens)))

        loop = asyncio.get_running_loop()
        out_q: asyncio.Queue = asyncio.Queue()
        meter_box: dict[str, Any] = {}

        def emit(tok: int, fin: str | None) -> None:
            loop.call_soon_threadsafe(out_q.put_nowait, (tok, fin))

        creq = continuation_request(blob, emit=emit)
        # single-metering across the splice (satellite): the exporter
        # emitted NO record at the cut; this continuation's terminal
        # record — fed by the blob's meter carry — covers the WHOLE
        # session, so the gateway's spliced stream meters exactly once
        creq.meter_sink = meter_box.update
        creq.prefix_hashes = self._prefix_hashes_for(creq.prompt)
        entry = self.flight.begin(
            rid, model=self.model_name, prompt_tokens=len(tokens),
            max_tokens=creq.max_tokens, stream=True)
        creq.trace = RequestTrace(entry=entry, tracer=self.tracer,
                                  span=None)
        rm = RequestMetrics(
            metrics=self.metrics,
            operation="chat" if chat else "text_completion",
            provider="tpuserve", request_model=self.model_name,
            response_model=self.model_name)
        try:
            self.engine.submit(creq)
        except EngineOverloadedError as e:
            return web.Response(
                status=429,
                body=oai.error_body(str(e), type_="rate_limit_error"),
                headers={"retry-after": "1"},
                content_type="application/json")
        except ValueError as e:
            return web.Response(status=400, body=oai.error_body(str(e)),
                                content_type="application/json")
        # the continuation itself is exportable again (chained moves)
        self._live[rid] = (creq, meta)

        resp = web.StreamResponse(
            status=200,
            headers={"content-type": "text/event-stream",
                     "cache-control": "no-cache",
                     "x-aigw-request-id": rid})
        set_tcp_nodelay(request.transport)
        await resp.prepare(request)
        decoder = StreamingDecoder(self.tokenizer)
        # prime the detokenizer with the generated-so-far tail: UTF-8
        # characters and stop strings spanning the migration seam
        # resolve exactly as they would have on the exporting replica
        emitted = ""
        for t in tokens[orig_len:]:
            emitted += decoder.push(t)

        async def write_piece(piece: str) -> None:
            if not piece:
                return
            if chat:
                await resp.write(oai.stream_chunk_sse(
                    response_id=rid, model=self.model_name,
                    created=created, delta={"content": piece}))
            else:
                await resp.write(SSEEvent(data=json.dumps({
                    "id": rid, "object": "text_completion",
                    "created": created, "model": self.model_name,
                    "choices": [{"index": 0, "text": piece,
                                 "finish_reason": None}],
                })).encode())

        n_out = 0
        finish = "stop"
        try:
            done = False
            while not done:
                first = await out_q.get()
                burst = [first]
                while True:
                    try:
                        burst.append(out_q.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                pieces: list[str] = []
                for tok, fin in burst:
                    if tok >= 0:
                        n_out += 1
                        rm.record_tokens_emitted(1)
                        piece = decoder.push(tok)
                        if piece:
                            emitted += piece
                            hit = _find_stop(emitted, stop_strs)
                            if hit is not None:
                                keep = hit - (len(emitted) - len(piece))
                                pieces.append(piece[:max(keep, 0)])
                                finish = "stop"
                                creq.cancelled.set()
                                done = True
                                break
                            pieces.append(piece)
                    if fin is not None:
                        finish = fin
                        if fin not in ("error", "migrated"):
                            pieces.append(decoder.flush())
                        done = True
                        break
                await write_piece("".join(pieces))
        except (asyncio.CancelledError, ConnectionResetError):
            creq.cancelled.set()
            self._end_trace(creq.trace, "cancelled", n_out, orig_len)
            raise
        usage = self._usage_from_meter(orig_len, n_prev + n_out,
                                       meter_box)
        rm.finish(usage)
        self._end_trace(creq.trace, finish, n_out, orig_len)
        if finish == "migrated":
            await resp.write_eof()  # moved again: next replica finishes
            return resp
        await resp.write(self._final_stream_frame(
            chat, rid, created, finish,
            usage if include_usage else None))
        await resp.write(SSEEvent(data="[DONE]").encode())
        await resp.write_eof()
        return resp

    # -- debug surface (flight recorder + profiler) -----------------------
    async def _debug_requests(self, _request: web.Request) -> web.Response:
        """Recent + slow request timelines from the flight recorder —
        answerable on any replica with no tracing backend attached."""
        return web.json_response(self.flight.snapshot())

    async def _debug_request(self, request: web.Request) -> web.Response:
        entry = self.flight.get(request.match_info["rid"])
        if entry is None:
            return web.Response(
                status=404,
                body=oai.error_body("unknown request id"),
                content_type="application/json")
        return web.json_response(entry.detail())

    #: hard cap on one /debug/profile capture window
    _PROFILE_MAX_SECONDS = 30.0

    async def _debug_profile(self, request: web.Request) -> web.Response:
        """On-demand ``jax.profiler`` capture: trace device+host activity
        for ?seconds=N into a fresh directory and return its path.
        Opt-in (``--enable-profile-endpoint``): a profiler on the data
        port is an inspection/DoS surface, so it 404s when disabled."""
        if not self._enable_profile:
            return web.Response(
                status=404,
                body=oai.error_body(
                    "profiling endpoint disabled (start tpuserve with "
                    "--enable-profile-endpoint)"),
                content_type="application/json")
        try:
            seconds = float(request.query.get("seconds", "2"))
        except ValueError:
            return web.Response(
                status=400, body=oai.error_body("seconds must be a number"),
                content_type="application/json")
        seconds = min(max(seconds, 0.1), self._PROFILE_MAX_SECONDS)
        if self._profile_lock.locked():
            return web.Response(
                status=409,
                body=oai.error_body("a profile capture is already running"),
                content_type="application/json")
        async with self._profile_lock:
            out_dir = tempfile.mkdtemp(prefix="tpuserve-profile-")

            def capture() -> None:
                jax.profiler.start_trace(out_dir)
                try:
                    time.sleep(seconds)
                finally:
                    jax.profiler.stop_trace()

            try:
                await asyncio.to_thread(capture)
            except Exception as e:  # noqa: BLE001 — profiler quirks must
                # surface as a client error, not a crashed replica
                return web.Response(
                    status=500,
                    body=oai.error_body(f"profiler capture failed: {e}",
                                        type_="server_error"),
                    content_type="application/json")
        return web.json_response(
            {"profile_dir": out_dir, "seconds": seconds})


async def run_tpuserve(
    model: str,
    host: str = "127.0.0.1",
    port: int = 8011,
    max_batch_size: int = 8,
    max_seq_len: int = 2048,
    page_size: int = 128,
    hbm_pages: int = 0,
    tp: int = 1,
    ep: int = 1,
    sp: int = 1,
    quantize: str = "",
    lora_adapters: dict | None = None,
    lora_slots: int = 0,
    tenant_slot_cap: int = 0,
    decode_steps_per_tick: int = 8,
    enable_prefix_cache: bool = True,
    sp_prefill_min_tokens: int = 1024,
    prefill_chunk_tokens: int = 256,
    spec_tokens: int = 0,
    spec_adaptive: bool = True,
    pallas_attn: bool = False,
    attention_backend: str = "xla-bucketed",
    decode_backend: str = "auto",
    kv_cache_dtype: str = "bfloat16",
    ragged_chunk_tokens: int = 256,
    logprobs_topk: int = 0,
    adaptive_decode_window: bool = True,
    async_transfers: bool = True,
    warm_prefill_buckets: int = 0,
    warm_decode_buckets: int = 0,
    first_token_fast_path: bool = True,
    prefill_bucket_rungs: int = 2,
    flight_entries: int = 256,
    enable_profile_endpoint: bool = False,
    migration_young_tokens: int = 64,
    constrained_decoding: bool = True,
    kv_host_bytes: int = 0,
) -> web.AppRunner:
    server = TPUServeServer(
        model,
        EngineConfig(
            max_batch_size=max_batch_size,
            max_seq_len=max_seq_len,
            page_size=page_size,
            num_pages=hbm_pages,
            decode_steps_per_tick=decode_steps_per_tick,
            enable_prefix_cache=enable_prefix_cache,
            sp_prefill_min_tokens=sp_prefill_min_tokens,
            prefill_chunk_tokens=prefill_chunk_tokens,
            spec_tokens=spec_tokens,
            spec_adaptive=spec_adaptive,
            pallas_attn=pallas_attn,
            attention_backend=attention_backend,
            decode_backend=decode_backend,
            kv_cache_dtype=kv_cache_dtype,
            ragged_chunk_tokens=ragged_chunk_tokens,
            logprobs_topk=logprobs_topk,
            adaptive_decode_window=adaptive_decode_window,
            async_transfers=async_transfers,
            warm_prefill_buckets=warm_prefill_buckets,
            warm_decode_buckets=warm_decode_buckets,
            first_token_fast_path=first_token_fast_path,
            prefill_bucket_rungs=prefill_bucket_rungs,
            tenant_slot_cap=tenant_slot_cap,
            migration_young_tokens=migration_young_tokens,
            constrained_decoding=constrained_decoding,
            kv_host_bytes=kv_host_bytes,
        ),
        tp=tp,
        ep=ep,
        sp=sp,
        quantize=quantize,
        lora_adapters=lora_adapters,
        lora_slots=lora_slots,
        flight_entries=flight_entries,
        enable_profile_endpoint=enable_profile_endpoint,
    )
    runner = web.AppRunner(server.app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    logger.info("tpuserve listening on %s:%d (model=%s)", host, port, model)
    return runner
