"""Token cost expression engine.

Equivalent of the reference's CEL-based cost engine
(``internal/llmcostcel/cel.go:32-71``): a cost expression is compiled once at
config load and evaluated per request with the variables

    model, backend, route_name,
    input_tokens, output_tokens, total_tokens,
    cached_input_tokens, cache_creation_input_tokens, reasoning_tokens

and must produce a non-negative integer cost.

Instead of CEL we compile a restricted Python expression: the AST is
whitelisted (arithmetic, comparisons, boolean ops, conditional expression,
min/max, variable names, numeric/string literals) so configuration can never
execute arbitrary code. This matches CEL's expressive envelope for the cost
use case while staying dependency-free.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from aigw_tpu.config.model import (
    Config,
    ConfigError,
    LLMRequestCost,
    LLMRequestCostType,
)


#: Variables available inside cost expressions (reference cel.go:32-49,
#: plus ``tenant`` — the multi-tenant accounting key the gateway derives
#: from the x-aigw-tenant header or the model's adapter suffix).
#:
#: The second block is the engine-truth meter surface: variables sourced
#: from the tpuserve ``MeterRecord`` a self-hosted response carries in
#: ``usage.aigw_meter``. They default to 0 (or "" for ``priority``) when
#: the backend is an external provider that meters nothing, so one cost
#: expression can price both paths.
COST_VARIABLES = (
    "model",
    "backend",
    "route_name",
    "tenant",
    "input_tokens",
    "output_tokens",
    "total_tokens",
    "cached_input_tokens",
    "cache_creation_input_tokens",
    "reasoning_tokens",
    # engine-truth meter variables (tpuserve MeterRecord)
    "prefill_padded_tokens",
    "prefix_reused_tokens",
    "decode_tokens",
    "spec_drafted_tokens",
    "spec_accepted_tokens",
    "kv_page_byte_seconds",
    "host_page_byte_seconds",
    "priority",
)

_ALLOWED_NODES = (
    ast.Expression,
    ast.BinOp,
    ast.UnaryOp,
    ast.BoolOp,
    ast.Compare,
    ast.IfExp,
    ast.Call,
    ast.Name,
    ast.Load,
    ast.Constant,
    ast.Add,
    ast.Sub,
    ast.Mult,
    ast.Div,
    ast.FloorDiv,
    ast.Mod,
    ast.Pow,
    ast.USub,
    ast.UAdd,
    ast.Not,
    ast.And,
    ast.Or,
    ast.Eq,
    ast.NotEq,
    ast.Lt,
    ast.LtE,
    ast.Gt,
    ast.GtE,
    ast.In,
    ast.NotIn,
    ast.Tuple,
)

_ALLOWED_FUNCS = {"min": min, "max": max, "int": int, "float": float, "abs": abs}

_MAX_UINT64 = (1 << 64) - 1


def meter_to_tuple(record: dict) -> tuple:
    """Flatten an engine MeterRecord dict into a hashable, order-stable
    tuple of ``(key, value)`` pairs for carriage inside ``TokenUsage``."""
    return tuple(sorted((str(k), v) for k, v in record.items()))


def meter_dict(usage: "TokenUsage") -> dict:
    """Inverse of :func:`meter_to_tuple` for the usage's meter payload."""
    return dict(usage.meter)


@dataclass(frozen=True)
class TokenUsage:
    """Cumulative token usage for one request.

    The reference accumulates usage with *override* semantics — the last
    usage chunk on a stream wins (extproc/processor_impl.go:556-574,
    metrics.TokenUsage). ``merge_override`` implements exactly that.

    ``meter`` carries the engine-truth MeterRecord (when the backend is
    tpuserve) as a sorted tuple of ``(key, value)`` pairs so the dataclass
    stays frozen/hashable; :func:`meter_dict` recovers the mapping.
    """

    input_tokens: int = 0
    output_tokens: int = 0
    total_tokens: int = 0
    cached_input_tokens: int = 0
    cache_creation_input_tokens: int = 0
    reasoning_tokens: int = 0
    meter: tuple = ()

    def merge_override(self, other: "TokenUsage") -> "TokenUsage":
        """Fields present (non-zero) in ``other`` override ours."""
        if other == TokenUsage():
            return self
        return TokenUsage(
            input_tokens=other.input_tokens or self.input_tokens,
            output_tokens=other.output_tokens or self.output_tokens,
            total_tokens=other.total_tokens or self.total_tokens,
            cached_input_tokens=other.cached_input_tokens
            or self.cached_input_tokens,
            cache_creation_input_tokens=other.cache_creation_input_tokens
            or self.cache_creation_input_tokens,
            reasoning_tokens=other.reasoning_tokens or self.reasoning_tokens,
            meter=other.meter or self.meter,
        )


class CostProgram:
    """A compiled cost expression (reference llmcostcel.NewProgram, cel.go:51)."""

    def __init__(self, expression: str):
        self.expression = expression
        try:
            tree = ast.parse(expression, mode="eval")
        except SyntaxError as e:
            raise ConfigError(f"invalid cost expression {expression!r}: {e}") from None
        for node in ast.walk(tree):
            if not isinstance(node, _ALLOWED_NODES):
                raise ConfigError(
                    f"cost expression {expression!r}: disallowed syntax "
                    f"{type(node).__name__}"
                )
            if isinstance(node, ast.Name):
                if node.id not in COST_VARIABLES and node.id not in _ALLOWED_FUNCS:
                    raise ConfigError(
                        f"cost expression {expression!r}: unknown variable "
                        f"{node.id!r}"
                    )
            if isinstance(node, ast.Call):
                if not (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _ALLOWED_FUNCS
                ):
                    raise ConfigError(
                        f"cost expression {expression!r}: only "
                        f"{sorted(_ALLOWED_FUNCS)} calls allowed"
                    )
        self._code = compile(tree, "<cost-expression>", "eval")
        # Smoke-evaluate at compile time so bad expressions fail at config
        # load, not per request (the reference does the same via a CEL
        # typecheck in NewProgram).
        self.evaluate(TokenUsage(), model="m", backend="b", route_name="r")

    def evaluate(
        self,
        usage: TokenUsage,
        *,
        model: str = "",
        backend: str = "",
        route_name: str = "",
        tenant: str = "",
    ) -> int:
        m = dict(usage.meter)
        env = {
            "__builtins__": {},
            "model": model,
            "backend": backend,
            "route_name": route_name,
            "tenant": tenant,
            "input_tokens": usage.input_tokens,
            "output_tokens": usage.output_tokens,
            "total_tokens": usage.total_tokens,
            "cached_input_tokens": usage.cached_input_tokens,
            "cache_creation_input_tokens": usage.cache_creation_input_tokens,
            "reasoning_tokens": usage.reasoning_tokens,
            "prefill_padded_tokens": m.get("prefill_padded", 0),
            "prefix_reused_tokens": m.get("prefix_reused", 0),
            "decode_tokens": m.get("decode_tokens", 0),
            "spec_drafted_tokens": m.get("spec_drafted", 0),
            "spec_accepted_tokens": m.get("spec_accepted", 0),
            "kv_page_byte_seconds": m.get("hbm_page_byte_s", 0.0),
            "host_page_byte_seconds": m.get("host_page_byte_s", 0.0),
            "priority": m.get("priority", ""),
            **_ALLOWED_FUNCS,
        }
        out = eval(self._code, env)  # noqa: S307 — AST whitelisted above
        cost = int(out)
        if cost < 0:
            raise ValueError(
                f"cost expression {self.expression!r} produced negative {cost}"
            )
        return min(cost, _MAX_UINT64)


class CostCalculator:
    """All compiled cost metrics for a config; produces the metadata map
    written at end-of-stream (reference extproc/util.go buildDynamicMetadata)."""

    def __init__(self, costs: tuple[LLMRequestCost, ...]):
        self._entries: list[tuple[LLMRequestCost, CostProgram | None]] = []
        for c in costs:
            prog = (
                CostProgram(c.expression)
                if c.cost_type is LLMRequestCostType.EXPRESSION
                else None
            )
            self._entries.append((c, prog))

    @staticmethod
    def from_config(cfg: Config) -> "CostCalculator":
        return CostCalculator(cfg.llm_request_costs)

    def calculate(
        self,
        usage: TokenUsage,
        *,
        model: str = "",
        backend: str = "",
        route_name: str = "",
        tenant: str = "",
    ) -> dict[str, int]:
        out: dict[str, int] = {}
        for cost, prog in self._entries:
            t = cost.cost_type
            if t is LLMRequestCostType.INPUT_TOKEN:
                v = usage.input_tokens
            elif t is LLMRequestCostType.OUTPUT_TOKEN:
                v = usage.output_tokens
            elif t is LLMRequestCostType.TOTAL_TOKEN:
                v = usage.total_tokens
            elif t is LLMRequestCostType.CACHED_INPUT_TOKEN:
                v = usage.cached_input_tokens
            elif t is LLMRequestCostType.CACHE_CREATION_INPUT_TOKEN:
                v = usage.cache_creation_input_tokens
            elif t is LLMRequestCostType.REASONING_TOKEN:
                v = usage.reasoning_tokens
            else:
                assert prog is not None
                v = prog.evaluate(
                    usage, model=model, backend=backend,
                    route_name=route_name, tenant=tenant,
                )
            out[cost.metadata_key] = v
        return out
