"""The native gateway data-plane server.

Replaces the reference's Envoy + ext_proc pair (internal/extproc/server.go,
processor_impl.go) with one native server that keeps the reference's
deepest design insight — the **two-phase processor**:

  Phase 1 (route selection): parse the body only enough to extract the
  model, stamp the model header, match a route. The original parsed body is
  captured. (≈ routerProcessor.ProcessRequestBody, processor_impl.go:213)

  Phase 2 (upstream, per attempt): against the finally-chosen backend,
  translate the captured body to the backend schema, apply header/body
  mutations, inject credentials, send. A retry/fallover constructs a fresh
  translator and re-translates from the captured body — which is what makes
  fallback *across schemas* work (processor_impl.go:73-131,334-339).

Streaming responses flow through the translator chunk-by-chunk with token
usage mined mid-stream; cost metadata is produced at end-of-stream and fed
to the quota/rate-limit engine (≈ Envoy dynamic metadata consumed by the
rate-limit filter, filterconfig.go:84-87).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Any, Callable

import aiohttp
from aiohttp import web

from aigw_tpu.config.model import (
    Config,
    DESTINATION_ENDPOINT_HEADER,
    MODEL_NAME_HEADER,
    ORIGINAL_PATH_HEADER,
    APISchemaName,
)
from aigw_tpu.config.runtime import RuntimeBackend, RuntimeConfig
from aigw_tpu.gateway.auth import AuthError
from aigw_tpu.gateway.circuit import CircuitBreaker
from aigw_tpu.gateway.controller import (
    ControllerConfig,
    FleetController,
    build_launcher,
)
from aigw_tpu.gateway.costs import TokenUsage
from aigw_tpu.gateway.fleetstate import (
    DecisionRing,
    merge_rollups,
    relabel_exposition,
)
from aigw_tpu.gateway.mutators import apply_body_mutation, apply_header_mutation
from aigw_tpu.gateway.picker import (
    ADAPTER_HEADER,
    AFFINITY_HEADER,
    KV_CHAIN_HEADER,
    KV_PEERS_HEADER,
    PREFIX_HEADER,
    PRIORITY_HEADER,
    PROMPT_TOKENS_HEADER,
    TENANT_HEADER,
    ContextLengthError,
    Endpoint as PickerEndpoint,
    EndpointPicker,
    SLOShedError,
)
from aigw_tpu.gateway.router import (
    BackendSelector,
    NoRouteError,
    match_route,
    split_model,
)
from aigw_tpu.gateway.usage import UsageLedger
from aigw_tpu.obs.metrics import (
    GenAIMetrics,
    RequestMetrics,
    render_controller_gauges,
    render_fleet_gauges,
    render_usage_gauges,
)
from aigw_tpu.obs.tracing import (
    DEFAULT_HEADER_ATTRIBUTES,
    Tracer,
    genai_attributes,
    header_attributes,
    parse_header_attribute_mapping,
)
from aigw_tpu.schemas import anthropic as anth
from aigw_tpu.schemas import openai as oai
from aigw_tpu.schemas import typed as typed_schemas
from aigw_tpu.schemas import typed_response
from aigw_tpu.translate import Endpoint, TranslationError, get_translator

logger = logging.getLogger(__name__)

#: endpoint path → (Endpoint, front schema, metrics operation)
_ENDPOINTS: dict[str, tuple[Endpoint, APISchemaName, str]] = {
    Endpoint.CHAT_COMPLETIONS.value: (
        Endpoint.CHAT_COMPLETIONS, APISchemaName.OPENAI, "chat"),
    Endpoint.COMPLETIONS.value: (
        Endpoint.COMPLETIONS, APISchemaName.OPENAI, "text_completion"),
    Endpoint.EMBEDDINGS.value: (
        Endpoint.EMBEDDINGS, APISchemaName.OPENAI, "embeddings"),
    Endpoint.MESSAGES.value: (
        Endpoint.MESSAGES, APISchemaName.ANTHROPIC, "chat"),
    Endpoint.TOKENIZE.value: (
        Endpoint.TOKENIZE, APISchemaName.OPENAI, "tokenize"),
    Endpoint.RESPONSES.value: (
        Endpoint.RESPONSES, APISchemaName.OPENAI, "responses"),
    Endpoint.IMAGES_GENERATIONS.value: (
        Endpoint.IMAGES_GENERATIONS, APISchemaName.OPENAI, "image_generation"),
    Endpoint.RERANK.value: (
        Endpoint.RERANK, APISchemaName.COHERE, "rerank"),
    Endpoint.AUDIO_SPEECH.value: (
        Endpoint.AUDIO_SPEECH, APISchemaName.OPENAI, "audio_speech"),
    Endpoint.AUDIO_TRANSCRIPTIONS.value: (
        Endpoint.AUDIO_TRANSCRIPTIONS, APISchemaName.OPENAI,
        "audio_transcription"),
    Endpoint.AUDIO_TRANSLATIONS.value: (
        Endpoint.AUDIO_TRANSLATIONS, APISchemaName.OPENAI,
        "audio_translation"),
}

#: endpoints whose request body is multipart/form-data, not JSON — these
#: pass through untranslated (model extracted from the form part; the
#: reference's ParseMultipartBody, endpointspec.go)
_MULTIPART_ENDPOINTS = {
    Endpoint.AUDIO_TRANSCRIPTIONS,
    Endpoint.AUDIO_TRANSLATIONS,
}


def _conversation_affinity_key(body: dict) -> str:
    """Key a conversation by its STABLE head — the system prompt(s) plus
    the first user message. Unlike the growing message prefix, the head is
    identical on every turn of one chat, so the picker can pin the
    conversation to the replica whose prefix cache holds it; distinct
    conversations differ in their first user message."""
    import hashlib as _hashlib
    import json as _json

    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        return ""
    head: list = []
    first_user = None
    for m in messages:
        if not isinstance(m, dict):
            return ""
        role = m.get("role")
        if role in ("system", "developer"):
            head.append(m)
        elif role == "user":
            first_user = m
            break
        else:
            break
    if first_user is None:
        return ""
    head.append(first_user)
    blob = _json.dumps(head, sort_keys=True).encode()
    return _hashlib.blake2b(blob, digest_size=12).hexdigest()


def _prefix_hash_key(body: dict) -> str:
    """Key the request's SHARED prompt prefix — the system/developer
    messages only. Unlike the conversation key (which includes the first
    user message and so is unique per chat), every request templated
    from the same system prompt shares this hash, so the picker can
    steer them toward the replica whose KV prefix cache already holds
    those pages (soft cache-affinity routing, gateway/picker.py)."""
    import hashlib as _hashlib
    import json as _json

    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        return ""
    head: list = []
    for m in messages:
        if not isinstance(m, dict):
            return ""
        if m.get("role") in ("system", "developer"):
            head.append(m)
        else:
            break
    if not head:
        return ""
    blob = _json.dumps(head, sort_keys=True).encode()
    return _hashlib.blake2b(blob, digest_size=12).hexdigest()


def _prompt_token_estimate(body: dict) -> int:
    """Conservative prompt-token estimate for the picker's
    context-length filter and prompt-priced TTFT model (long-context
    satellite). An explicit x-aigw-prompt-tokens header wins upstream
    of this; the estimate only needs the right order of magnitude:
    bytes/4 approximates BPE tokens and UNDER-estimates byte-level
    tokenizers, so a borderline prompt never draws a spurious gateway
    400 — it routes, and the replica's own over-length check still
    guards, exactly as before this filter existed."""
    n = 0
    prompt = body.get("prompt")
    if isinstance(prompt, str):
        n += len(prompt.encode("utf-8", errors="ignore"))
    messages = body.get("messages")
    if isinstance(messages, list):
        for m in messages:
            if not isinstance(m, dict):
                continue
            c = m.get("content")
            if isinstance(c, str):
                n += len(c.encode("utf-8", errors="ignore"))
            elif isinstance(c, list):
                for part in c:
                    if (isinstance(part, dict)
                            and isinstance(part.get("text"), str)):
                        n += len(part["text"].encode(
                            "utf-8", errors="ignore"))
    return n // 4


def _multipart_model(raw: bytes, content_type: str) -> str:
    """Extract the `model` form field from a multipart body without
    touching the (possibly large) audio parts. Boundary parsing is
    shared with the rewrite path (translate/multipart.py) so the
    extract and rewrite sides can never disagree on the framing."""
    from aigw_tpu.translate.multipart import parse_multipart_boundary

    b = parse_multipart_boundary(content_type)
    if not b:
        return ""
    boundary = b"--" + b.encode()
    for part in raw.split(boundary):
        header_end = part.find(b"\r\n\r\n")
        if header_end < 0:
            continue
        headers = part[:header_end]
        if b'name="model"' in headers:
            return (
                part[header_end + 4 :]
                .rstrip(b"\r\n-")
                .decode("utf-8", errors="replace")
                .strip()
            )
    return ""

#: upstream statuses that trigger failover to the next backend
_RETRIABLE_STATUS = {429, 500, 502, 503, 504}

CostSink = Callable[[dict[str, int], dict[str, str]], Any]


class _RawBody:
    """Non-JSON (multipart) request carried through phase 2 untranslated."""

    def __init__(self, raw: bytes, content_type: str, model: str):
        self.raw = raw
        self.content_type = content_type
        self.model = model


class GatewayServer:
    """aiohttp application hosting the full data plane."""

    def __init__(
        self,
        runtime: RuntimeConfig,
        *,
        metrics: GenAIMetrics | None = None,
        cost_sink: CostSink | None = None,
        tracer: Tracer | None = None,
    ):
        self._runtime = runtime
        self.metrics = metrics or GenAIMetrics()
        self.tracer = tracer or Tracer()
        # request-header → span-attribute mapping (reference
        # requestheaderattrs; default agent-session-id:session.id)
        self._header_attrs = parse_header_attribute_mapping(
            os.environ.get("AIGW_HEADER_ATTRIBUTES",
                           DEFAULT_HEADER_ATTRIBUTES)
        )
        self._cost_sink = cost_sink
        # OpenInference privacy knobs + structured access log (reference:
        # openinference/config.go env vars; Envoy access-log enrichment)
        from aigw_tpu.obs.accesslog import AccessLogger
        from aigw_tpu.obs.openinference import TraceConfig as OITraceConfig

        self._oi_config = OITraceConfig.from_env()
        self.access_log = AccessLogger()
        # circuit breaker unified with the fleet health machine (ISSUE
        # 14): keyed by backend name for logical backends AND by
        # replica address for picked endpoints; every open/close lands
        # in the replica's fleet event ring, and the picker's merged
        # routability view consults is_open — one failure-evidence
        # surface, not two that can disagree
        self.circuit = CircuitBreaker(
            on_transition=self._on_circuit_transition)
        #: optional () -> {key: condition} of NOT-Accepted objects, wired
        #: by the CLI when the config source is a reconciled manifest dir
        self.conditions_fn = None
        self._session: aiohttp.ClientSession | None = None
        self.app = web.Application(client_max_size=64 * 1024 * 1024)
        for path in _ENDPOINTS:
            self.app.router.add_post(path, self._handle)
        self.app.router.add_get("/v1/models", self._handle_models)
        self.app.router.add_get("/health", self._handle_health)
        self.app.router.add_get("/metrics", self._handle_metrics)
        # engine-truth usage metering (ISSUE 20): the per-tenant token
        # & KV-residency cost ledger + its query/export API
        self.app.router.add_get("/usage", self._handle_usage)
        self.usage_ledger = self._build_usage_ledger(runtime)
        # fleet observability plane (ISSUE 12): one pane of glass over
        # every picker-polled replica pool — aggregated health/SLO
        # state, Prometheus federation, and the routing-decision audit
        # ring (always on, like tpuserve's flight recorder: decisions
        # are the gateway's timelines and carry no credentials)
        self.app.router.add_get("/fleet/state", self._handle_fleet_state)
        self.app.router.add_get("/fleet/metrics",
                                self._handle_fleet_metrics)
        self.app.router.add_get("/debug/decisions",
                                self._handle_decisions)
        # offline batch tier (ISSUE 19): file upload + batch lifecycle
        # forwarded to a picker-chosen replica (batch priority — most
        # idle capacity); later polls follow the id → replica map so
        # submit/poll/fetch land on the replica that holds the state
        self.app.router.add_post("/v1/files", self._handle_file_upload)
        self.app.router.add_get("/v1/files/{fid}/content",
                                self._handle_batch_forward)
        self.app.router.add_post("/v1/batches",
                                 self._handle_batch_create)
        self.app.router.add_get("/v1/batches/{bid}",
                                self._handle_batch_forward)
        self.app.router.add_post("/v1/batches/{bid}/cancel",
                                 self._handle_batch_forward)
        self._batch_replica: dict[str, str] = {}
        self.decisions = DecisionRing(
            capacity=int(os.environ.get("AIGW_DECISION_RING", "512")))
        # debug/admin surface (reference: pprof :6060 + admin server on a
        # separate local port, internal/pprof/pprof.go:18-40). Off by
        # default on the data-plane port — any API client could otherwise
        # read thread stacks and config topology; opt in with
        # AIGW_ENABLE_DEBUG=true (e.g. when bound to localhost).
        if os.environ.get("AIGW_ENABLE_DEBUG", "").lower() == "true":
            self.app.router.add_get("/debug/config", self._handle_debug_config)
            self.app.router.add_get("/debug/stacks", self._handle_debug_stacks)
        self._pickers: dict[str, EndpointPicker] = {}
        self._picker_tasks: set[asyncio.Task] = set()
        # fleet control plane (ISSUE 14): one lifecycle manager per
        # backend pool that configures a `controller` block
        self._controllers: dict[str, FleetController] = {}
        self._build_pickers(runtime)
        self.app.on_startup.append(self._start_pickers)
        # MCP proxy is always registered (default path /mcp) so a config
        # hot-reload can add/change backends, filters, and authz without a
        # restart — only the HTTP *path* is fixed once the router freezes
        # (the reference hot-reloads MCPConfig through the same filterapi
        # bundle watcher as routes).
        from aigw_tpu.mcp import MCPConfig, MCPProxy
        from aigw_tpu.obs.metrics import MCPMetrics

        self.mcp = MCPProxy(
            MCPConfig.parse(runtime.config.mcp or {}),
            metrics=MCPMetrics(self.metrics.registry),
        )
        self.mcp.register(self.app)
        self.app.on_cleanup.append(self._cleanup)

    # -- lifecycle --------------------------------------------------------
    @property
    def runtime(self) -> RuntimeConfig:
        return self._runtime

    @staticmethod
    def _build_usage_ledger(runtime: RuntimeConfig) -> UsageLedger | None:
        """The metering ledger from the config's ``usage`` block.
        Metering is ON by default (no block = in-memory ledger with
        defaults); ``usage: {enabled: false}`` is the A/B off leg."""
        from aigw_tpu.config.model import _thaw

        raw = _thaw(runtime.config.usage) or {}
        if not isinstance(raw, dict):
            raw = {}
        if not raw.get("enabled", True):
            return None
        journal = str(raw.get("journal", "") or "")
        budgets = raw.get("budgets") or {}
        kwargs = dict(
            window_s=float(raw.get("window_s", 60.0)),
            retain_windows=int(raw.get("retain_windows", 64)),
            budgets={str(k): float(v) for k, v in budgets.items()},
            burn_windows=int(raw.get("burn_windows", 3)),
        )
        if journal:
            # crash-safe resume: replay what survived, keep appending
            return UsageLedger.replay(journal, **kwargs)
        return UsageLedger(**kwargs)

    def set_runtime(self, rc: RuntimeConfig) -> None:
        """Hot-swap config (called by ConfigWatcher). Pickers whose
        endpoint pools are unchanged are reused so telemetry and session
        affinity survive reloads."""
        if rc.config.usage != self._runtime.config.usage:
            # metering knobs changed: rebuild (a journal-backed ledger
            # replays itself, so totals survive the swap)
            old_ledger = self.usage_ledger
            self.usage_ledger = self._build_usage_ledger(rc)
            if old_ledger is not None:
                old_ledger.close()
        self._runtime = rc
        from aigw_tpu.mcp import MCPConfig

        self.mcp.update_config(MCPConfig.parse(rc.config.mcp or {}))
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        old = self._pickers
        old_ctl = self._controllers
        self._build_pickers(rc)
        if loop is not None:
            for name, ctl in old_ctl.items():
                if self._controllers.get(name) is not ctl:
                    self._spawn(loop, ctl.stop())
            for name, picker in old.items():
                if self._pickers.get(name) is not picker:
                    self._spawn(loop, picker.stop())
            for name, picker in self._pickers.items():
                if old.get(name) is not picker:
                    self._spawn(loop, picker.start())
            for name, ctl in self._controllers.items():
                if old_ctl.get(name) is not ctl:
                    self._spawn(loop, ctl.start())

    def _spawn(self, loop: asyncio.AbstractEventLoop, coro) -> None:
        # the loop holds tasks weakly; retain refs until completion
        task = loop.create_task(coro)
        self._picker_tasks.add(task)
        task.add_done_callback(self._picker_tasks.discard)

    def _build_pickers(self, rc: RuntimeConfig) -> None:
        from aigw_tpu.config.model import _thaw

        pickers: dict[str, EndpointPicker] = {}
        for name, rb in rc.backends.items():
            b = rb.backend
            if not b.endpoints:
                continue
            prev = self._pickers.get(name)
            key = (b.endpoints, b.picker_poll_interval, b.picker_mode,
                   b.slo_ttft_ms, b.fleet_obs, b.slo_objective,
                   b.slo_window_s, b.slo_burn_windows)
            if prev is not None and getattr(prev, "_config_key", None) == key:
                pickers[name] = prev  # unchanged pool: keep state
                continue
            picker = EndpointPicker(
                [PickerEndpoint.parse(_thaw(e)) for e in b.endpoints],
                poll_interval=b.picker_poll_interval,
                mode=b.picker_mode,
                slo_ttft_ms=b.slo_ttft_ms,
                fleet_obs=b.fleet_obs,
                slo_objective=b.slo_objective,
                slo_window_s=b.slo_window_s,
                slo_burn_windows=b.slo_burn_windows,
            )
            picker._config_key = key  # type: ignore[attr-defined]
            pickers[name] = picker
        self._pickers = pickers
        # the merged routability view: the picker consults the SAME
        # breaker the attempt loop feeds, keyed by replica address
        for picker in self._pickers.values():
            picker.breaker = self.circuit
        self._build_controllers(rc)

    def _build_controllers(self, rc: RuntimeConfig) -> None:
        from aigw_tpu.config.model import _thaw

        controllers: dict[str, FleetController] = {}
        for name, rb in rc.backends.items():
            raw = rb.backend.controller
            picker = self._pickers.get(name)
            if raw is None or picker is None:
                continue
            cfg = ControllerConfig.parse(_thaw(raw))
            if not cfg.enabled:
                continue
            prev = self._controllers.get(name)
            if (prev is not None and prev.picker is picker
                    and getattr(prev, "_config_raw", None) == raw):
                controllers[name] = prev  # unchanged: keep its state
                continue
            ctl = FleetController(
                picker=picker, cfg=cfg,
                launcher=build_launcher(cfg.launcher),
                decisions=self.decisions, backend=name)
            ctl._config_raw = raw  # type: ignore[attr-defined]
            controllers[name] = ctl
        self._controllers = controllers

    async def _start_pickers(self, _app) -> None:
        for picker in self._pickers.values():
            await picker.start()
        for ctl in self._controllers.values():
            await ctl.start()

    def _on_circuit_transition(self, key: str, opened: bool,
                               failures: int) -> None:
        """Breaker open/close → the fleet event ring of whichever pool
        knows this key as a replica address (ISSUE 14 unification).
        Backend-name keys have no replica entry and are skipped."""
        for picker in self._pickers.values():
            if key in picker.state:
                picker.fleet.mark_breaker(key, opened, failures)

    async def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                auto_decompress=True,
                timeout=aiohttp.ClientTimeout(total=None),
            )
        return self._session

    async def _cleanup(self, _app: web.Application) -> None:
        for ctl in self._controllers.values():
            # stops the control loop AND terminates launcher-owned
            # replica processes — shutdown must not orphan children
            await ctl.stop()
        for picker in self._pickers.values():
            await picker.stop()
        if self._session is not None and not self._session.closed:
            await self._session.close()
        if self.usage_ledger is not None:
            self.usage_ledger.close()

    # -- admin endpoints --------------------------------------------------
    async def _handle_health(self, _request: web.Request) -> web.Response:
        payload = {
            "status": "ok",
            "uuid": self._runtime.config.uuid,
            "circuit": self.circuit.snapshot(),
        }
        # reconciling control plane: surface quarantined objects so an
        # operator doesn't have to know to cat aigw-status.json (the
        # reference shows the same conditions via `kubectl get`)
        if self.conditions_fn is not None:
            bad = self.conditions_fn()
            payload["objects_not_accepted"] = len(bad)
            if bad:
                payload["not_accepted"] = sorted(bad)
        return web.json_response(payload)

    async def _handle_metrics(self, _request: web.Request) -> web.Response:
        body = self.metrics.export()
        if self.usage_ledger is not None:
            body += render_usage_gauges(self.usage_ledger.snapshot())
        return web.Response(body=body, content_type="text/plain")

    async def _handle_usage(self, request: web.Request) -> web.Response:
        """``GET /usage`` (ISSUE 20): the metering ledger's windowed
        per-tenant/per-model view. Query params: ``since`` (unix ts),
        ``tenant``, ``model`` filter the windows; ``export=jsonl``
        streams the filtered windows as JSON lines instead (the bulk
        export a billing pipeline ingests)."""
        if self.usage_ledger is None:
            return web.json_response(
                {"error": "usage metering disabled"}, status=404)
        try:
            since = float(request.query.get("since", "0") or 0.0)
        except ValueError:
            since = 0.0
        payload = self.usage_ledger.query(
            since=since,
            tenant=request.query.get("tenant", ""),
            model=request.query.get("model", ""),
        )
        if request.query.get("export", "") == "jsonl":
            body = "".join(json.dumps(w, sort_keys=True) + "\n"
                           for w in payload["windows"])
            return web.Response(body=body.encode(),
                                content_type="application/jsonl")
        return web.json_response(payload)

    # -- offline batch tier (ISSUE 19) ------------------------------------
    #: bound on the (file/batch id → replica) routing map
    _BATCH_MAP_MAX = 10_000

    def _batch_pick(self) -> str | None:
        """A replica for NEW batch state: the first configured pool's
        batch-priority pick — most idle capacity, never SLO-shed (the
        picker's batch branch skips admission control entirely)."""
        for _name, picker in sorted(self._pickers.items()):
            dest = picker.pick({PRIORITY_HEADER: "batch"})
            if dest:
                return dest
        return None

    def _remember_batch(self, obj_id: str, addr: str) -> None:
        self._batch_replica[obj_id] = addr
        while len(self._batch_replica) > self._BATCH_MAP_MAX:
            self._batch_replica.pop(next(iter(self._batch_replica)))

    async def _proxy_batch(self, request: web.Request, addr: str,
                           raw: bytes | None = None
                           ) -> tuple[int, bytes, str]:
        """Forward one batch-surface request to its replica verbatim;
        (status, body, content_type) — upstream failures map to 502."""
        session = await self._get_session()
        if raw is None:
            raw = await request.read()
        try:
            async with session.request(
                    request.method, f"http://{addr}{request.path}",
                    data=raw,
                    headers={"content-type": request.headers.get(
                        "content-type", "application/json")},
                    timeout=aiohttp.ClientTimeout(total=60.0)) as resp:
                return (resp.status, await resp.read(),
                        resp.content_type or "application/json")
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            return (502,
                    error_body(f"batch replica {addr} unreachable: {e}",
                               type_="server_error"),
                    "application/json")

    async def _handle_file_upload(self, request: web.Request
                                  ) -> web.Response:
        dest = self._batch_pick()
        if dest is None:
            return web.Response(
                status=503,
                body=error_body("no replica available for batch work",
                                type_="server_error"),
                content_type="application/json")
        status, body, ctype = await self._proxy_batch(request, dest)
        if status == 200:
            try:
                fid = str(json.loads(body).get("id", ""))
            except ValueError:
                fid = ""
            if fid:
                self._remember_batch(fid, dest)
        return web.Response(status=status, body=body,
                            content_type=ctype)

    async def _handle_batch_create(self, request: web.Request
                                   ) -> web.Response:
        """POST /v1/batches — lands on the replica already holding the
        input file (the id → replica map); a miss falls back to a fresh
        batch pick, where the unknown file id 404s honestly."""
        raw = await request.read()
        try:
            fid = str(json.loads(raw).get("input_file_id", ""))
        except ValueError:
            fid = ""
        dest = self._batch_replica.get(fid) or self._batch_pick()
        if dest is None:
            return web.Response(
                status=503,
                body=error_body("no replica available for batch work",
                                type_="server_error"),
                content_type="application/json")
        status, body, ctype = await self._proxy_batch(request, dest,
                                                      raw=raw)
        if status == 200:
            try:
                bid = str(json.loads(body).get("id", ""))
            except ValueError:
                bid = ""
            if bid:
                self._remember_batch(bid, dest)
        return web.Response(status=status, body=body,
                            content_type=ctype)

    async def _handle_batch_forward(self, request: web.Request
                                    ) -> web.Response:
        """Poll / cancel / output fetch — follows the id → replica
        map (batch state is replica-local by design)."""
        oid = (request.match_info.get("bid")
               or request.match_info.get("fid") or "")
        dest = self._batch_replica.get(oid)
        if dest is None:
            return web.Response(
                status=404,
                body=error_body(f"unknown batch object {oid!r}"),
                content_type="application/json")
        status, body, ctype = await self._proxy_batch(request, dest)
        if status == 200 and request.match_info.get("bid"):
            # learn the output file id from poll bodies so the later
            # GET /v1/files/{ofid}/content resolves to the same replica
            try:
                ofid = str(json.loads(body).get("output_file_id")
                           or "")
            except ValueError:
                ofid = ""
            if ofid:
                self._remember_batch(ofid, dest)
        return web.Response(status=status, body=body,
                            content_type=ctype)

    # -- fleet observability plane (ISSUE 12) -----------------------------
    async def _handle_fleet_state(self, _request: web.Request
                                  ) -> web.Response:
        """Aggregated fleet snapshot: per-replica health machine state
        + event rings + staleness stamps + key gauges, per-backend
        rollups, and the live SLO burn-rate windows — one pane of glass
        over every picker-polled pool."""
        backends = {
            name: picker.fleet.snapshot(picker.state)
            for name, picker in self._pickers.items()
        }
        for name, ctl in self._controllers.items():
            if name in backends:
                # lifecycle manager state (ISSUE 14): scaling decisions,
                # drains in progress, and the bounded action ring
                backends[name]["controller"] = ctl.snapshot()
        return web.json_response({
            "ts": round(time.time(), 3),
            "backends": backends,
            "fleet": merge_rollups(
                [b["rollup"] for b in backends.values()]),
            "decisions_recorded": self.decisions.recorded,
        })

    async def _handle_fleet_metrics(self, _request: web.Request
                                    ) -> web.Response:
        """Prometheus federation: every replica's ``tpuserve_*``
        samples re-exported with a ``replica`` label (histograms,
        per-device gauges and exemplars included) plus the
        ``aigw_fleet_*`` rollup gauges — one scrape covers the fleet."""
        session = await self._get_session()
        chunks: list[bytes] = []
        seen: set = set()
        errors = 0

        async def scrape(addr: str) -> str | None:
            try:
                async with session.get(
                    f"http://{addr}/metrics",
                    timeout=aiohttp.ClientTimeout(total=2.0),
                ) as resp:
                    if resp.status != 200:
                        return None
                    return (await resp.read()).decode(
                        "utf-8", errors="replace")
            except (aiohttp.ClientError, asyncio.TimeoutError):
                return None

        for name, picker in self._pickers.items():
            addrs = [e.address for e in picker.endpoints
                     if picker.fleet.health_of(e.address) != "down"]
            texts = await asyncio.gather(*(scrape(a) for a in addrs))
            for addr, text in zip(addrs, texts):
                if text is None:
                    errors += 1
                    continue
                chunks.append(
                    relabel_exposition(text, addr, seen).encode())
            label = name if len(self._pickers) > 1 else ""
            chunks.append(render_fleet_gauges(
                picker.fleet.rollup(picker.state), backend=label))
            ctl = self._controllers.get(name)
            if ctl is not None:
                chunks.append(render_controller_gauges(
                    ctl.gauge_values(), backend=label))
        chunks.append(
            b"# TYPE aigw_fleet_scrape_errors gauge\n"
            b"aigw_fleet_scrape_errors %d\n" % errors)
        return web.Response(body=b"".join(chunks),
                            content_type="text/plain")

    async def _handle_decisions(self, request: web.Request
                                ) -> web.Response:
        """The routing-decision audit ring: every pick's full explain
        (candidates, scores, predicted-TTFT map, affinity terms), shed
        events with their Retry-After, and migration stamps — filter
        with ``?rid=<x-aigw-request-id>`` to join one decision against
        the serving replica's /debug/requests/{id} timeline."""
        rid = request.query.get("rid", "")
        try:
            limit = max(1, min(1000, int(
                request.query.get("limit", "100"))))
        except ValueError:
            limit = 100
        return web.json_response({
            "capacity": self.decisions.capacity,
            "recorded": self.decisions.recorded,
            "decisions": self.decisions.snapshot(rid=rid, limit=limit),
        })

    async def _handle_models(self, request: web.Request) -> web.Response:
        """/v1/models — configured models, host-scoped like the
        reference's ModelsByHost (models_processor.go:30-150): models whose
        serving routes are restricted to other hostnames are hidden.
        The listing also carries the model ZOO (ISSUE 7): every
        ``<base>:<adapter>`` name the picker-polled tpuserve replicas
        report on /state whose base model routes here — so clients
        discover servable adapters without per-adapter config entries."""
        rc = self._runtime
        host = request.host.split(":")[0].lower()
        visible_rules = [
            rule for route in rc.routes_for_host(host) for rule in route.rules
        ]

        def visible(name: str) -> bool:
            base = split_model(name)[0]
            for probe_name in ({name, base}):
                probe = {MODEL_NAME_HEADER: probe_name}
                if any(r.matches(probe) for r in visible_rules):
                    return True
            return False

        # structured-output / tool-calling capability flags (ISSUE 9):
        # replicas that enforce constraints natively report them on
        # /state; the merged listing carries them per served base model
        caps_by_model: dict[str, dict] = {}
        for picker in self._pickers.values():
            for st in picker.state.values():
                if st.healthy and st.model and st.capabilities:
                    caps_by_model[st.model] = dict(st.capabilities)

        def extra_for(name: str):
            caps = caps_by_model.get(split_model(name)[0])
            return {"capabilities": caps} if caps else None

        entries: list[tuple] = [
            (m.name, m.owned_by, m.created_at, extra_for(m.name))
            for m in rc.config.models
            if visible(m.name)
        ]
        seen = {e[0] for e in entries}
        for picker in self._pickers.values():
            for st in picker.state.values():
                if not (st.healthy and st.model):
                    continue
                for adapter in st.adapters_registered:
                    name = f"{st.model}:{adapter}"
                    if name not in seen and visible(name):
                        seen.add(name)
                        entries.append((name, "aigw-tpu-lora", 0,
                                        extra_for(name)))
        return web.json_response(oai.models_response(entries))

    async def _handle_debug_config(self, _request: web.Request) -> web.Response:
        """Redacted view of the live config (credentials masked)."""
        import json as _json

        from aigw_tpu.utils.redaction import SENSITIVE_HEADERS  # noqa: F401

        cfg = self._runtime.config.to_dict()
        for b in cfg.get("backends", ()):
            if "auth" in b:
                b["auth"] = {"kind": b["auth"].get("kind", "?"),
                             "credentials": "[REDACTED]"}
        if "mcp" in cfg and isinstance(cfg["mcp"], dict):
            cfg["mcp"] = dict(cfg["mcp"])
            cfg["mcp"].pop("session_seed", None)
            cfg["mcp"].pop("session_fallback_seed", None)
        return web.json_response(cfg)

    async def _handle_debug_stacks(self, _request: web.Request) -> web.Response:
        """Thread stack dump — the pprof-goroutine equivalent."""
        import sys as _sys
        import traceback as _tb

        out = []
        for tid, frame in _sys._current_frames().items():
            out.append(f"--- thread {tid} ---")
            out.extend(_tb.format_stack(frame))
        return web.Response(text="\n".join(out),
                            content_type="text/plain")

    def _log_rejection(
        self, request: web.Request, status: int, started: float,
        model: str = "", reason: str = "",
    ) -> None:
        """Access-log line for requests rejected before the attempt loop
        (schema 400s, unknown-model 404s) — the lines operators grep for
        when debugging client misconfiguration."""
        if not self.access_log.enabled:
            return
        from aigw_tpu.obs.openinference import error_type_for_status

        self.access_log.log(
            method=request.method,
            path=request.path,
            status=status,
            duration_ms=(time.monotonic() - started) * 1000.0,
            model=model,
            error_type=reason or error_type_for_status(status),
            client=request.remote or "",
            request_id=request.headers.get("x-request-id", ""),
        )

    # -- the data plane ---------------------------------------------------
    async def _handle(self, request: web.Request) -> web.StreamResponse:
        endpoint, front_schema, operation = _ENDPOINTS[request.path]
        rc = self._runtime  # pin the config for this request
        started = time.monotonic()
        error_body = (
            anth.error_body
            if front_schema is APISchemaName.ANTHROPIC
            else oai.error_body
        )
        try:
            raw = await request.read()
        except (aiohttp.web.RequestPayloadError,
                aiohttp.http_exceptions.HttpProcessingError) as e:
            # e.g. a corrupt gzip request body fails the server-side
            # inflater mid-read — that's the client's 400, not our 500
            self._log_rejection(request, 400, started,
                                reason="bad_request_body")
            return web.Response(
                status=400,
                body=error_body(f"unreadable request body: {e}"),
                content_type="application/json")
        # compressed request bodies (reference: extproc decodes encoded
        # bodies before translation, util.go decodeContentIfNeeded; the
        # inference-extension conformance drives gzipped JSON).
        # aiohttp's server layer transparently inflates supported
        # codings and 400s unsupported/corrupt ones at read time (the
        # try/except above); this fallback only fires when gzip bytes
        # reach us undecoded (magic 1f 8b — e.g. behind a raw
        # transport). The translated upstream body is re-serialized, so
        # the encoding is consumed and never forwarded.
        enc = request.headers.get("content-encoding", "").lower().strip()
        if enc == "gzip" and raw[:2] == b"\x1f\x8b":
            import gzip as _gzip
            import zlib as _zlib

            try:
                raw = _gzip.decompress(raw)
            except (OSError, EOFError, _zlib.error):
                self._log_rejection(request, 400, started,
                                    reason="bad_encoding")
                return web.Response(
                    status=400,
                    body=error_body("invalid gzip request body"),
                    content_type="application/json")
        elif enc and enc not in ("identity", "gzip", "deflate"):
            # aiohttp transparently inflates gzip/deflate (and br when
            # the Brotli package exists); any OTHER declared coding
            # reaches this handler UNDECODED on this aiohttp — parsing
            # those raw bytes as JSON would be a silent mis-read, so
            # it's the client's 400 (the inference-extension
            # conformance contract: undecodable encodings are 400s,
            # never 500s or accidental 200s)
            try:
                from aiohttp.compression_utils import HAS_BROTLI
            except ImportError:  # pragma: no cover — old aiohttp
                HAS_BROTLI = False
            if not (enc == "br" and HAS_BROTLI):
                self._log_rejection(request, 400, started,
                                    reason="bad_encoding")
                return web.Response(
                    status=400,
                    body=error_body(
                        f"unsupported content-encoding: {enc}"),
                    content_type="application/json")
        # ---- phase 1: route selection ----------------------------------
        if endpoint in _MULTIPART_ENDPOINTS:
            ctype = request.headers.get("content-type", "")
            model = _multipart_model(raw, ctype)
            if not model:
                self._log_rejection(request, 400, started,
                                    reason="missing_model")
                return web.Response(
                    status=400,
                    body=error_body("missing 'model' form field"),
                    content_type="application/json")
            body: Any = _RawBody(raw, ctype, model)
        else:
            try:
                body = oai.parse_json_body(raw)
                model = oai.request_model(body)
                if endpoint is Endpoint.MESSAGES:
                    anth.validate_messages_request(body)
                else:
                    # typed per-endpoint schemas incl. chat vendor fields
                    # (schemas/typed.py; reference apischema rejects
                    # malformed bodies before any upstream traffic)
                    typed_schemas.validate_request(endpoint.value, body)
            except oai.SchemaError as e:
                self._log_rejection(request, 400, started,
                                    reason="invalid_request")
                return web.Response(
                    status=400, body=error_body(str(e)),
                    content_type="application/json")
        client_headers = {k.lower(): v for k, v in request.headers.items()}
        # multi-tenant accounting key (ISSUE 7): an explicit tenant
        # header wins; adapter-suffixed zoo names ("llama-3-8b:tenant-a")
        # default to per-adapter tenancy. Injected into client_headers so
        # tenant-keyed quota rules (client_key_header: x-aigw-tenant),
        # the end-of-stream cost sink, and the upstream relay all key on
        # ONE consistent tenant.
        tenant = client_headers.get(TENANT_HEADER, "") or \
            split_model(model)[1]
        if tenant:
            client_headers[TENANT_HEADER] = tenant
        match_headers = {
            **client_headers,
            MODEL_NAME_HEADER: model,
            ORIGINAL_PATH_HEADER: request.path,
        }
        try:
            match = match_route(rc, request.host, match_headers)
        except NoRouteError:
            self._log_rejection(request, 404, started, model=model,
                                reason="model_not_found")
            return web.Response(
                status=404,
                body=error_body(
                    f"model {model!r} is not served by this gateway",
                    type_="model_not_found" if front_schema is APISchemaName.OPENAI
                    else "not_found_error",
                ),
                content_type="application/json",
            )

        req_metrics = RequestMetrics(
            metrics=self.metrics, operation=operation, request_model=model
        )
        selector = BackendSelector(rule=match.rule, circuit=self.circuit)
        route_name = match.route.name

        # tracing: continue the caller's trace, span per gateway request
        # (reference: router processor starts the span and injects headers,
        # processor_impl.go:289-295)
        span = None
        if self.tracer.enabled:
            # OTEL_PROPAGATORS-configured extraction (W3C + B3 variants)
            parent = self.tracer.propagators.extract(client_headers)
            span = self.tracer.start_span(f"{operation} {model}", parent)
            span.attributes.update(
                header_attributes(client_headers, self._header_attrs)
            )
            if isinstance(body, dict):
                span.attributes.update(
                    self._openinference_request_attrs(endpoint, body, raw)
                )

        # ---- phase 2: upstream attempts --------------------------------
        status = 500
        try:
            resp_out = await self._attempt_loop(
                request, endpoint, front_schema, selector, rc, body,
                req_metrics, route_name, error_body, client_headers, span,
            )
            status = resp_out.status
            return resp_out
        finally:
            if span is not None:
                span.attributes.update(
                    genai_attributes(
                        operation=operation,
                        request_model=model,
                        response_model=req_metrics.response_model,
                        backend=req_metrics.provider,
                        input_tokens=req_metrics.final_usage.input_tokens,
                        output_tokens=req_metrics.final_usage.output_tokens,
                        streaming=req_metrics.tokens_seen > 0,
                    )
                )
                if req_metrics.error_type:
                    span.record_error(req_metrics.error_type)
                span.end()
            if self.access_log.enabled:
                from aigw_tpu.obs.openinference import error_type_for_status

                err = req_metrics.error_type
                if err.isdigit():
                    err = error_type_for_status(int(err))
                self.access_log.log(
                    method=request.method,
                    path=request.path,
                    status=status,
                    duration_ms=(time.monotonic()
                                 - req_metrics.start) * 1000.0,
                    route=route_name,
                    backend=req_metrics.provider,
                    model=model,
                    response_model=req_metrics.response_model,
                    stream=req_metrics.tokens_seen > 0,
                    input_tokens=req_metrics.final_usage.input_tokens,
                    output_tokens=req_metrics.final_usage.output_tokens,
                    total_tokens=req_metrics.final_usage.total_tokens,
                    cached_tokens=(
                        req_metrics.final_usage.cached_input_tokens),
                    costs=req_metrics.costs,
                    error_type=err,
                    client=request.remote or "",
                    trace_id=(span.context.trace_id
                              if span is not None else ""),
                    span_id=(span.context.span_id
                             if span is not None else ""),
                    request_id=client_headers.get("x-request-id", ""),
                    upstream_request_id=req_metrics.upstream_request_id,
                    attempts=req_metrics.attempts,
                    decision=req_metrics.decision,
                )

    def _openinference_request_attrs(
        self, endpoint: Endpoint, body: dict[str, Any], raw: bytes
    ) -> dict[str, Any]:
        from aigw_tpu.obs import openinference as oi

        try:
            if endpoint is Endpoint.CHAT_COMPLETIONS:
                return oi.chat_request_attributes(
                    body, raw, self._oi_config)
            if endpoint is Endpoint.MESSAGES:
                return oi.chat_request_attributes(
                    body, raw, self._oi_config,
                    system=oi.LLM_SYSTEM_ANTHROPIC)
            if endpoint is Endpoint.EMBEDDINGS:
                return oi.embeddings_request_attributes(
                    body, raw, self._oi_config)
            if endpoint is Endpoint.COMPLETIONS:
                return oi.completion_request_attributes(
                    body, raw, self._oi_config)
            if endpoint is Endpoint.RERANK:
                return oi.rerank_request_attributes(
                    body, raw, self._oi_config)
        except Exception:  # noqa: BLE001 — telemetry must never 500
            logger.debug("openinference request attrs failed",
                         exc_info=True)
        return {}

    def _oi_response_builder(self, endpoint: Endpoint):
        """One endpoint→builder dispatch for both the unary and
        streaming span-attribute paths (endpoint MESSAGES ⇔ the
        Anthropic front)."""
        from aigw_tpu.obs import openinference as oi

        return {
            Endpoint.CHAT_COMPLETIONS: oi.chat_response_attributes,
            Endpoint.MESSAGES: oi.anthropic_response_attributes,
            Endpoint.EMBEDDINGS: oi.embeddings_response_attributes,
            Endpoint.COMPLETIONS: oi.completion_response_attributes,
            Endpoint.RERANK: oi.rerank_response_attributes,
        }.get(endpoint)

    def _openinference_response_attrs(
        self, span, endpoint: Endpoint, payload: bytes,
    ) -> None:
        builder = self._oi_response_builder(endpoint)
        if builder is None:
            return
        try:
            resp = json.loads(payload)
            if not isinstance(resp, dict):
                return
            span.attributes.update(builder(resp, self._oi_config))
        except Exception:  # noqa: BLE001 — telemetry must never 500
            logger.debug("openinference response attrs failed",
                         exc_info=True)

    async def _attempt_loop(
        self, request, endpoint, front_schema, selector, rc, body,
        req_metrics, route_name, error_body, client_headers, span,
    ) -> web.StreamResponse:
        last_error: tuple[int, bytes] = (
            502,
            error_body("all upstream backends failed",
                       type_="upstream_error"),
        )
        attempt = 0
        while True:
            ref = selector.next_backend()
            if ref is None:
                break
            rb = rc.backends[ref.backend]
            if attempt > 0:
                self.metrics.retries_total.labels(route_name, rb.backend.name).inc()
            attempt += 1
            req_metrics.attempts = attempt
            req_metrics.provider = rb.backend.name
            try:
                result = await self._attempt(
                    request, endpoint, front_schema, rb, body,
                    req_metrics, route_name, error_body, client_headers,
                    span,
                )
            except _RetriableUpstreamError as e:
                logger.warning(
                    "backend %s failed (%s), trying next", rb.backend.name, e
                )
                if e.count_failure:
                    self.circuit.record_failure(rb.backend.name)
                last_error = (e.status, e.client_body)
                self.metrics.requests_total.labels(
                    route_name, rb.backend.name, str(e.status)
                ).inc()
                continue
            except AuthError as e:
                req_metrics.finish(TokenUsage(), error_type="auth")
                return web.Response(
                    status=401, body=error_body(str(e), type_="authentication_error"),
                    content_type="application/json")
            except (TranslationError, oai.SchemaError) as e:
                req_metrics.finish(TokenUsage(), error_type="translation")
                status = getattr(e, "status", 400)  # NotFoundError → 404
                return web.Response(
                    status=status,
                    body=error_body(
                        str(e),
                        type_="not_found" if status == 404
                        else "invalid_request_error"),
                    content_type="application/json")
            self.circuit.record_success(rb.backend.name)
            return result

        req_metrics.finish(TokenUsage(), error_type="upstream_exhausted")
        return web.Response(
            status=last_error[0], body=last_error[1],
            content_type="application/json")

    async def _attempt(
        self,
        request: web.Request,
        endpoint: Endpoint,
        front_schema: APISchemaName,
        rb: RuntimeBackend,
        body: dict[str, Any],
        req_metrics: RequestMetrics,
        route_name: str,
        error_body: Callable[..., bytes],
        client_headers: dict[str, str],
        span=None,
    ) -> web.StreamResponse:
        backend = rb.backend
        # explicit None check: aiohttp's web.Response is a MutableMapping
        # over its (empty) per-request state, so a fresh 429 Response is
        # FALSY — a bare walrus truthiness test silently dropped the
        # quota rejection and let the request through
        rc_limited = await self._check_quota(client_headers, rb,
                                             req_metrics, error_body)
        if rc_limited is not None:
            return rc_limited
        if isinstance(body, _RawBody):
            # multipart passthrough: no translation, original bytes forward
            from aigw_tpu.translate.base import RequestTx as _RequestTx

            translator = get_translator(
                Endpoint.CHAT_COMPLETIONS,  # response side is passthrough
                APISchemaName.OPENAI,
                APISchemaName.OPENAI,
            )
            path = request.path
            if backend.schema.name is APISchemaName.AZURE_OPENAI:
                from aigw_tpu.translate.openai_azure import (
                    DEFAULT_API_VERSION,
                    _ENDPOINT_SUFFIX,
                )
                import urllib.parse as _up2

                dep = _up2.quote(
                    backend.model_name_override or body.model, safe="")
                path = (
                    f"/openai/deployments/{dep}/"
                    f"{_ENDPOINT_SUFFIX[endpoint]}"
                    f"?api-version="
                    f"{backend.schema.version or DEFAULT_API_VERSION}"
                )
            out_body = body.raw
            out_ctype = body.content_type
            if (backend.model_name_override
                    and backend.model_name_override != body.model):
                # the reference rewrites the model form field when the
                # backend overrides the model name, every other part
                # verbatim (multipart_helper.go:16-66)
                from aigw_tpu.translate.multipart import (
                    rewrite_multipart_model,
                )

                out_body, out_ctype = rewrite_multipart_model(
                    body.raw, body.content_type,
                    backend.model_name_override)
            tx = _RequestTx(body=out_body, path=path)
            headers = {
                "content-type": out_ctype,
                "accept": "application/json",
            }
        else:
            translator = get_translator(
                endpoint,
                front_schema,
                backend.schema.name,
                model_name_override=backend.model_name_override,
                out_version=backend.schema.version,
            )
            # Retry safety: translators are contractually read-only over
            # the captured body (they build fresh structures — the
            # reference's sjson no-in-place rule, translator.go:140-153),
            # so each attempt can re-translate without a deep copy.
            if self._translator_blocks(endpoint):
                # /v1/responses with a file-backed transcript store:
                # previous_response_id resolution reads disk — off the loop
                tx = await asyncio.to_thread(translator.request, body)
            else:
                tx = translator.request(body)
            out_body = apply_body_mutation(tx.body, backend.body_mutation)

            headers = {
                "content-type": "application/json",
                "accept": "text/event-stream" if tx.stream
                else "application/json",
            }
        # Endpoint-picker support: an externally pre-selected destination
        # (the reference's x-gateway-destination-endpoint + ORIGINAL_DST
        # contract, post_cluster_modify.go:67-80) wins; otherwise the
        # in-process picker chooses a replica from the backend's pool.
        dest = request.headers.get(DESTINATION_ENDPOINT_HEADER, "")
        prefix_key_used = ""
        decision: dict[str, Any] | None = None
        pick_headers = client_headers
        if not dest and backend.name in self._pickers:
            if backend.picker_content_affinity and isinstance(body, dict):
                derived = {}
                if AFFINITY_HEADER not in client_headers:
                    key = _conversation_affinity_key(body)
                    if key:
                        derived[AFFINITY_HEADER] = key
                if PREFIX_HEADER not in client_headers:
                    # shared system-prompt hash → soft cache-affinity:
                    # the picker prefers the replica whose prefix cache
                    # this prompt head was recently routed to
                    pkey = _prefix_hash_key(body)
                    if pkey:
                        derived[PREFIX_HEADER] = pkey
                if derived:
                    pick_headers = dict(client_headers) | derived
            # adapter-affinity (ISSUE 7): an adapter-suffixed zoo name
            # prefers replicas whose /state reports the LoRA row already
            # resident (soft — any replica can hot-load it)
            adapter = split_model(req_metrics.request_model)[1]
            if adapter and ADAPTER_HEADER not in pick_headers:
                pick_headers = dict(pick_headers) | {
                    ADAPTER_HEADER: adapter}
            # long-context satellite: prompt length is a routing input —
            # an explicit client header wins, else estimate from the
            # prompt bytes so the picker can filter replicas whose
            # advertised max_seq_len the prompt exceeds and price the
            # prefill into its predicted TTFT
            if (PROMPT_TOKENS_HEADER not in pick_headers
                    and isinstance(body, dict)):
                est = _prompt_token_estimate(body)
                if est:
                    pick_headers = dict(pick_headers) | {
                        PROMPT_TOKENS_HEADER: str(est)}
            # explain is ALWAYS computed now (ISSUE 12): the decision
            # audit ring records every pick, traced or not — the span
            # attrs below still only render when tracing is on
            explain: dict[str, Any] = {}
            try:
                dest = self._pickers[backend.name].pick(
                    pick_headers, explain=explain) or ""
            except SLOShedError as e:
                # SLO admission control (ISSUE 8): every candidate's
                # predicted TTFT blows the budget — shed with
                # 429 + Retry-After instead of queueing into collapse
                self.metrics.slo_sheds_total.labels(
                    route_name, backend.name).inc()
                self.metrics.requests_total.labels(
                    route_name, backend.name, "429").inc()
                req_metrics.finish(TokenUsage(), error_type="slo_shed")
                if backend.fleet_obs:
                    # shed events land in the audit ring too — "why
                    # did my request 429" is a routing decision
                    req_metrics.decision = self.decisions.record(
                        route=route_name, backend=backend.name,
                        model=req_metrics.request_model,
                        request_id=client_headers.get(
                            "x-request-id", ""),
                        shed=True,
                        retry_after_s=e.retry_after_s,
                        pick=dict(explain))
                if span is not None:
                    span.set("aigw.pick.shed", True)
                    span.set("aigw.pick.predicted_ttft_ms",
                             round(e.predicted_ms, 1))
                return web.Response(
                    status=429,
                    body=error_body(str(e), type_="rate_limit_error"),
                    headers={"retry-after": str(e.retry_after_s)},
                    content_type="application/json")
            except ContextLengthError as e:
                # long-context satellite: the prompt exceeds EVERY
                # fresh candidate's advertised context length — answer
                # a clean 400 at the gateway instead of collecting the
                # replica's over-length error after a routed admission
                self.metrics.requests_total.labels(
                    route_name, backend.name, "400").inc()
                req_metrics.finish(
                    TokenUsage(), error_type="context_length")
                if backend.fleet_obs:
                    req_metrics.decision = self.decisions.record(
                        route=route_name, backend=backend.name,
                        model=req_metrics.request_model,
                        request_id=client_headers.get(
                            "x-request-id", ""),
                        context_rejected=True,
                        prompt_tokens=e.prompt_tokens,
                        max_ctx=e.max_ctx,
                        pick=dict(explain))
                if span is not None:
                    span.set("aigw.pick.context_rejected", True)
                    span.set("aigw.pick.prompt_tokens", e.prompt_tokens)
                    span.set("aigw.pick.max_ctx", e.max_ctx)
                return web.Response(
                    status=400,
                    body=error_body(
                        str(e), type_="invalid_request_error"),
                    content_type="application/json")
            if dest and backend.fleet_obs:
                decision = self.decisions.record(
                    route=route_name, backend=backend.name,
                    model=req_metrics.request_model,
                    request_id=client_headers.get("x-request-id", ""),
                    chosen=dest,
                    pick=dict(explain))
                req_metrics.decision = decision
            if span is not None and dest:
                # why the picker chose this replica — the span-level
                # answer to "which endpoint served me, and was it
                # cache/session affinity or load" (slo mode adds the
                # per-endpoint predicted TTFTs behind the decision)
                span.set("aigw.endpoint", dest)
                for k, v in (explain or {}).items():
                    span.set(f"aigw.pick.{k}",
                             json.dumps(v) if isinstance(v, dict) else v)
            prefix_key_used = pick_headers.get(PREFIX_HEADER, "")
            if dest and backend.kv_fleet:
                # KV memory hierarchy (ISSUE 11): name the siblings the
                # fleet index says hold this request's chain — a prefix
                # miss on the chosen replica then becomes a page fetch
                # over /kv/pages instead of a re-prefill
                peers = self._pickers[backend.name].kv_peers(
                    dest, pick_headers)
                if peers:
                    headers[KV_PEERS_HEADER] = ",".join(peers)
                    if decision is not None:
                        decision["kv_peers"] = list(peers)
        base_url = f"http://{dest}" if dest else backend.url
        if not base_url:
            raise _RetriableUpstreamError(
                502, error_body(f"backend {backend.name} has no url"),
                "missing url")
        headers.update(tx.headers)
        if span is not None:
            self.tracer.propagators.inject(span.context, headers)
        else:
            # tracing disabled at the gateway: still RELAY the caller's
            # trace context verbatim so the replica hop can parent its
            # spans / flight-recorder entries on the caller's trace
            for h in ("traceparent", "b3", "x-b3-traceid",
                      "x-b3-spanid", "x-b3-sampled"):
                if h in client_headers:
                    headers[h] = client_headers[h]
        if TENANT_HEADER in client_headers:
            # the replica's fairness guard keys on the SAME tenant the
            # gateway accounts/ratelimits by
            headers[TENANT_HEADER] = client_headers[TENANT_HEADER]
        if PRIORITY_HEADER in client_headers:
            # priority class (ISSUE 19): the replica's two-class
            # scheduler must see the SAME class the picker routed by
            headers[PRIORITY_HEADER] = client_headers[PRIORITY_HEADER]
        headers = apply_header_mutation(headers, backend.header_mutation)
        import urllib.parse as _up

        headers["host"] = _up.urlsplit(base_url).netloc
        path = tx.path or request.path
        headers, path = rb.auth_handler.apply(headers, out_body, path)

        if logger.isEnabledFor(logging.DEBUG):
            from aigw_tpu.utils.redaction import redact_body, redact_headers

            logger.debug(
                "upstream attempt backend=%s path=%s headers=%s body=%s",
                backend.name, path, redact_headers(headers),
                redact_body(body) if not isinstance(body, _RawBody)
                else f"[multipart {len(body.raw)} bytes]",
            )
        session = await self._get_session()
        timeout = aiohttp.ClientTimeout(
            total=backend.request_timeout,
            sock_connect=min(10.0, backend.request_timeout),
            sock_read=backend.stream_idle_timeout if tx.stream else None,
        )
        #: this request went through the picker (an external
        #: x-gateway-destination-endpoint pin is NOT failed over —
        #: the pinner chose that exact replica on purpose)
        picked = bool(dest) and backend.name in self._pickers

        def _move_dest(nxt: str) -> None:
            # pre-first-byte failover (ISSUE 14): re-aim the SAME
            # translated request at a sibling replica. Only the
            # destination-derived pieces change; the translated body,
            # auth, and mutations were all destination-independent.
            nonlocal dest, base_url
            if decision is not None:
                decision.setdefault("failover_from", []).append(dest)
                decision["chosen"] = nxt
            if span is not None:
                span.set("aigw.pick.failover_from", dest)
            if KV_PEERS_HEADER in headers:
                peers = [p for p in headers[KV_PEERS_HEADER].split(",")
                         if p and p != nxt]
                if peers:
                    headers[KV_PEERS_HEADER] = ",".join(peers)
                else:
                    del headers[KV_PEERS_HEADER]
            dest = nxt
            base_url = f"http://{dest}"
            headers["host"] = _up.urlsplit(base_url).netloc

        def _sibling(tried: set[str]) -> str | None:
            picker = self._pickers.get(backend.name)
            if picker is None:
                return None
            try:
                nxt = picker.pick(pick_headers, exclude=frozenset(tried))
            except (SLOShedError, ContextLengthError):
                return None
            return nxt if nxt and nxt not in tried else None

        # at most ONE sibling retry, and only before any stream byte has
        # been relayed: a connect error or an immediate retriable 5xx
        # from a picked replica re-picks the next-ranked sibling instead
        # of surfacing the dead replica's error to the client
        failed_over = not picked
        breaker_counted: set[str] = set()
        while True:
            try:
                resp = await session.post(
                    base_url + path, data=out_body, headers=headers,
                    timeout=timeout
                )
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                if picked:
                    # per-replica breaker evidence: the dead process is
                    # condemned by address, not just its whole backend
                    self.circuit.record_failure(dest)
                if not failed_over:
                    nxt = _sibling({dest})
                    if nxt is not None:
                        failed_over = True
                        logger.warning(
                            "pre-first-byte failover %s -> %s (%s)",
                            dest, nxt, e)
                        _move_dest(nxt)
                        continue
                raise _RetriableUpstreamError(
                    502, error_body(f"upstream connect error: {e}",
                                    type_="upstream_error"),
                    str(e) or type(e).__name__,
                ) from None
            if (not failed_over and resp.status in (500, 502, 503, 504)):
                self.circuit.record_failure(dest)
                breaker_counted.add(dest)
                nxt = _sibling({dest})
                if nxt is not None:
                    failed_over = True
                    logger.warning(
                        "pre-first-byte failover %s -> %s (status %d)",
                        dest, nxt, resp.status)
                    resp.release()
                    _move_dest(nxt)
                    continue
            break

        async with _closing(resp):
            if resp.status >= 400:
                try:
                    err = await resp.read()
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    err = b""
                client_err = translator.response_error(resp.status, err)
                if resp.status in _RETRIABLE_STATUS:
                    if picked and dest not in breaker_counted:
                        self.circuit.record_failure(dest)
                    raise _RetriableUpstreamError(resp.status, client_err,
                                                  f"status {resp.status}")
                req_metrics.finish(TokenUsage(), error_type=str(resp.status))
                self.metrics.requests_total.labels(
                    route_name, backend.name, str(resp.status)
                ).inc()
                return web.Response(
                    status=resp.status, body=client_err,
                    content_type="application/json")

            if picked:
                # response started: close the replica-address circuit
                self.circuit.record_success(dest)
            translator.response_headers(
                resp.status, {k.lower(): v for k, v in resp.headers.items()}
            )
            # tpuserve's per-request id: joins this request's access-log
            # line against the replica's /debug/requests/{id} timeline
            req_metrics.upstream_request_id = resp.headers.get(
                "x-aigw-request-id", "")
            if decision is not None and req_metrics.upstream_request_id:
                # the audit-ring join key (ISSUE 12): the decision now
                # resolves straight to the serving replica's
                # flight-recorder timeline under the same id
                decision["upstream_request_id"] = (
                    req_metrics.upstream_request_id)
            if backend.name in self._pickers:
                # learn (prefix-head → KV chain) from the replica's
                # response — the fleet index can then locate this
                # prompt head's chain for later requests (ISSUE 11)
                chain_hex = resp.headers.get(KV_CHAIN_HEADER, "")
                if chain_hex and prefix_key_used:
                    self._pickers[backend.name].note_chain(
                        prefix_key_used, chain_hex)
            ctype = resp.headers.get("content-type", "")
            upstream_streams = tx.stream and (
                "text/event-stream" in ctype
                or "vnd.amazon.eventstream" in ctype
            )
            if upstream_streams:
                migrator = None
                if (backend.migration and dest
                        and backend.name in self._pickers
                        and endpoint in (Endpoint.CHAT_COMPLETIONS,
                                         Endpoint.COMPLETIONS)):
                    # prefill/decode disaggregation (ISSUE 8): this
                    # stream may be handed to a decode-leaning replica
                    # mid-flight if the source's prefill queue backs up
                    migrator = _Migrator(
                        picker=self._pickers[backend.name],
                        backend=backend, src=dest, session=session,
                        decision=decision)
                return await self._stream_response(
                    request, resp, translator, rb, req_metrics, route_name,
                    client_headers, front_schema, span=span,
                    endpoint=endpoint, migrator=migrator,
                )
            try:
                raw = await resp.read()
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                raise _RetriableUpstreamError(
                    502,
                    error_body(f"upstream body read failed: {e}",
                               type_="upstream_error"),
                    str(e) or type(e).__name__,
                ) from None
            if self._translator_blocks(endpoint):
                # end-of-stream persists the transcript to disk
                rx = await asyncio.to_thread(
                    translator.response_body, raw, True)
            else:
                rx = translator.response_body(raw, True)
            # Response-side typed validation (r5): the body the gateway
            # re-emits must carry the front schema's response shape — a
            # malformed upstream body is an upstream failure (reference
            # ResponseError semantics, translator.go:42-77), retriable
            # on the next backend like any other 502.
            if (not isinstance(body, _RawBody)
                    and typed_response.has_spec(endpoint)):
                parsed = rx.parsed
                try:
                    if parsed is None:
                        parsed = json.loads(rx.body or raw)
                    typed_response.validate_response(endpoint, parsed)
                except (json.JSONDecodeError, oai.SchemaError) as e:
                    if (endpoint is Endpoint.RESPONSES
                            and isinstance(parsed, dict)):
                        # the translator persisted a transcript for an
                        # id the client will never see — roll it back
                        rid = parsed.get("id")
                        if isinstance(rid, str) and rid:
                            from aigw_tpu.translate.responses import (
                                RESPONSE_STORE,
                            )

                            if self._translator_blocks(endpoint):
                                await asyncio.to_thread(
                                    RESPONSE_STORE.delete, rid)
                            else:
                                RESPONSE_STORE.delete(rid)
                    raise _RetriableUpstreamError(
                        502,
                        error_body(
                            f"upstream returned a malformed "
                            f"{endpoint.value} response: {e}",
                            type_="upstream_error"),
                        f"malformed upstream body: {e}",
                    ) from None
            usage = rx.usage
            req_metrics.response_model = rx.model
            if span is not None:
                self._openinference_response_attrs(
                    span, endpoint, rx.body or raw)
            req_metrics.finish(usage)
            await self._sink_costs(usage, req_metrics, route_name, client_headers)
            self.metrics.requests_total.labels(
                route_name, backend.name, str(resp.status)
            ).inc()
            upstream_ctype = resp.headers.get(
                "content-type", "application/json")
            out_headers = {}
            if req_metrics.upstream_request_id:
                # relay the replica's request id to the client — the
                # key a bug report can quote straight into the
                # replica's /debug/requests/{id}
                out_headers["x-aigw-request-id"] = (
                    req_metrics.upstream_request_id)
            return web.Response(
                status=resp.status, body=rx.body or raw,
                headers=out_headers,
                content_type=upstream_ctype.split(";")[0])

    async def _stream_response(
        self,
        request: web.Request,
        resp: aiohttp.ClientResponse,
        translator: Any,
        rb: RuntimeBackend,
        req_metrics: RequestMetrics,
        route_name: str,
        client_headers: dict[str, str],
        front_schema: APISchemaName = APISchemaName.OPENAI,
        span=None,
        endpoint: Endpoint | None = None,
        migrator: "_Migrator | None" = None,
    ) -> web.StreamResponse:
        """Proxy the SSE stream through the translator — the hot loop
        (reference processor_impl.go:481-575).

        First-frame latency contract: nothing here buffers beyond ONE
        complete SSE event. ``iter_any`` yields upstream bytes as they
        arrive, the translator re-emits per chunk, and the typed-stream
        validator relays every *complete* event immediately (only the
        partial tail waits for its terminator). Combined with
        TCP_NODELAY below and ``x-accel-buffering: no``, the first
        content delta leaves this hop as soon as tpuserve writes it.
        """
        out = web.StreamResponse(
            status=200,
            headers={
                "content-type": "text/event-stream",
                "cache-control": "no-cache",
                "x-accel-buffering": "no",
            },
        )
        if req_metrics.upstream_request_id:
            # replica request id → client (joins /debug/requests/{id})
            out.headers["x-aigw-request-id"] = (
                req_metrics.upstream_request_id)
        from aigw_tpu.utils.net import set_tcp_nodelay

        set_tcp_nodelay(request.transport)
        await out.prepare(request)
        usage = TokenUsage()
        model = ""
        # span output attrs for streams: reconstruct the response from
        # the front-schema SSE bytes (reference sse_converter.go). Only
        # when tracing is on — the accumulator parses every event.
        acc = None
        if span is not None and endpoint in (
            Endpoint.CHAT_COMPLETIONS, Endpoint.MESSAGES,
            Endpoint.COMPLETIONS,
        ):
            from aigw_tpu.obs.openinference import StreamAccumulator

            acc = StreamAccumulator()
        # Response-side typed validation for streams (r5): every event
        # the gateway re-emits is validated against the front schema's
        # chunk/event spec. Translators may re-emit at arbitrary byte
        # boundaries (passthrough forwards upstream chunks verbatim), so
        # events are reassembled across writes: validated-complete
        # events are relayed, the tail stays buffered, and a malformed
        # event is NEVER relayed — the stream ends with the error event.
        sse_buf = b""
        check_events = typed_response.has_stream_spec(endpoint)

        def _bad_event(raw: bytes) -> "oai.SchemaError | None":
            # field parsing (multi-line data joining, comments, CRLF)
            # delegates to the shared SSE parser — only the framing
            # scan below is local, because verbatim relay needs byte
            # offsets, which SSEParser does not expose
            from aigw_tpu.translate.sse import _parse_event

            ev = _parse_event(raw)
            if ev is None or not ev.data or ev.data.strip() == "[DONE]":
                return None
            try:
                typed_response.validate_stream_event(
                    endpoint, json.loads(ev.data))
            except (json.JSONDecodeError, oai.SchemaError) as e:
                return oai.SchemaError(str(e))
            return None

        def _scan_events(
            buf: bytes,
        ) -> "tuple[bytes, bytes, oai.SchemaError | None]":
            """(relay-able prefix of complete good events, remainder,
            error). On error the bad event stays in the remainder.
            Boundary rules byte-identical to SSEParser.feed: an event
            ends at the first blank line, \\n\\n or \\r\\n\\r\\n."""
            ok_end = pos = 0
            while True:
                sep = -1
                seplen = 0
                for cand in (b"\n\n", b"\r\n\r\n"):
                    i = buf.find(cand, pos)
                    if i != -1 and (sep == -1 or i < sep):
                        sep, seplen = i, len(cand)
                if sep == -1:
                    return buf[:ok_end], buf[ok_end:], None
                err = _bad_event(buf[pos:sep])
                if err is not None:
                    return buf[:ok_end], buf[ok_end:], err
                pos = ok_end = sep + seplen

        async def _relay(body: bytes) -> None:
            nonlocal sse_buf
            if not check_events:
                if acc is not None:
                    acc.feed(body)
                await out.write(body)
                return
            good, sse_buf, err = _scan_events(sse_buf + body)
            if good:
                if acc is not None:
                    acc.feed(good)
                await out.write(good)
            if err is not None:
                raise err

        try:
            async for chunk in resp.content.iter_any():
                rx = translator.response_body(chunk, False)
                usage = usage.merge_override(rx.usage)
                model = rx.model or model
                req_metrics.record_tokens_emitted(rx.tokens_emitted)
                if rx.body:
                    await _relay(rx.body)
                if migrator is not None:
                    # may cut the session at the source: its stream
                    # then ends at a token boundary and this loop runs
                    # to EOF, flushing every pre-cut token first
                    await migrator.maybe_export(
                        req_metrics.tokens_seen,
                        req_metrics.upstream_request_id)
            if migrator is not None and migrator.export is not None:
                # splice the decode replica's continuation: frames carry
                # the SAME response id, terminal frames included — the
                # client sees one uninterrupted stream
                cont = await migrator.start_continuation()
                if cont is None:
                    # resume from the last exported state on another
                    # sibling (ISSUE 14): the blob is in hand and no
                    # continuation byte was relayed yet, so a second
                    # target adopts the chain gap-free
                    cont = await migrator.retry_continuation()
                if cont is None:
                    # the session was cut but nobody resumed it — this
                    # is a real mid-stream loss; surface the SSE error
                    # event via the except path below
                    raise aiohttp.ClientPayloadError(
                        "migration continuation failed after export")
                self.metrics.migrations_total.labels(
                    route_name, rb.backend.name).inc()
                if span is not None:
                    span.set("aigw.migrated_to", migrator.target)
                async with _closing(cont):
                    async for chunk in cont.content.iter_any():
                        rx = translator.response_body(chunk, False)
                        usage = usage.merge_override(rx.usage)
                        model = rx.model or model
                        req_metrics.record_tokens_emitted(
                            rx.tokens_emitted)
                        if rx.body:
                            await _relay(rx.body)
            if self._translator_blocks(endpoint):
                # end-of-stream persists the transcript to disk
                rx = await asyncio.to_thread(
                    translator.response_body, b"", True)
            else:
                rx = translator.response_body(b"", True)
            usage = usage.merge_override(rx.usage)
            model = rx.model or model
            if rx.body:
                await _relay(rx.body)
            if check_events and sse_buf:
                # final event not terminated by a blank line (the same
                # shape SSEParser.flush handles): validate before relay
                # — the malformed-never-relayed invariant holds at EOF
                err = _bad_event(sse_buf)
                if err is not None:
                    raise err
                await out.write(sse_buf)
                sse_buf = b""
        except (aiohttp.ClientError, asyncio.TimeoutError,
                oai.SchemaError) as e:
            # Mid-stream failure: the client already has bytes; surface an
            # SSE error event rather than failing over (the reference's
            # per-try idle timeout only retries before response start).
            # The event is shaped for the *front* schema so the client
            # SDK recognizes it (Anthropic SDKs need `event: error` with
            # an Anthropic error envelope). A SchemaError means the
            # upstream emitted a malformed event — it was NOT relayed;
            # the stream ends with the error event instead.
            malformed = isinstance(e, oai.SchemaError)
            logger.warning("stream from %s %s: %s", rb.backend.name,
                           "emitted malformed event" if malformed
                           else "aborted", e)
            msg = ("upstream emitted a malformed stream event"
                   if malformed else "upstream stream interrupted")
            if front_schema is APISchemaName.ANTHROPIC:
                await out.write(
                    b'event: error\n'
                    b'data: {"type": "error", "error": {"type": '
                    b'"overloaded_error", "message": "'
                    + msg.encode() + b'"}}\n\n'
                )
            else:
                await out.write(
                    b'data: {"error": {"message": "' + msg.encode()
                    + b'", "type": "upstream_error", "code": null}}\n\n'
                )
        req_metrics.response_model = model
        if acc is not None:
            final = acc.response()
            builder = self._oi_response_builder(endpoint)
            if final is not None and builder is not None:
                try:
                    span.attributes.update(
                        builder(final, self._oi_config))
                except Exception:  # noqa: BLE001
                    logger.debug("stream span attrs failed", exc_info=True)
        req_metrics.finish(usage)
        await self._sink_costs(usage, req_metrics, route_name, client_headers)
        self.metrics.requests_total.labels(route_name, rb.backend.name, "200").inc()
        await out.write_eof()
        return out

    @staticmethod
    def _translator_blocks(endpoint: "Endpoint | None") -> bool:
        """True when translator request/end-of-stream calls do disk I/O
        (file-backed /v1/responses transcript store) and must be
        thread-hopped off the event loop — same contract as the quota
        backend below and FileReplayStore.blocking."""
        if endpoint is not Endpoint.RESPONSES:
            return False
        from aigw_tpu.translate.responses import RESPONSE_STORE

        return RESPONSE_STORE.blocking

    async def _check_quota(self, client_headers, rb, req_metrics,
                           error_body):
        """Admission check against token quotas (reference: Envoy
        ratelimit filter with domain ai-gateway-quota,
        extensionserver/quota_ratelimit.go:59). Consumption happens at
        end-of-stream in _sink_costs. A shared (flock'd-file) backend
        can block on cross-worker lock contention, so it runs off the
        event loop; the in-memory limiter is called inline."""
        limiter = self._runtime.rate_limiter
        if limiter is None or not limiter.rules:
            return None
        if limiter.backend is not None:
            ok, rule = await asyncio.to_thread(
                limiter.check,
                req_metrics.request_model, rb.backend.name, client_headers,
            )
        else:
            ok, rule = limiter.check(
                req_metrics.request_model, rb.backend.name, client_headers
            )
        if ok:
            return None
        client_err = error_body(
            f"token quota exceeded (rule {rule.name!r})",
            type_="rate_limit_error",
        )
        if rule.backend:
            # a backend-scoped budget: other backends may still have
            # budget, so fail over — but without a circuit-breaker
            # failure mark (the backend is healthy; a refilled quota
            # window must not find the circuit open)
            raise _RetriableUpstreamError(429, client_err,
                                          f"quota {rule.name}",
                                          count_failure=False)
        req_metrics.finish(TokenUsage(), error_type="429")
        return web.Response(
            status=429,
            body=client_err,
            headers={"retry-after": "1"},
            content_type="application/json",
        )

    async def _sink_costs(
        self,
        usage: TokenUsage,
        req_metrics: RequestMetrics,
        route_name: str,
        client_headers: dict[str, str],
    ) -> None:
        """End-of-stream cost metadata (≈ dynamic metadata for the
        rate-limit filter, extproc/util.go buildDynamicMetadata).

        Quota consumption is keyed by the *request* model — the same value
        _check_quota matched against — so model-scoped budgets enforce
        consistently even when the backend reports a versioned response
        model or a model_name_override rewrote the upstream name.

        ISSUE 20: the usage ledger records here too — EVERY finished
        request, with or without configured cost programs — folding the
        engine MeterRecord (usage.aigw_meter) into the per-tenant
        windowed ledger, reconciling it against the mined token counts,
        and stamping the priced cost onto the request's decision-ring
        entry so /debug/decisions shows what each pick cost."""
        limiter = self._runtime.rate_limiter
        has_quota = limiter is not None and limiter.rules
        ledger = self.usage_ledger
        if (self._cost_sink is None and not has_quota
                and not self.access_log.enabled and ledger is None):
            return
        model = req_metrics.request_model
        backend = req_metrics.provider
        tenant = client_headers.get(TENANT_HEADER, "")
        costs = self._runtime.cost_calculator_for(route_name).calculate(
            usage, model=model, backend=backend, route_name=route_name,
            tenant=tenant,
        )
        if ledger is not None:
            # ledger cost = the summed configured cost metrics (0 when
            # no cost programs are configured — the token/residency
            # columns still accumulate engine truth)
            total_cost = sum(costs.values())
            ledger.record(tenant, model, usage, cost=total_cost)
            req_metrics.decision["cost"] = total_cost
            if costs:
                req_metrics.decision["costs"] = dict(costs)
        if not costs:
            return
        req_metrics.costs = dict(costs)
        if has_quota:
            if limiter.backend is not None:
                # flock'd shared store: contention must not stall the loop
                await asyncio.to_thread(
                    limiter.consume, costs, model, backend, client_headers)
            else:
                limiter.consume(costs, model, backend, client_headers)
        if self._cost_sink is not None:
            self._cost_sink(
                costs,
                {"model": model, "backend": backend, "route": route_name},
            )


class _Migrator:
    """Gateway-side orchestrator for migrating ONE streaming session
    (ISSUE 8 prefill/decode disaggregation). While the gateway relays a
    stream from its source replica it watches the picker's polled
    telemetry; when the source's admission queue is deep (prefill
    pressure), the session is still young, and a decode-leaning sibling
    exists, it cuts the session via the source's ``/migrate/export``
    and splices the target's ``/migrate/import`` continuation stream —
    the client sees one uninterrupted SSE stream under one response id.

    At most one migration attempt per request; a declined or failed
    export leaves the source serving untouched."""

    def __init__(self, picker: EndpointPicker, backend, src: str,
                 session: aiohttp.ClientSession,
                 decision: dict | None = None):
        self.picker = picker
        self.backend = backend
        self.src = src
        self.session = session
        self.attempted = False
        self.export: dict | None = None
        self.target: str | None = None
        #: the request's audit-ring entry (ISSUE 12): a fired migration
        #: is part of the routing decision's afterlife — stamped here
        #: so /debug/decisions shows the trigger next to the pick
        self.decision = decision

    def _drain_requested(self) -> bool:
        """The source replica is draining (controller scale-in/update,
        operator /drain, or its own /state announcement) — every
        migration-capable stream must move off regardless of queue
        pressure or age (ISSUE 14 lossless drain)."""
        h = self.picker.fleet.health.get(self.src)
        return h is not None and h.draining

    def _pick_target(self, force: bool = False,
                     exclude: set | frozenset = frozenset()
                     ) -> str | None:
        src_st = self.picker.state.get(self.src)
        if src_st is None or not src_st.healthy:
            return None
        if not src_st.migration_capable:
            # the replica reports `migration: false` on /state (e.g.
            # prefix cache disabled — no refcounted page export path):
            # stop polling for this stream instead of 409ing an export
            self.attempted = True
            return None
        if not force and src_st.queued < self.backend.migration_queue_depth:
            return None  # no prefill pressure at the source
        now = time.monotonic()
        best: str | None = None
        best_pred = 0.0
        for addr, st in self.picker.state.items():
            if addr == self.src or addr in exclude or not st.healthy:
                continue
            if not self.picker.is_routable(addr):
                continue  # down/draining/breaker-open: not a new home
            if not st.migration_capable:
                continue  # can't adopt a page chain
            if now - st.updated_at >= self.picker.STALE_AFTER:
                continue
            if st.queued > 0 or st.active_slots >= st.max_slots:
                continue  # not decode-leaning: nowhere to put the slot
            p = self.picker.predicted_ttft_ms(st)
            p = 0.0 if p is None else p
            if best is None or p < best_pred:
                best, best_pred = addr, p
        return best

    async def maybe_export(self, tokens_seen: int, rid: str) -> None:
        """Per-chunk check (cheap dict reads until the trigger fires).
        On trigger, POSTs the source's export endpoint — after which the
        source ends its stream at a token boundary and the relay loop
        runs to EOF naturally, flushing every pre-cut token."""
        if self.attempted or not rid or tokens_seen < 1:
            return
        draining = self._drain_requested()
        if not draining and tokens_seen > self.backend.migration_young_tokens:
            self.attempted = True  # matured past migratability
            return
        target = self._pick_target(force=draining)
        if target is None:
            return
        self.attempted = True
        try:
            async with self.session.post(
                f"http://{self.src}/migrate/export",
                json={"request_id": rid},
                timeout=aiohttp.ClientTimeout(total=60),
            ) as r:
                if r.status != 200:
                    # 409 = not now (finished / ineligible): the source
                    # keeps serving, nothing to splice
                    logger.info("migration export declined (%d)",
                                r.status)
                    return
                self.export = await r.json()
            self.target = target
            if self.decision is not None:
                self.decision["migrated_to"] = target
                self.decision["migration_trigger"] = {
                    "src_queued": int(getattr(
                        self.picker.state.get(self.src), "queued", 0)),
                    "tokens_seen": tokens_seen,
                    "drain": draining,
                }
            logger.info("migrating session %s: %s -> %s", rid, self.src,
                        target)
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            logger.warning("migration export failed: %s", e)

    async def retry_continuation(self) -> aiohttp.ClientResponse | None:
        """Resume from the last exported state on a DIFFERENT sibling
        (ISSUE 14 crash failover): the cut already happened and the
        blob is in hand — if the chosen target died or refused the
        import, any other idle migration-capable replica can adopt the
        chain. The client stream stays gap-free by construction: the
        continuation always starts at the export cut, and zero
        continuation bytes were relayed before this retry. Returns None
        when no alternative target exists (the caller degrades to the
        typed error event)."""
        if self.export is None or self.target is None:
            return None
        failed = self.target
        nxt = self._pick_target(force=True, exclude={failed})
        if nxt is None:
            return None
        self.target = nxt
        if self.decision is not None:
            self.decision.setdefault(
                "migration_retargeted_from", []).append(failed)
            self.decision["migrated_to"] = nxt
        logger.info("migration continuation retarget %s -> %s",
                    failed, nxt)
        return await self.start_continuation()

    async def start_continuation(self) -> aiohttp.ClientResponse | None:
        """Hand the blob to the target replica; returns the SSE response
        that continues the client stream (original response id), or
        None when the import failed."""
        if self.export is None or self.target is None:
            return None
        try:
            r = await self.session.post(
                f"http://{self.target}/migrate/import",
                json=self.export,
                timeout=aiohttp.ClientTimeout(
                    total=self.backend.request_timeout,
                    sock_read=self.backend.stream_idle_timeout),
            )
            if r.status != 200:
                body = await r.read()
                r.release()
                logger.warning("migration import failed (%d): %s",
                               r.status, body[:200])
                return None
            return r
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            logger.warning("migration import failed: %s", e)
            return None


class _RetriableUpstreamError(Exception):
    def __init__(self, status: int, client_body: bytes, reason: str,
                 count_failure: bool = True):
        super().__init__(reason)
        self.status = status
        self.client_body = client_body
        #: whether the circuit breaker should count this as a backend
        #: failure; quota rejections fail over without poisoning the
        #: circuit (the backend itself is healthy)
        self.count_failure = count_failure


class _closing:
    def __init__(self, resp: aiohttp.ClientResponse):
        self._resp = resp

    async def __aenter__(self):
        return self._resp

    async def __aexit__(self, *exc):
        self._resp.release()
        return False


async def run_gateway(
    runtime: RuntimeConfig,
    host: str = "127.0.0.1",
    port: int = 1975,
    reuse_port: bool = False,
    **kwargs: Any,
) -> tuple[GatewayServer, web.AppRunner]:
    """Start the gateway; returns (server, runner). Caller owns shutdown.

    ``reuse_port=True`` binds with SO_REUSEPORT so multiple worker
    processes share one listening port, the kernel load-balancing
    accepted connections across them (the multi-worker mode — Envoy's
    role in the reference is a multi-threaded C++ proxy; CPython's GIL
    means horizontal processes, not threads)."""
    server = GatewayServer(runtime, **kwargs)
    # aiohttp's per-request INFO access log is pure hot-path overhead
    # (~4x rps at high concurrency); structured access logging is our
    # own AIGW_ACCESS_LOG pipeline (obs/accesslog.py)
    runner = web.AppRunner(server.app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, host, port, reuse_port=reuse_port or None)
    await site.start()
    logger.info("gateway listening on %s:%d", host, port)
    return server, runner
