"""Route matching and weighted/priority backend selection.

The reference delegates this to Envoy (weighted clusters from
AIGatewayRouteRuleBackendRef weights, priority-ordered fallback +
BackendTrafficPolicy retries — ai_gateway_route.go:377-397,
examples/provider_fallback). Here it is native: first-match rule lookup,
then a retry-aware selector that walks priority tiers and weighted-samples
within a tier, never repeating a failed backend.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any

from aigw_tpu.config.model import Route, RouteRule, RuleBackendRef
from aigw_tpu.config.runtime import RuntimeConfig


class NoRouteError(Exception):
    """No route rule matched (→ 404, the reference's route-not-found rule)."""


def split_model(name: str) -> tuple[str, str]:
    """Model-zoo name resolution: ``<base>:<adapter>`` → (base, adapter);
    a plain name is (name, ""). The colon convention is tpuserve's LoRA
    surface (replica /v1/models lists ``llama-3-8b:tenant-a`` style
    entries); the gateway routes such names by their BASE model and uses
    the adapter part for tenancy accounting and picker affinity."""
    base, _, adapter = name.partition(":")
    return (base, adapter) if adapter else (name, "")


@dataclass
class RouteMatch:
    route: Route
    rule: RouteRule


def match_route(
    rc: RuntimeConfig, host: str, headers: dict[str, str]
) -> RouteMatch:
    from aigw_tpu.config.model import MODEL_NAME_HEADER

    for route in rc.routes_for_host(host):
        for rule in route.rules:
            if rule.matches(headers):
                return RouteMatch(route=route, rule=rule)
    # model-zoo fallback: an adapter-suffixed name ("llama-3-8b:tenant-a")
    # routes to the rule serving its base model — a route per adapter
    # would make every adapter a config change, and the serving replica
    # resolves the suffix itself (tpuserve _resolve_adapter)
    model = headers.get(MODEL_NAME_HEADER, "")
    base, adapter = split_model(model)
    if adapter:
        base_headers = dict(headers, **{MODEL_NAME_HEADER: base})
        for route in rc.routes_for_host(host):
            for rule in route.rules:
                if rule.matches(base_headers):
                    return RouteMatch(route=route, rule=rule)
    raise NoRouteError("no route matched the request model")


@dataclass
class BackendSelector:
    """Retry-aware backend iterator for one request.

    Walks priority tiers in ascending order (priority 0 first). Within a
    tier, picks weighted-random among backends not yet tried — equivalent to
    Envoy's weighted-cluster pick plus priority failover. Backends whose
    circuit is open (outlier ejection) are deferred to a second pass so a
    fully-ejected rule still gets a best-effort attempt.
    """

    rule: RouteRule
    circuit: Any = None  # aigw_tpu.gateway.circuit.CircuitBreaker | None
    rng: random.Random = field(default_factory=random.Random)
    _tried: set[str] = field(default_factory=set)
    _skip_open: bool = True

    def next_backend(self) -> RuleBackendRef | None:
        ref = self._next_backend_pass()
        if ref is None and self._skip_open and self.circuit is not None:
            # every healthy candidate is exhausted: allow open-circuit
            # backends rather than failing outright
            self._skip_open = False
            ref = self._next_backend_pass()
        return ref

    def _next_backend_pass(self) -> RuleBackendRef | None:
        for priority in sorted({b.priority for b in self.rule.backends}):
            tier = [
                b
                for b in self.rule.backends
                if b.priority == priority
                and b.backend not in self._tried
                and b.weight > 0
                and not (
                    self._skip_open
                    and self.circuit is not None
                    and self.circuit.is_open(b.backend)
                )
            ]
            if not tier:
                continue
            total = sum(b.weight for b in tier)
            pick = self.rng.uniform(0, total)
            acc = 0.0
            for b in tier:
                acc += b.weight
                if pick <= acc:
                    self._tried.add(b.backend)
                    return b
            self._tried.add(tier[-1].backend)
            return tier[-1]
        return None
