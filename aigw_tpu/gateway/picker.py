"""TPU endpoint picker — KV-occupancy- and topology-aware load balancing.

The role the reference delegates to an external EPP service speaking
ext_proc (InferencePool → picker sets ``x-gateway-destination-endpoint``,
reference inferencepool.go:47, post_cluster_modify.go:67-80). Here the
picker is in-process: it polls each tpuserve replica's ``/state``
telemetry (KV page occupancy, queue depth, active slots — exported by
aigw_tpu/tpuserve/server.py) and scores endpoints:

    score = kv_occupancy [worst device]      (HBM pressure — on a mesh
                                              replica the WORST device's
                                              occupancy, polled from the
                                              per-device /state map)
          + queued / max_slots               (waiting work)
          + active_slots / max_slots * 0.5   (decode batch load)
          + queue_wait_ms / 1000             (queue latency: seconds the
                                              oldest request has waited —
                                              a replica whose queue MOVES
                                              beats one the same depth
                                              stuck behind a long prefill)
          + SLICE_PENALTY                    (for sessions only: replicas
                                              OUTSIDE the session's ICI
                                              slice — failover and
                                              load-forced moves prefer a
                                              same-slice sibling on ties)

    Topology is live, not just configured: each replica reports its own
    slice on ``/state`` (tpuserve exports ``jax.devices()`` slice_index
    and chip coords), overriding the static ``slice`` label.

Session affinity (``x-aigw-session-affinity``, or derived from the
conversation head by the gateway) is per-endpoint STICKY: the session
stays on its previous replica — whose prefix cache holds its KV — unless
that replica's score exceeds the best alternative by
``STICKINESS_MARGIN``. Unhealthy or stale endpoints are skipped; with no
telemetry at all the picker falls back to round-robin.

Prefix affinity (``x-aigw-prefix-hash``, or derived from the request's
system-prompt head by the gateway) is SOFT, not sticky: requests whose
prefix hash was recently routed to a replica get a bounded score BONUS
toward it — that replica's prefix cache already holds the shared
system-prompt KV pages, so landing there turns the prompt prefill into
a suffix (or single-token) resume. The bonus is a constant
(``PREFIX_AFFINITY_BONUS``) while the load/queue_wait terms are
unbounded, so affinity never overrides saturation; unlike sessions,
many independent clients share one prefix key, and hard stickiness
would funnel them all onto one replica.
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Any

import aiohttp

from aigw_tpu.gateway.kvindex import KVIndex
from aigw_tpu.gateway.fleetstate import DOWN, DRAINING, FleetState
from aigw_tpu.obs.slomon import SLOMonitor

logger = logging.getLogger(__name__)

#: request header carrying a session affinity key (optional)
AFFINITY_HEADER = "x-aigw-session-affinity"

#: request header carrying a shared-prefix hash (optional; the gateway
#: derives one from the system/developer message head when the backend
#: enables the picker) — soft cache-affinity, see module docstring
PREFIX_HEADER = "x-aigw-prefix-hash"

#: request header carrying the LoRA adapter the request needs (derived
#: by the gateway from the model's ":adapter" suffix). SOFT affinity:
#: replicas reporting the adapter RESIDENT on /state get a score bonus
#: — landing there serves from the already-loaded row; any replica of
#: the pool can still hot-load it, so affinity never gates placement.
ADAPTER_HEADER = "x-aigw-adapter"

#: request header carrying the tenant key (client-set, or derived by the
#: gateway from the model's adapter suffix) — relayed upstream so the
#: replica's fairness guard and the gateway's quota/cost accounting key
#: on the same tenant
TENANT_HEADER = "x-aigw-tenant"

#: priority class header (ISSUE 19): requests marked ``batch`` ride the
#: offline tier — the picker routes them to the replica with the MOST
#: idle capacity (the inverse of the interactive preference) and never
#: SLO-sheds them (batch queues, it doesn't 429); relayed upstream so
#: the replica's two-class scheduler sees the same class
PRIORITY_HEADER = "x-aigw-priority"

#: KV chain-hash header (ISSUE 11): the hex content hash of the
#: request's first prompt page. Usually LEARNED, not client-set — each
#: tpuserve response carries it, and the picker remembers (prefix-head
#: → chain) so later requests sharing the prefix head resolve to a
#: chain the fleet index can locate. A client/test may also set it
#: directly. Replicas the index says hold the chain get the bounded
#: fleet-hit bonus and are named as fetch peers.
KV_CHAIN_HEADER = "x-aigw-kv-chain"

#: upstream request header naming sibling replicas that hold the
#: request's chain (comma-separated "host:port") — the chosen replica
#: fetches missing prefix pages from them over POST /kv/pages instead
#: of re-prefilling (tpuserve/server.py consumes it)
KV_PEERS_HEADER = "x-aigw-kv-peers"

#: request header carrying the client's own prompt-token count
#: (optional). When absent the gateway estimates one from the prompt
#: byte length before pick() — the estimate feeds the picker's
#: context-length filter and the prompt-priced TTFT model, never the
#: replica (tpuserve recounts with its real tokenizer on admission).
PROMPT_TOKENS_HEADER = "x-aigw-prompt-tokens"


class SLOShedError(Exception):
    """Every fresh candidate's predicted TTFT blows the configured SLO:
    admitting the request would queue it into collapse. The gateway
    surfaces 429 + Retry-After instead (ISSUE 8 admission control)."""

    def __init__(self, retry_after_s: int, predicted_ms: float,
                 slo_ms: float):
        super().__init__(
            f"predicted TTFT {predicted_ms:.0f}ms exceeds the "
            f"{slo_ms:.0f}ms SLO on every candidate replica")
        self.retry_after_s = retry_after_s
        self.predicted_ms = predicted_ms
        self.slo_ms = slo_ms


class ContextLengthError(Exception):
    """The request's prompt exceeds the advertised ``max_seq_len`` of
    EVERY fresh candidate replica: routing it anywhere would burn a
    full admission round-trip just to collect tpuserve's over-length
    ValueError mid-stream. The gateway surfaces a clean 400 instead
    (long-context satellite: context length is a routing input, not a
    replica-side surprise)."""

    def __init__(self, prompt_tokens: int, max_ctx: int):
        super().__init__(
            f"prompt of ~{prompt_tokens} tokens exceeds the "
            f"{max_ctx}-token context length of every candidate "
            f"replica")
        self.prompt_tokens = prompt_tokens
        self.max_ctx = max_ctx


@dataclass(frozen=True)
class Endpoint:
    address: str  # host:port
    slice_name: str = ""  # ICI slice / host grouping label

    @staticmethod
    def parse(value: Any) -> "Endpoint":
        if isinstance(value, str):
            return Endpoint(address=value)
        return Endpoint(address=value["address"],
                        slice_name=value.get("slice", ""))


@dataclass
class EndpointState:
    healthy: bool = False
    kv_occupancy: float = 0.0
    queued: int = 0
    active_slots: int = 0
    max_slots: int = 1
    queue_wait_ms: float = 0.0  # age of the oldest queued request
    # prefix-cache effectiveness reported by the replica on /state
    # (tpuserve prefix_cache_hit_rate) — dashboard/affinity telemetry
    prefix_hit_rate: float = 0.0
    # served base model + adapter zoo reported on /state: resident
    # adapters feed the adapter-affinity score term; registered names
    # feed the gateway's /v1/models zoo listing
    model: str = ""
    adapters_resident: frozenset = frozenset()
    adapters_registered: tuple = ()
    # ICI slice reported by the replica itself on /state (TPU multislice
    # slice_index) — overrides the statically configured slice label, so
    # topology follows reality after reschedules
    slice_name: str = ""
    # MEASURED per-device memory pressure polled from /state (ISSUE 9
    # satellite, VERDICT r5 residue: the topology-aware picker used to
    # score labels, never a measured signal): live jax memory_stats()
    # bytes_in_use / bytes_limit as a fraction (0.0 on backends without
    # memory stats — the term then vanishes from the score)
    hbm_frac: float = 0.0
    # structured-output / tool-calling capability flags reported on
    # /state — merged into the gateway's /v1/models zoo listing
    constrained: bool = False
    capabilities: dict = field(default_factory=dict)
    # serving-phase latency distributions polled from /state
    # (phase → {p50, p95, p99} in ms; -1 = no observations) — the
    # SLO-aware mode's predictive inputs (ISSUE 8)
    phase_percentiles: dict = field(default_factory=dict)
    # migration-eligibility gauge polled from /state: slots whose
    # prefill is done but decode is young — what a decode-leaning
    # sibling could take over
    migratable_slots: int = 0
    # mesh serving (ISSUE 10): the replica's REAL per-device map polled
    # from /state `devices` (memory_frac / kv_occupancy / param_bytes
    # per device), the worst-device memory fraction, its device
    # population, and whether the replica can serve /migrate/export|
    # import at all (`migration` capability flag; replicas predating
    # the flag are assumed capable — the export 409 still guards)
    devices: tuple = ()
    hbm_frac_worst: float = 0.0
    mesh_devices: int = 1
    migration_capable: bool = True
    # KV memory hierarchy (ISSUE 11): the replica's advertised chain-
    # hash digest (resident + host-spilled) polled from /state — fed
    # into the picker's fleet-wide KVIndex on every poll
    kv_chains: tuple = ()
    updated_at: float = 0.0
    # fleet observability (ISSUE 12): when the last poll SUCCEEDED
    # (monotonic; 0 = never), consecutive failed polls since, and the
    # replica's self-reported identity/uptime. The stale-poll fix: a
    # failed poll used to leave the last-good state frozen with only
    # `healthy` flipped — these stamps make staleness first-class, so
    # slo mode and /fleet/state can tell "current truth" from "how the
    # replica looked before it died".
    last_poll_ok_ts: float = 0.0
    poll_failures: int = 0
    replica_id: str = ""
    uptime_s: float = 0.0
    # long-context serving: the replica's advertised context length
    # (0 = not advertised, filter vanishes), its sequence-parallel
    # axis size, and the measured prefill cost per token — the
    # context-length filter and the prompt-priced TTFT model read
    # these off /state
    max_seq_len: int = 0
    sp: int = 1
    prefill_ms_per_token: float = 0.0
    # MoE serving (ISSUE 18): hottest-expert load ratio polled from
    # /state (max expert tokens / mean; 1.0 = perfectly balanced, 0.0 =
    # dense replica — the term vanishes). PR 10 worst-device discipline
    # extended to expert shards: an expert-parallel replica's step time
    # is its hottest expert's, so imbalance prices the replica even
    # when slots and queue look fine.
    moe_expert_imbalance: float = 0.0
    # priority-tiered serving (ISSUE 19): the replica's offline-class
    # footprint polled from /state. ``queued``/``queue_wait_ms`` above
    # stay interactive-only (batch rides its own engine queue), so
    # predicted_ttft_ms never prices batch backlog; these feed the
    # batch routing branch (most idle capacity), fleetwatch's per-class
    # columns, and the controller's retire-drain wait.
    batch_queued: int = 0
    batch_active: int = 0
    batch_preemptions: int = 0

    def staleness_s(self, now: float | None = None) -> float:
        """Seconds since the last successful poll (-1 = never)."""
        if not self.last_poll_ok_ts:
            return -1.0
        return max(0.0, (now if now is not None else time.monotonic())
                   - self.last_poll_ok_ts)

    def worst_hbm_frac(self) -> float:
        """Worst per-device memory fraction — the mesh memory signal
        the score consumes (one hot shard stalls every tensor-parallel
        step, so the WORST device prices the replica, not device 0).
        Falls back to the device-0 scalar when the replica exports no
        per-device data."""
        per = max((float(d.get("memory_frac", 0.0) or 0.0)
                   for d in self.devices), default=0.0)
        return max(self.hbm_frac, self.hbm_frac_worst, per)

    def worst_kv_occupancy(self) -> float:
        """Worst per-device KV pool occupancy (uniform under pure tensor
        parallelism — the head-sharded pool allocates pages globally —
        but real the moment layouts diverge). Never below the scalar
        gauge."""
        per = max((float(d.get("kv_occupancy", 0.0) or 0.0)
                   for d in self.devices), default=0.0)
        return max(self.kv_occupancy, per)


class EndpointPicker:
    """Picker for one backend pool."""

    STALE_AFTER = 10.0  # seconds without telemetry → treat as unknown

    def __init__(self, endpoints: list[Endpoint],
                 poll_interval: float = 1.0,
                 mode: str = "static",
                 slo_ttft_ms: float = 0.0,
                 fleet_obs: bool = True,
                 slo_objective: float = 0.95,
                 slo_window_s: float = 30.0,
                 slo_burn_windows: int = 3):
        if mode not in ("static", "slo"):
            raise ValueError(f"picker mode must be 'static' or 'slo' "
                             f"(got {mode!r})")
        self.endpoints = endpoints
        self.poll_interval = poll_interval
        #: "static" — the classic score sum; "slo" — rank candidates by
        #: PREDICTED TTFT derived from each replica's live phase
        #: histograms + queue depth (ISSUE 8), falling back to static
        #: scoring while no replica has histogram data yet
        self.mode = mode
        #: admission-control budget for slo mode: when > 0 and every
        #: fresh candidate's predicted TTFT exceeds it, pick() raises
        #: SLOShedError instead of routing (the gateway sheds with
        #: 429 + Retry-After). 0 = route-only (never shed).
        self.slo_ttft_ms = slo_ttft_ms
        self.state: dict[str, EndpointState] = {
            e.address: EndpointState() for e in endpoints
        }
        self._by_addr = {e.address: e for e in endpoints}
        self._rr = itertools.cycle([e.address for e in endpoints])
        # session key → address, LRU-bounded
        self._affinity: "collections.OrderedDict[str, str]" = (
            collections.OrderedDict()
        )
        # prefix hash → address a request with that prefix was most
        # recently routed to (its prefix cache likely holds the pages)
        self._prefix_affinity: "collections.OrderedDict[str, str]" = (
            collections.OrderedDict()
        )
        # fleet-wide chain-hash → replica index (ISSUE 11), fed by the
        # kv_chains digests this poll loop already collects
        self.kv_index = KVIndex()
        # fleet observability plane (ISSUE 12): health state machine +
        # rollups + the live SLO burn-rate monitor, all fed from this
        # same poll loop. fleet_obs=False drops the monitor (the A/B
        # control); the health machine itself is a few dict ops and
        # stays on — /fleet/state must always answer.
        self.fleet_obs = fleet_obs
        self.fleet = FleetState(
            slomon=SLOMonitor(
                slo_ms=slo_ttft_ms, objective=slo_objective,
                window_s=slo_window_s, k_windows=slo_burn_windows)
            if fleet_obs else None)
        # prefix hash → KV chain hash learned from tpuserve response
        # headers (x-aigw-kv-chain): resolves a request's prefix head
        # to the content chain the index can locate, LRU-bounded
        self._prefix_chain: "collections.OrderedDict[str, str]" = (
            collections.OrderedDict()
        )
        # merged routability (ISSUE 14): the gateway installs its
        # circuit breaker here so pick() consults ONE view — health
        # machine (down/draining) + breaker state — instead of the two
        # tracking overlapping failure evidence independently
        self.breaker = None
        self._task: asyncio.Task | None = None

    # -- fleet membership (ISSUE 14 controller) ---------------------------
    def add_endpoint(self, address: str, slice_name: str = "") -> None:
        """Join a freshly launched replica to the pool (scale-out /
        failover replacement). Idempotent; the poll loop picks it up on
        its next cycle."""
        if address in self._by_addr:
            return
        e = Endpoint(address=address, slice_name=slice_name)
        self.endpoints.append(e)
        self._by_addr[address] = e
        self.state[address] = EndpointState()
        self._rr = itertools.cycle([x.address for x in self.endpoints])

    def remove_endpoint(self, address: str) -> None:
        """Retire a replica from the pool (scale-in after drain, or a
        crashed replica the controller replaced): drops its telemetry,
        fleet health entry, index entries, and affinity memory."""
        self.endpoints = [e for e in self.endpoints
                          if e.address != address]
        self._by_addr.pop(address, None)
        self.state.pop(address, None)
        self.kv_index.remove(address)
        self.fleet.forget(address)
        self.forget_endpoint(address)
        # pick() returns None before touching the cycle when the pool
        # is empty, so an empty cycle is never advanced
        self._rr = itertools.cycle([x.address for x in self.endpoints])

    def forget_endpoint(self, address: str) -> None:
        """Drop session/prefix affinity entries pointing at a dead or
        retired replica — the controller's "re-route queued work" hook:
        the next request of an affine session re-picks over the live
        pool instead of chasing its dead home through the stickiness
        margin."""
        for mapping in (self._affinity, self._prefix_affinity):
            for key in [k for k, v in mapping.items() if v == address]:
                del mapping[key]

    def is_routable(self, address: str) -> bool:
        """The merged health view (ISSUE 14): a replica is routable
        only when the fleet health machine doesn't have it down or
        draining AND the gateway's circuit breaker (when installed)
        isn't open for it. Poll-level freshness/health is layered on
        top by the score path."""
        if self.fleet.health_of(address) in (DOWN, DRAINING):
            return False
        return not (self.breaker is not None
                    and self.breaker.is_open(address))

    # -- polling ----------------------------------------------------------
    async def start(self) -> None:
        self._task = asyncio.create_task(self._poll_loop(),
                                         name="endpoint-picker")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _poll_loop(self) -> None:
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=2.0)
        ) as session:
            while True:
                await asyncio.gather(
                    *(self._poll_one(session, e) for e in self.endpoints),
                    return_exceptions=True,
                )
                await asyncio.sleep(self.poll_interval)

    async def _poll_one(self, session: aiohttp.ClientSession,
                        e: Endpoint) -> None:
        st = self.state.get(e.address)
        if st is None:
            return  # removed (controller scale-in) mid-poll-cycle

        def failed() -> None:
            # the stale-poll fix (ISSUE 12): a failed poll used to flip
            # `healthy` and nothing else — the last-good telemetry sat
            # frozen underneath. Count the failure, feed the fleet
            # health machine, and leave last_poll_ok_ts aging so every
            # consumer can SEE the staleness instead of trusting the
            # replica's last happy self.
            st.healthy = False
            st.poll_failures += 1
            # expiry on replica death: a fetch pointed at a dead
            # sibling only wastes the fetch timeout
            self.kv_index.remove(e.address)
            self.fleet.note_poll(e.address, False)

        try:
            async with session.get(f"http://{e.address}/state") as resp:
                if resp.status != 200:
                    failed()
                    return
                data = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            # ValueError covers a replica answering 200 with a torn /
            # non-JSON body (json.JSONDecodeError): previously that
            # escaped this handler and the replica stayed "healthy" on
            # entirely stale data — the frozen-EndpointState bug
            failed()
            return
        st.healthy = True
        st.kv_occupancy = float(data.get("kv_occupancy", 0.0))
        st.queued = int(data.get("queued", 0))
        st.active_slots = int(data.get("active_slots", 0))
        st.max_slots = max(1, int(data.get("max_slots", 1)))
        st.queue_wait_ms = float(data.get("queue_wait_ms", 0.0))
        st.prefix_hit_rate = float(data.get("prefix_cache_hit_rate", 0.0))
        st.phase_percentiles = dict(data.get("phase_percentiles") or {})
        st.migratable_slots = int(data.get("migratable_slots", 0))
        st.hbm_frac = float(data.get("device_memory_frac", 0.0) or 0.0)
        st.hbm_frac_worst = float(
            data.get("device_memory_frac_worst", 0.0) or 0.0)
        st.devices = tuple(d for d in (data.get("devices") or ())
                           if isinstance(d, dict))
        st.mesh_devices = max(1, int(data.get("mesh_devices", 1) or 1))
        st.migration_capable = bool(data.get("migration", True))
        st.constrained = bool(data.get("constrained_decoding", False))
        st.capabilities = dict(data.get("capabilities") or {})
        st.slice_name = str(data.get("slice", "") or "")
        st.model = str(data.get("model", "") or "")
        st.adapters_resident = frozenset(
            data.get("adapters_resident") or ())
        st.adapters_registered = tuple(
            data.get("adapters_registered") or ())
        st.kv_chains = tuple(
            str(k) for k in (data.get("kv_chains") or ()))
        self.kv_index.update(e.address, st.kv_chains)
        st.replica_id = str(data.get("replica_id", "") or "")
        st.uptime_s = float(data.get("uptime_s", 0.0) or 0.0)
        st.max_seq_len = int(data.get("max_seq_len", 0) or 0)
        st.sp = max(1, int(data.get("sp", 1) or 1))
        st.prefill_ms_per_token = float(
            data.get("prefill_ms_per_token", 0.0) or 0.0)
        st.moe_expert_imbalance = float(
            data.get("moe_expert_imbalance", 0.0) or 0.0)
        st.batch_queued = int(data.get("batch_queued", 0) or 0)
        st.batch_active = int(data.get("batch_active", 0) or 0)
        st.batch_preemptions = int(
            data.get("batch_preemptions", 0) or 0)
        st.poll_failures = 0
        st.last_poll_ok_ts = time.monotonic()
        st.updated_at = time.monotonic()
        # fleet aggregation (ISSUE 12): health machine + rollup source
        # + the burn-rate monitor's histogram feed, all off this poll
        self.fleet.note_poll(e.address, True, data)

    # -- manual state injection (tests / push-based telemetry) ------------
    def observe(self, address: str, *, kv_occupancy: float = 0.0,
                queued: int = 0, active_slots: int = 0,
                max_slots: int = 1, queue_wait_ms: float = 0.0,
                prefix_hit_rate: float = 0.0,
                slice_name: str = "",
                adapters_resident: tuple = (),
                model: str = "",
                adapters_registered: tuple = (),
                phase_percentiles: dict | None = None,
                migratable_slots: int = 0,
                hbm_frac: float = 0.0,
                hbm_frac_worst: float = 0.0,
                devices: tuple = (),
                migration_capable: bool = True,
                kv_chains: tuple = (),
                max_seq_len: int = 0,
                sp: int = 1,
                prefill_ms_per_token: float = 0.0,
                moe_expert_imbalance: float = 0.0,
                batch_queued: int = 0,
                batch_active: int = 0,
                batch_preemptions: int = 0) -> None:
        st = self.state[address]
        st.healthy = True
        st.kv_occupancy = kv_occupancy
        st.queued = queued
        st.active_slots = active_slots
        st.max_slots = max(1, max_slots)
        st.queue_wait_ms = queue_wait_ms
        st.prefix_hit_rate = prefix_hit_rate
        st.hbm_frac = hbm_frac
        st.hbm_frac_worst = hbm_frac_worst
        if devices:
            st.devices = tuple(devices)
        st.migration_capable = migration_capable
        if phase_percentiles is not None:
            st.phase_percentiles = dict(phase_percentiles)
        st.migratable_slots = migratable_slots
        if slice_name:
            st.slice_name = slice_name
        if adapters_resident:
            st.adapters_resident = frozenset(adapters_resident)
        if model:
            st.model = model
        if adapters_registered:
            st.adapters_registered = tuple(adapters_registered)
        if kv_chains:
            st.kv_chains = tuple(kv_chains)
            self.kv_index.update(address, st.kv_chains)
        if max_seq_len:
            st.max_seq_len = max_seq_len
        if sp > 1:
            # mirror the max_seq_len/prefill_ms_per_token guards: a
            # push-fed observe() that omits sp must not reset a polled
            # replica's advertised sp axis back to the default
            st.sp = sp
        if prefill_ms_per_token:
            st.prefill_ms_per_token = prefill_ms_per_token
        if moe_expert_imbalance:
            st.moe_expert_imbalance = moe_expert_imbalance
        st.batch_queued = batch_queued
        st.batch_active = batch_active
        if batch_preemptions:
            st.batch_preemptions = batch_preemptions
        st.poll_failures = 0
        st.last_poll_ok_ts = time.monotonic()
        st.updated_at = time.monotonic()
        self.fleet.note_poll(address, True)

    # -- picking ----------------------------------------------------------
    #: a sticky endpoint keeps the session unless its score exceeds the
    #: best alternative by this much (KV locality beats small load skew)
    STICKINESS_MARGIN = 0.5
    #: score penalty for leaving the session's current ICI slice: on
    #: failover (or a load-forced move) a same-slice replica wins score
    #: ties — it shares the multislice interconnect domain of the
    #: replica that holds the session's KV, so cross-replica prefix
    #: migration and any future KV-transfer path stay on ICI instead of
    #: DCN. Small enough that real load imbalance still dominates.
    SLICE_PENALTY = 0.25
    #: score bonus toward the replica that recently served this request's
    #: prefix hash (its prefix cache likely holds the shared prompt
    #: pages). A CONSTANT, while the occupancy/queue/queue_wait terms are
    #: unbounded — cache affinity tips ties and small skews but never
    #: overrides a saturated replica. Below STICKINESS_MARGIN so session
    #: stickiness (exact-KV locality) outranks prefix locality.
    PREFIX_AFFINITY_BONUS = 0.3
    #: score bonus toward replicas whose /state reports the request's
    #: LoRA adapter RESIDENT — serving there skips the hot load (a row
    #: scatter + possible eviction of another tenant's warm adapter).
    #: Below PREFIX_AFFINITY_BONUS: a resident adapter is cheaper to
    #: recreate than a warm KV prefix, and any replica can load it.
    ADAPTER_AFFINITY_BONUS = 0.2
    #: fleet-hit locality (ISSUE 11): bonus toward replicas the KVIndex
    #: says HOLD this request's chain (resident or host-spilled) —
    #: landing there serves the prefix from local memory, landing
    #: elsewhere costs a cross-replica page fetch. Deliberately BELOW
    #: session stickiness (a session's exact-KV replica always
    #: outranks a chain sibling) and ABOVE adapter affinity (warm KV
    #: pages are dearer to recreate than a LoRA row); like the other
    #: affinities it is a constant against unbounded load terms, so it
    #: never beats saturation.
    KV_FLEET_BONUS = 0.25
    #: MoE expert-imbalance penalty (ISSUE 18): scales with how far the
    #: replica's hottest expert runs above the mean (imbalance − 1,
    #: clamped to [0, 1]) — an expert-parallel step is as slow as its
    #: hottest expert shard, so a skewed router prices the replica like
    #: a hot device. BOUNDED by the constant: below STICKINESS_MARGIN
    #: (session KV locality still outranks router skew — moving a
    #: session costs more than a slow expert) and above
    #: ADAPTER_AFFINITY_BONUS (a saturated expert shard outweighs a
    #: warm LoRA row). 0 on dense replicas — the term vanishes.
    MOE_IMBALANCE_PENALTY = 0.25
    _AFFINITY_MAX = 100_000

    # -- slo mode (ISSUE 8) -------------------------------------------------
    #: affinity adjustments in PREDICTED-TTFT MILLISECONDS (slo mode
    #: ranks in ms, not score units). A replica whose prefix cache holds
    #: the prompt head skips most of its prefill — worth a real ms
    #: bonus; a resident adapter saves a row load; leaving the session's
    #: slice costs ICI→DCN on any future KV transfer.
    PREFIX_AFFINITY_BONUS_MS = 100.0
    ADAPTER_AFFINITY_BONUS_MS = 50.0
    KV_FLEET_BONUS_MS = 75.0
    SLICE_PENALTY_MS = 50.0
    #: a sticky session stays put unless its replica's predicted TTFT
    #: exceeds the best candidate's by this much
    STICKINESS_MARGIN_MS = 250.0

    def predicted_ttft_ms(self, st: EndpointState,
                          prompt_tokens: int = 0) -> float | None:
        """Predicted TTFT for a NEW arrival on this replica, from its
        live phase histograms (PR 5) + queue depth: the arrival stands
        behind ``queued`` waiting requests plus itself — admitted in
        BATCHED prefill passes of up to ``max_slots`` prompts each
        (tpuserve coalesces same-burst admissions into one [G, S]
        call, so the queue drains in ceil((queued+1)/max_slots) prefill
        rounds, not queued+1 serial prefills) — plus however long the
        current queue head has already been stuck (queue_wait_ms: a
        moving queue predicts near zero, a wedged one predicts its own
        stall). None when the replica has no histogram data at all — a
        replica that has served nothing predicts nothing — and None
        when the telemetry is STALE (no successful poll within
        STALE_AFTER): a dead replica's last happy histograms predict
        nothing either (ISSUE 12 stale-poll fix; pick() also excludes
        stale endpoints, this guards direct callers like the
        migration orchestrator and push-fed test state).

        ``prompt_tokens`` (long-context satellite): when the caller
        knows the request's prompt length AND the replica exports its
        measured ``prefill_ms_per_token`` rate, the prediction adds the
        EXCESS of this prompt's priced prefill over the histogram p50 —
        a 64k prompt is not a p50 prefill, and routing it as one
        systematically under-predicts the very requests the chunked-sp
        path exists for. 0 (or an un-priced replica) leaves the
        historical model untouched."""
        if (st.last_poll_ok_ts
                and time.monotonic() - st.last_poll_ok_ts
                >= self.STALE_AFTER):
            return None
        pp = st.phase_percentiles or {}
        pf = float((pp.get("prefill") or {}).get("p50", -1.0))
        if pf < 0:
            # no prefill observations yet (e.g. decode-only so far):
            # fall back to the whole-TTFT distribution
            pf = float((pp.get("ttft") or {}).get("p50", -1.0))
            if pf < 0:
                return None
        rounds = -(-(st.queued + 1) // max(1, st.max_slots))
        pred = st.queue_wait_ms + pf * rounds
        if prompt_tokens > 0 and st.prefill_ms_per_token > 0:
            # the arrival's own prefill is one of those rounds; when
            # its priced cost exceeds the p50 round, charge the excess
            pred += max(
                0.0, prompt_tokens * st.prefill_ms_per_token - pf)
        return pred

    # -- KV memory hierarchy (ISSUE 11) -----------------------------------
    def note_chain(self, prefix_key: str, chain_hex: str) -> None:
        """Learn (prefix-head hash → KV chain hash) from a tpuserve
        response's x-aigw-kv-chain header: the next request sharing the
        prefix head resolves to a chain the fleet index can locate."""
        if not prefix_key or not chain_hex:
            return
        self._prefix_chain[prefix_key] = chain_hex
        self._prefix_chain.move_to_end(prefix_key)
        while len(self._prefix_chain) > self._AFFINITY_MAX:
            self._prefix_chain.popitem(last=False)

    def _chain_for(self, headers: dict[str, str] | None) -> str:
        """The request's KV chain hash: an explicit x-aigw-kv-chain
        header wins, else the chain learned for its prefix-head hash
        ("" = unknown — fleet terms vanish)."""
        h = headers or {}
        chain = h.get(KV_CHAIN_HEADER, "")
        if chain:
            return chain
        pkey = h.get(PREFIX_HEADER, "")
        return self._prefix_chain.get(pkey, "") if pkey else ""

    def kv_peers(self, chosen: str,
                 headers: dict[str, str] | None = None,
                 limit: int = 3) -> list[str]:
        """Sibling replicas the fleet index says hold this request's
        chain (healthy, fresh, excluding the chosen replica) — the
        gateway names them in x-aigw-kv-peers so a prefix miss on
        ``chosen`` becomes a cross-replica page fetch."""
        chain = self._chain_for(headers)
        if not chain:
            return []
        now = time.monotonic()
        out = []
        for addr in sorted(self.kv_index.replicas(chain)):
            st = self.state.get(addr)
            if (addr != chosen and st is not None and st.healthy
                    and now - st.updated_at < self.STALE_AFTER):
                out.append(addr)
        return out[:limit]

    def _slice_of(self, addr: str) -> str:
        """Effective slice of an endpoint: the slice the replica itself
        reported on /state when available (tpuserve exports
        jax.devices() topology), else the configured label."""
        st = self.state.get(addr)
        if st is not None and st.slice_name:
            return st.slice_name
        e = self._by_addr.get(addr)
        return e.slice_name if e is not None else ""

    def pick(self, headers: dict[str, str] | None = None,
             explain: dict[str, Any] | None = None,
             exclude: frozenset | set | None = None) -> str | None:
        """Returns 'host:port' for the request, or None if no endpoints.

        ``explain``: optional dict the pick fills with WHY the endpoint
        won (``sticky`` session affinity held / ``prefix_affinity``
        bonus applied to the winner / ``round_robin`` blind fallback,
        plus the number of fresh candidates) — the gateway attaches it
        to the request span so a trace shows the routing decision.

        ``exclude``: replicas to skip entirely — the pre-first-byte
        failover retry (ISSUE 14) re-picks with the replica that just
        refused the connection excluded, so the retry can't land on
        the same dead process the poll loop hasn't condemned yet."""
        if not self.endpoints:
            return None
        exclude = exclude or frozenset()
        now = time.monotonic()
        affinity_key = (headers or {}).get(AFFINITY_HEADER, "")
        prev_addr = self._affinity.get(affinity_key) if affinity_key else None
        prefix_key = (headers or {}).get(PREFIX_HEADER, "")
        prefix_addr = (self._prefix_affinity.get(prefix_key)
                       if prefix_key else None)
        adapter_key = (headers or {}).get(ADAPTER_HEADER, "")
        # long-context satellite: the request's (estimated) prompt
        # token count — context-length filter + prompt-priced TTFT
        try:
            prompt_tokens = max(0, int(
                (headers or {}).get(PROMPT_TOKENS_HEADER, 0) or 0))
        except (TypeError, ValueError):
            prompt_tokens = 0
        # fleet-hit locality (ISSUE 11): replicas the index says hold
        # this request's KV chain — resident or host-spilled
        kv_chain = self._chain_for(headers)
        kv_holders = (self.kv_index.replicas(kv_chain) if kv_chain
                      else frozenset())
        # the slice to prefer: where the session's replica lives —
        # meaningful even when that replica is unhealthy (failover
        # should land on a same-slice sibling)
        prev_slice = self._slice_of(prev_addr) if prev_addr else ""

        def score_of(e: Endpoint) -> float | None:
            st = self.state[e.address]
            if e.address in exclude:
                return None
            if not self.is_routable(e.address):
                # merged view (ISSUE 14): down, DRAINING (the controller
                # is moving its sessions off — new work must not land
                # there), or the circuit breaker is open for it
                return None
            if not (st.healthy and now - st.updated_at < self.STALE_AFTER):
                return None
            score = (
                # WORST-device KV occupancy and memory pressure (ISSUE
                # 10): a mesh replica is priced by its hottest shard —
                # device 0 looking idle says nothing when device 5
                # holds the saturated head shard. Both reduce to the
                # scalar gauges on replicas without per-device data.
                st.worst_kv_occupancy()
                + st.queued / st.max_slots
                + 0.5 * st.active_slots / st.max_slots
                + st.queue_wait_ms / 1000.0
                # MEASURED device-memory pressure (jax memory_stats()
                # polled from /state): a replica near its HBM limit is
                # a bad home for new KV even when its slot/queue
                # numbers look fine — weights/fragmentation/adapters
                # consume HBM the kv_occupancy label can't see. 0.0 on
                # backends without memory stats — the term vanishes.
                + st.worst_hbm_frac()
            )
            if st.moe_expert_imbalance > 1.0:
                # MoE router skew (ISSUE 18): price the replica by its
                # hottest expert — bounded so load terms still dominate
                score += self.MOE_IMBALANCE_PENALTY * min(
                    1.0, st.moe_expert_imbalance - 1.0)
            if prev_slice and self._slice_of(e.address) != prev_slice:
                score += self.SLICE_PENALTY
            if prefix_addr == e.address:
                # prefix-affinity: this replica recently served this
                # prefix hash — its cache likely still holds the pages
                score -= self.PREFIX_AFFINITY_BONUS
            if adapter_key and adapter_key in st.adapters_resident:
                # adapter-affinity: the LoRA row is already loaded
                # here — serving elsewhere pays a hot load (and may
                # evict a warm adapter on the other replica)
                score -= self.ADAPTER_AFFINITY_BONUS
            if e.address in kv_holders:
                # fleet-hit locality: this replica holds the chain's
                # KV (resident or spilled) — serving here skips both
                # the re-prefill AND the cross-replica fetch
                score -= self.KV_FLEET_BONUS
            return score

        scores = {e.address: score_of(e) for e in self.endpoints}
        fresh = {a: s for a, s in scores.items() if s is not None}
        # context-length filter (long-context satellite): drop fresh
        # candidates whose advertised max_seq_len the prompt exceeds —
        # tpuserve would only 400 it after a full admission round-trip
        # (or worse, mid-stream). When EVERY fresh candidate is
        # length-filtered the request is unroutable as a matter of
        # capability, not load: raise so the gateway answers a clean
        # 400 — falling into round-robin would knowingly route to a
        # replica that must reject.
        if prompt_tokens and fresh:
            fits = {a: s for a, s in fresh.items()
                    if not (self.state[a].max_seq_len
                            and prompt_tokens
                            > self.state[a].max_seq_len)}
            if not fits:
                max_ctx = max(self.state[a].max_seq_len for a in fresh)
                if explain is not None:
                    explain.update(
                        ctx_filtered=len(fresh),
                        prompt_tokens=prompt_tokens,
                        max_ctx=max_ctx)
                raise ContextLengthError(prompt_tokens, max_ctx)
            if explain is not None and len(fits) < len(fresh):
                explain["ctx_filtered"] = len(fresh) - len(fits)
            fresh = fits
        # slo mode (ISSUE 8): rank by PREDICTED TTFT from live phase
        # histograms instead of the static score sum. Candidates with no
        # histogram data yet predict 0 (a replica that has served
        # nothing is presumed idle); only when NO candidate has data
        # does the picker fall back to static scoring — and it never
        # sheds blind.
        # offline tier routing (ISSUE 19): batch goes to the replica
        # with the MOST idle capacity — total footprint (interactive
        # slots + queue + its own class's backlog) over slot count,
        # plus KV pressure. Batch is NEVER SLO-shed: the slo branch
        # below (and its shed) is skipped entirely — a loaded fleet
        # queues batch on the least-loaded replica and lets the
        # two-class engine scheduler soak slots as they free up.
        batch_pick = (headers or {}).get(PRIORITY_HEADER, "") == "batch"
        pred_raw: dict[str, float | None] = {}
        if self.mode == "slo" and fresh and not batch_pick:
            pred_raw = {a: self.predicted_ttft_ms(self.state[a],
                                                  prompt_tokens)
                        for a in fresh}
        if batch_pick and fresh:

            def batch_load(a: str) -> float:
                st = self.state[a]
                return ((st.active_slots + st.queued + st.batch_queued)
                        / st.max_slots + st.worst_kv_occupancy())

            chosen = min(sorted(fresh), key=batch_load)
            if explain is not None:
                explain.update(
                    mode="batch", candidates=len(fresh),
                    batch_load=round(batch_load(chosen), 4))
        elif any(p is not None for p in pred_raw.values()):
            pred = {a: (p if p is not None else 0.0)
                    for a, p in pred_raw.items()}
            if self.slo_ttft_ms > 0:
                # admission control on the RAW predictions (capacity,
                # not preference): every candidate blown → shed now
                # rather than queue the request into collapse
                best_raw = min(pred.values())
                if best_raw > self.slo_ttft_ms:
                    retry = max(1, int(
                        -(-(best_raw - self.slo_ttft_ms) // 1000)))
                    if explain is not None:
                        explain.update(
                            mode="slo", shed=True, candidates=len(pred),
                            predicted_ttft_ms={
                                a: round(p, 1) for a, p in pred.items()},
                            retry_after_s=retry)
                    raise SLOShedError(retry, best_raw, self.slo_ttft_ms)
            adj = {}
            for a, p in pred.items():
                v = p
                if prev_slice and self._slice_of(a) != prev_slice:
                    v += self.SLICE_PENALTY_MS
                if prefix_addr == a:
                    v -= self.PREFIX_AFFINITY_BONUS_MS
                if adapter_key and adapter_key in \
                        self.state[a].adapters_resident:
                    v -= self.ADAPTER_AFFINITY_BONUS_MS
                if a in kv_holders:
                    v -= self.KV_FLEET_BONUS_MS
                adj[a] = v
            chosen = min(adj, key=adj.__getitem__)
            if (prev_addr in adj and adj[prev_addr]
                    <= adj[chosen] + self.STICKINESS_MARGIN_MS):
                chosen = prev_addr
            if explain is not None:
                explain.update(
                    mode="slo",
                    candidates=len(adj),
                    predicted_ttft_ms={a: round(p, 1)
                                       for a, p in pred.items()},
                    predicted_ttft_chosen_ms=round(pred[chosen], 1),
                    sticky=chosen == prev_addr and bool(affinity_key),
                    prefix_affinity=chosen == prefix_addr
                    and bool(prefix_key),
                    adapter_affinity=bool(adapter_key) and adapter_key
                    in self.state[chosen].adapters_resident,
                    kv_fleet_hit=chosen in kv_holders,
                    staleness_s=round(
                        self.state[chosen].staleness_s(now), 3),
                )
        elif not fresh:
            # no telemetry (cold start / all down): round-robin blindly
            chosen = next(self._rr)
            for _ in range(len(self.endpoints)):
                # an excluded replica just actively refused — even the
                # blind fallback must not hand the retry right back
                if chosen not in exclude:
                    break
                chosen = next(self._rr)
            if explain is not None:
                explain.update(round_robin=True, candidates=0)
        else:
            best_addr = min(fresh, key=fresh.__getitem__)
            chosen = best_addr
            # per-endpoint stickiness: stay on the session's previous
            # replica (its prefix cache lives there) unless it is now much
            # worse than the best choice
            if (
                prev_addr in fresh
                and fresh[prev_addr] <= fresh[best_addr]
                + self.STICKINESS_MARGIN
            ):
                chosen = prev_addr
            if explain is not None:
                explain.update(
                    candidates=len(fresh),
                    score=round(fresh[chosen], 4),
                    # the mesh memory term the score consumed (ISSUE
                    # 10): worst-DEVICE fraction, not device 0's
                    hbm_frac_worst=round(
                        self.state[chosen].worst_hbm_frac(), 4),
                    sticky=chosen == prev_addr and bool(affinity_key),
                    prefix_affinity=chosen == prefix_addr
                    and bool(prefix_key),
                    adapter_affinity=bool(adapter_key) and adapter_key
                    in self.state[chosen].adapters_resident,
                    kv_fleet_hit=chosen in kv_holders,
                    # how old the chosen replica's telemetry is — the
                    # decision ring / span answer to "was this routed
                    # on current truth or near-stale data"
                    staleness_s=round(
                        self.state[chosen].staleness_s(now), 3),
                )
        if affinity_key:
            self._affinity[affinity_key] = chosen
            self._affinity.move_to_end(affinity_key)
            while len(self._affinity) > self._AFFINITY_MAX:
                self._affinity.popitem(last=False)  # LRU eviction
        if prefix_key:
            # remember where this prefix landed — the NEXT request with
            # the same prefix hash prefers the replica whose cache the
            # routing just warmed (even when load moved it this time)
            self._prefix_affinity[prefix_key] = chosen
            self._prefix_affinity.move_to_end(prefix_key)
            while len(self._prefix_affinity) > self._AFFINITY_MAX:
                self._prefix_affinity.popitem(last=False)
        return chosen
