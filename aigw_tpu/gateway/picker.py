"""TPU endpoint picker — KV-occupancy- and topology-aware load balancing.

The role the reference delegates to an external EPP service speaking
ext_proc (InferencePool → picker sets ``x-gateway-destination-endpoint``,
reference inferencepool.go:47, post_cluster_modify.go:67-80). Here the
picker is in-process: it polls each tpuserve replica's ``/state``
telemetry (KV page occupancy, queue depth, active slots — exported by
aigw_tpu/tpuserve/server.py) and scores endpoints:

    score = kv_occupancy                     (HBM pressure)
          + queued / max_slots               (waiting work)
          + active_slots / max_slots * 0.5   (decode batch load)
          + 0.25 if on a different slice than the session's previous
            endpoint (ICI affinity: keeps a conversation's KV-cache
            locality when replicas span slices)

Unhealthy or stale endpoints are skipped; with no telemetry at all the
picker falls back to round-robin.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Any

import aiohttp

logger = logging.getLogger(__name__)

#: request header carrying a session affinity key (optional)
AFFINITY_HEADER = "x-aigw-session-affinity"


@dataclass(frozen=True)
class Endpoint:
    address: str  # host:port
    slice_name: str = ""  # ICI slice / host grouping label

    @staticmethod
    def parse(value: Any) -> "Endpoint":
        if isinstance(value, str):
            return Endpoint(address=value)
        return Endpoint(address=value["address"],
                        slice_name=value.get("slice", ""))


@dataclass
class EndpointState:
    healthy: bool = False
    kv_occupancy: float = 0.0
    queued: int = 0
    active_slots: int = 0
    max_slots: int = 1
    updated_at: float = 0.0


class EndpointPicker:
    """Picker for one backend pool."""

    STALE_AFTER = 10.0  # seconds without telemetry → treat as unknown

    def __init__(self, endpoints: list[Endpoint],
                 poll_interval: float = 1.0):
        self.endpoints = endpoints
        self.poll_interval = poll_interval
        self.state: dict[str, EndpointState] = {
            e.address: EndpointState() for e in endpoints
        }
        self._rr = itertools.cycle([e.address for e in endpoints])
        self._affinity: dict[str, str] = {}  # session key → address
        self._task: asyncio.Task | None = None

    # -- polling ----------------------------------------------------------
    async def start(self) -> None:
        self._task = asyncio.create_task(self._poll_loop(),
                                         name="endpoint-picker")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _poll_loop(self) -> None:
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=2.0)
        ) as session:
            while True:
                await asyncio.gather(
                    *(self._poll_one(session, e) for e in self.endpoints),
                    return_exceptions=True,
                )
                await asyncio.sleep(self.poll_interval)

    async def _poll_one(self, session: aiohttp.ClientSession,
                        e: Endpoint) -> None:
        st = self.state[e.address]
        try:
            async with session.get(f"http://{e.address}/state") as resp:
                if resp.status != 200:
                    st.healthy = False
                    return
                data = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError):
            st.healthy = False
            return
        st.healthy = True
        st.kv_occupancy = float(data.get("kv_occupancy", 0.0))
        st.queued = int(data.get("queued", 0))
        st.active_slots = int(data.get("active_slots", 0))
        st.max_slots = max(1, int(data.get("max_slots", 1)))
        st.updated_at = time.monotonic()

    # -- manual state injection (tests / push-based telemetry) ------------
    def observe(self, address: str, *, kv_occupancy: float = 0.0,
                queued: int = 0, active_slots: int = 0,
                max_slots: int = 1) -> None:
        st = self.state[address]
        st.healthy = True
        st.kv_occupancy = kv_occupancy
        st.queued = queued
        st.active_slots = active_slots
        st.max_slots = max(1, max_slots)
        st.updated_at = time.monotonic()

    # -- picking ----------------------------------------------------------
    def pick(self, headers: dict[str, str] | None = None) -> str | None:
        """Returns 'host:port' for the request, or None if no endpoints."""
        if not self.endpoints:
            return None
        now = time.monotonic()
        affinity_key = (headers or {}).get(AFFINITY_HEADER, "")
        preferred_slice = ""
        if affinity_key:
            prev = self._affinity.get(affinity_key)
            if prev:
                preferred_slice = next(
                    (e.slice_name for e in self.endpoints
                     if e.address == prev),
                    "",
                )

        best: tuple[float, str] | None = None
        any_fresh = False
        for e in self.endpoints:
            st = self.state[e.address]
            fresh = st.healthy and now - st.updated_at < self.STALE_AFTER
            if not fresh:
                continue
            any_fresh = True
            score = (
                st.kv_occupancy
                + st.queued / st.max_slots
                + 0.5 * st.active_slots / st.max_slots
            )
            if preferred_slice and e.slice_name != preferred_slice:
                score += 0.25
            if best is None or score < best[0]:
                best = (score, e.address)
        if not any_fresh:
            # no telemetry (cold start / all down): round-robin blindly
            chosen = next(self._rr)
        else:
            chosen = best[1]  # type: ignore[index]
        if affinity_key:
            self._affinity[affinity_key] = chosen
            if len(self._affinity) > 100_000:
                self._affinity.clear()  # bounded memory, coarse reset
        return chosen
