"""Fleet control plane — the replica lifecycle manager (ISSUE 14).

The reference system is, above all, a *control plane* (CRD →
InferencePool → endpoint picker, PAPER.md §1). PRs 8–13 built and
exceeded its data plane; this module closes the loop between what the
gateway already *observes* (the PR 12 fleet observability plane: health
state machines, the SLO burn-rate monitor, the decision audit ring) and
what it can now *do*:

- **Autoscaling.** Scale-out consumes :class:`~aigw_tpu.obs.slomon.
  SLOMonitor`'s fleet-key **sustained-overshoot flag** — K consecutive
  windows of measured error-budget burn, never predictions — and acts
  through a pluggable :class:`ReplicaLauncher`. Scale-in fires on
  sustained idle capacity (``idle_ticks`` consecutive controller ticks
  with free slots above ``idle_slots_frac`` and an empty fleet queue)
  and retires via lossless drain, never kill.

- **Lossless drain.** Retirement flips the replica ``draining`` both
  replica-side (``POST /drain`` — tpuserve refuses new admissions with
  503+Retry-After and reports ``draining: true`` on /state) and
  gateway-side (the picker stops routing to draining replicas through
  the merged routability view), lets the gateway's migration
  orchestrator move every live migration-capable stream off (the
  ``_Migrator`` exports immediately for draining sources, bypassing its
  queue-depth and young-stream gates), waits out the stragglers, and
  only then terminates — zero dropped streams by construction.

- **Crash failover.** When :class:`~aigw_tpu.gateway.fleetstate.
  ReplicaHealth` walks a replica to ``down``, the controller drops the
  dead replica's session/prefix affinity entries (queued-at-the-gateway
  work re-routes on its next pick), and after ``down_grace_s`` of
  sustained death (a flapping replica must not trigger a
  launch/kill oscillation) launches a replacement when the live pool
  fell below ``min_replicas``. Streams caught mid-flight resume from
  their last exported state where one exists (the gateway retries the
  continuation on a sibling) and otherwise end with a clean typed error
  event — never a silent hang or torn stream.

Every lifecycle action lands in the controller's bounded event ring
(``/fleet/state`` → ``controller``), the decision audit ring
(``/debug/decisions``, ``lifecycle=...`` entries), and the
``aigw_ctl_*`` gauges on ``/fleet/metrics``.

The in-tree launcher is :class:`LocalProcessLauncher` — a subprocess
per replica through ``benchmarks/serve_child.py`` (exactly the bench
harness topology, which is also how tpuserve deploys on one host).
Production launchers (k8s, GCE MIGs) implement the same two-method
interface and are out of scope here.
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any

import aiohttp

from aigw_tpu.gateway.fleetstate import DEGRADED, DOWN, UNKNOWN, UP
from aigw_tpu.gateway.picker import EndpointPicker
from aigw_tpu.obs.slomon import SLOMonitor

logger = logging.getLogger(__name__)


@dataclass
class ControllerConfig:
    """Knobs for one backend pool's lifecycle manager. Defaults are
    deliberately conservative — production ticks in seconds; tests and
    the bench shrink everything."""

    enabled: bool = True
    #: pool size envelope: failover replaces below min, scale-out stops
    #: at max, scale-in never goes below min
    min_replicas: int = 1
    max_replicas: int = 4
    #: control-loop period
    tick_s: float = 1.0
    #: minimum seconds between any two scale actions (out, in, or
    #: failover replacement) — the anti-oscillation hysteresis
    scale_cooldown_s: float = 30.0
    #: scale-in predicate: this many CONSECUTIVE ticks of idle capacity
    #: (free-slot fraction ≥ idle_slots_frac, zero queued, no overshoot)
    idle_ticks: int = 60
    idle_slots_frac: float = 0.75
    #: a replica must stay `down` this long before the controller
    #: launches its replacement (flap protection — the health machine's
    #: own hysteresis walks it back up in 2 good polls)
    down_grace_s: float = 5.0
    #: drain budget: after this long a draining replica is retired with
    #: whatever stragglers remain (they see clean typed errors, never a
    #: silent hang — and the timeout is the operator's backstop against
    #: a wedged session pinning a replica forever)
    drain_timeout_s: float = 120.0
    #: launcher spec (config form): {"kind": "local", "spec": {...},
    #: "env": {...}} — None means observe/drain/re-route only, no
    #: launch capability
    launcher: dict | None = None

    @staticmethod
    def parse(value: dict) -> "ControllerConfig":
        """Raises ValueError on malformed input (Backend.parse maps it
        to ConfigError)."""
        if not isinstance(value, dict):
            raise ValueError(f"controller must be a mapping, got "
                             f"{type(value).__name__}")
        cfg = ControllerConfig(
            enabled=bool(value.get("enabled", True)),
            min_replicas=int(value.get("min_replicas", 1)),
            max_replicas=int(value.get("max_replicas", 4)),
            tick_s=float(value.get("tick_s", 1.0)),
            scale_cooldown_s=float(value.get("scale_cooldown_s", 30.0)),
            idle_ticks=int(value.get("idle_ticks", 60)),
            idle_slots_frac=float(value.get("idle_slots_frac", 0.75)),
            down_grace_s=float(value.get("down_grace_s", 5.0)),
            drain_timeout_s=float(value.get("drain_timeout_s", 120.0)),
            launcher=value.get("launcher"),
        )
        if cfg.min_replicas < 0 or cfg.max_replicas < 1:
            raise ValueError("controller replica bounds must be >= 0/1")
        if cfg.min_replicas > cfg.max_replicas:
            raise ValueError(
                f"controller min_replicas {cfg.min_replicas} > "
                f"max_replicas {cfg.max_replicas}")
        if cfg.tick_s <= 0:
            raise ValueError("controller tick_s must be > 0")
        if not 0.0 < cfg.idle_slots_frac <= 1.0:
            raise ValueError("controller idle_slots_frac must be in "
                             "(0, 1]")
        lc = cfg.launcher
        if lc is not None and dict(lc).get("kind", "local") != "local":
            raise ValueError(
                f"unknown controller launcher kind "
                f"{dict(lc).get('kind')!r}; in-tree: 'local'")
        return cfg


class ReplicaLauncher:
    """The controller's actuation interface. Implementations boot a
    replica process/pod and return its ``host:port``; terminate must be
    GRACEFUL (the controller drains before calling it)."""

    async def launch(self) -> str:
        raise NotImplementedError

    async def terminate(self, address: str) -> None:
        raise NotImplementedError

    def owns(self, address: str) -> bool:
        """Whether this launcher started (and may terminate) a replica.
        The controller never terminates replicas it didn't launch — it
        drains and removes them from routing instead."""
        return False

    async def close(self) -> None:
        """Terminate everything this launcher started (gateway
        shutdown must not orphan replica processes)."""


class LocalProcessLauncher(ReplicaLauncher):
    """Subprocess-per-replica launcher over the bench harness's
    ``benchmarks/serve_child.py`` topology: one tpuserve process per
    launch, serving the spec's model on a fresh port. SIGTERM on
    terminate rides tpuserve's graceful drain handler, SIGKILL only
    after ``term_grace_s``."""

    def __init__(self, spec: dict, child_path: str = "",
                 env: dict | None = None, boot_timeout_s: float = 1200.0,
                 term_grace_s: float = 30.0):
        self.spec = dict(spec)
        if not child_path:
            here = os.path.dirname(os.path.abspath(__file__))
            child_path = os.path.normpath(os.path.join(
                here, "..", "..", "benchmarks", "serve_child.py"))
        self.child_path = child_path
        self.env = dict(env or {})
        self.boot_timeout_s = boot_timeout_s
        self.term_grace_s = term_grace_s
        self._procs: dict[str, subprocess.Popen] = {}
        #: exit codes of replicas this launcher terminated (the drain
        #: rig asserts exit 0 — a clean drain, not a SIGKILL)
        self._exit_codes: dict[str, int] = {}

    @staticmethod
    def from_config(value: dict) -> "LocalProcessLauncher":
        v = dict(value)
        return LocalProcessLauncher(
            spec=dict(v.get("spec") or {}),
            child_path=str(v.get("child", "")),
            env={str(k): str(x) for k, x in (v.get("env") or {}).items()},
            boot_timeout_s=float(v.get("boot_timeout_s", 1200.0)),
            term_grace_s=float(v.get("term_grace_s", 30.0)),
        )

    def _wait_port(self, proc: subprocess.Popen) -> int:
        """Blocking SERVE_PORT= parse (runs on a worker thread); the
        select loop keeps a wedged-but-alive child from holding the
        read forever — same discipline as the bench harness."""
        import select

        fd = proc.stdout.fileno()
        os.set_blocking(fd, False)
        deadline = time.time() + self.boot_timeout_s
        buf = ""
        while time.time() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"replica child exited rc={proc.returncode} before "
                    "listening")
            r, _, _ = select.select([fd], [], [], 2.0)
            if not r:
                continue
            buf += os.read(fd, 4096).decode(errors="replace")
            *complete, buf = buf.split("\n")
            for line in complete:
                if line.startswith("SERVE_PORT="):
                    return int(line.split("=", 1)[1])
        proc.kill()
        raise RuntimeError("replica child never reported a port")

    async def launch(self) -> str:
        proc = subprocess.Popen(
            [sys.executable, self.child_path, json.dumps(self.spec)],
            stdout=subprocess.PIPE, text=True,
            env=dict(os.environ, **self.env),
        )
        try:
            port = await asyncio.to_thread(self._wait_port, proc)
        except BaseException:
            if proc.poll() is None:
                proc.kill()
            raise
        addr = f"127.0.0.1:{port}"
        self._procs[addr] = proc
        logger.info("launched replica %s (pid %d)", addr, proc.pid)
        return addr

    def owns(self, address: str) -> bool:
        return address in self._procs

    def pid(self, address: str) -> int | None:
        proc = self._procs.get(address)
        return proc.pid if proc is not None else None

    async def terminate(self, address: str) -> None:
        proc = self._procs.pop(address, None)
        if proc is None:
            return
        if proc.poll() is None:
            proc.terminate()  # SIGTERM → graceful drain → exit 0
            try:
                await asyncio.to_thread(proc.wait, self.term_grace_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                await asyncio.to_thread(proc.wait, 10)
        self._exit_codes[address] = proc.returncode
        logger.info("terminated replica %s rc=%s", address,
                    proc.returncode)

    def returncode(self, address: str) -> int | None:
        """Exit code of a terminated replica (None while running or
        unknown) — the drain rig asserts exit 0."""
        proc = self._procs.get(address)
        if proc is not None:
            return proc.returncode
        return self._exit_codes.get(address)

    async def close(self) -> None:
        for addr in list(self._procs):
            await self.terminate(addr)


#: counters every snapshot carries — drift-checked against
#: obs.metrics.CONTROLLER_GAUGES by the tier-1 smoke
COUNTERS = ("scale_outs", "scale_ins", "drains", "retires",
            "failovers", "launch_failures")


class FleetController:
    """Lifecycle manager for ONE backend pool, layered on the picker's
    existing poll loop — the controller adds no replica traffic beyond
    the ``POST /drain`` it sends when retiring.

    Deterministically testable: ``tick(now=...)`` is the whole control
    step and takes an injectable clock; ``start()`` merely runs it on a
    timer."""

    EVENTS_MAX = 64

    def __init__(self, picker: EndpointPicker, cfg: ControllerConfig,
                 launcher: ReplicaLauncher | None = None,
                 decisions=None, backend: str = "pool"):
        self.picker = picker
        self.cfg = cfg
        self.launcher = launcher
        #: the gateway's DecisionRing — every lifecycle action is a
        #: routing-relevant decision and lands there too (None in
        #: standalone/test use)
        self.decisions = decisions
        self.backend = backend
        self.counters: dict[str, int] = {k: 0 for k in COUNTERS}
        self.events: collections.deque = collections.deque(
            maxlen=self.EVENTS_MAX)
        self.idle_streak = 0
        #: None = no scale action yet (the first one is never
        #: cooldown-blocked — 0.0 would block it for cooldown seconds
        #: of a freshly-booted monotonic clock)
        self._last_scale_ts: float | None = None
        self._down_since: dict[str, float] = {}
        self._failover_done: set[str] = set()
        self._launches: set[asyncio.Task] = set()
        self._drains: dict[str, asyncio.Task] = {}
        self._drain_poll_s = max(0.05, min(0.5, cfg.tick_s / 2))
        self._session: aiohttp.ClientSession | None = None
        self._task: asyncio.Task | None = None

    # -- lifecycle --------------------------------------------------------
    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop(),
                                         name=f"fleet-ctl-{self.backend}")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        for t in list(self._launches) + list(self._drains.values()):
            t.cancel()
        if self._session is not None and not self._session.closed:
            await self._session.close()
        if self.launcher is not None:
            await self.launcher.close()

    async def _loop(self) -> None:
        while True:
            try:
                await self.tick()
            except Exception:  # noqa: BLE001 — the control loop must
                # survive any single tick's failure (a dead controller
                # is worse than a skipped tick)
                logger.exception("controller tick failed")
            await asyncio.sleep(self.cfg.tick_s)

    async def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=10.0))
        return self._session

    # -- bookkeeping ------------------------------------------------------
    def _event(self, action: str, replica: str = "",
               reason: str = "") -> None:
        ev = {"ts": round(time.time(), 3), "action": action}
        if replica:
            ev["replica"] = replica
        if reason:
            ev["reason"] = reason
        self.events.append(ev)
        if self.decisions is not None:
            self.decisions.record(lifecycle=action, backend=self.backend,
                                  replica=replica, reason=reason)
        logger.info("fleet-ctl[%s] %s %s %s", self.backend, action,
                    replica, reason)

    def _health_of(self, addr: str) -> str:
        return self.picker.fleet.health_of(addr)

    def live_addrs(self) -> list[str]:
        """Replicas currently carrying (or about to carry) load: up,
        degraded, or too new to have been polled — excluding draining,
        down, and mid-retirement ones."""
        return [e.address for e in self.picker.endpoints
                if self._health_of(e.address) in (UP, DEGRADED, UNKNOWN)
                and e.address not in self._drains]

    def _live_count(self) -> int:
        return len(self.live_addrs()) + len(self._launches)

    def _cooldown_ok(self, now: float) -> bool:
        return (self._last_scale_ts is None
                or now - self._last_scale_ts >= self.cfg.scale_cooldown_s)

    # -- the control step -------------------------------------------------
    async def tick(self, now: float | None = None) -> None:
        """One reconcile pass: failover detection, then the scale-out
        and scale-in predicates. All actuation is spawned as tasks so a
        slow launch/drain never blocks detection."""
        now = time.monotonic() if now is None else now
        self._tick_failover(now)
        self._tick_scale_out(now)
        self._tick_scale_in(now)

    def _tick_failover(self, now: float) -> None:
        down = {e.address for e in self.picker.endpoints
                if self._health_of(e.address) == DOWN}
        # replicas that recovered (restart on the same port walks back
        # up through the health machine's 2-good-poll gate) re-arm
        for addr in list(self._down_since):
            if addr not in down:
                self._down_since.pop(addr, None)
                self._failover_done.discard(addr)
        for addr in down:
            if addr not in self._down_since:
                # first sighting: re-route queued work NOW — affine
                # sessions must not chase the dead replica through the
                # stickiness margin while the grace timer runs
                self._down_since[addr] = now
                self.picker.forget_endpoint(addr)
                self._event("reroute", addr, "replica down")
            if addr in self._failover_done:
                continue
            if now - self._down_since[addr] < self.cfg.down_grace_s:
                continue  # flap protection
            self._failover_done.add(addr)
            self.counters["failovers"] += 1
            self._event("failover", addr,
                        f"down for {now - self._down_since[addr]:.1f}s")
            if (self._live_count() < self.cfg.min_replicas
                    and self.launcher is not None):
                self._last_scale_ts = now
                self._spawn_launch("failover replacement")

    def _tick_scale_out(self, now: float) -> None:
        # keyed on INTERACTIVE SLO burn only (ISSUE 19): the burn
        # monitor reads the replicas' TTFT histograms, and the engine
        # never observes batch streams into those — a fleet saturated
        # with offline soak but meeting interactive TTFT does not
        # scale out; batch absorbs the slack instead
        mon = self.picker.fleet.slomon
        if mon is None or not mon.sustained(SLOMonitor.FLEET_KEY):
            return
        if self._live_count() >= self.cfg.max_replicas:
            return
        if not self._cooldown_ok(now) or self._launches:
            return
        if self.launcher is None:
            self._event("scale_out_skipped", reason="no launcher")
            return
        self._last_scale_ts = now
        self.counters["scale_outs"] += 1
        self._event("scale_out",
                    reason="sustained SLO overshoot (measured burn)")
        self._spawn_launch("scale_out")

    def _tick_scale_in(self, now: float) -> None:
        live = self.live_addrs()
        if len(live) <= self.cfg.min_replicas or self._drains:
            self.idle_streak = 0
            return
        mon = self.picker.fleet.slomon
        if mon is not None and mon.sustained(SLOMonitor.FLEET_KEY):
            self.idle_streak = 0
            return
        slots_total = slots_free = queued = 0
        for addr in live:
            st = self.picker.state.get(addr)
            if st is None or not st.healthy:
                continue
            slots_total += st.max_slots
            # idleness is judged on INTERACTIVE occupancy (ISSUE 19):
            # batch soak is SUPPOSED to fill idle slots — counting it
            # would let a big offline backlog pin fleet capacity the
            # interactive class no longer needs
            slots_free += max(0, st.max_slots
                              - (st.active_slots - st.batch_active))
            queued += st.queued
        idle = (slots_total > 0 and queued == 0
                and slots_free / slots_total >= self.cfg.idle_slots_frac)
        self.idle_streak = self.idle_streak + 1 if idle else 0
        if self.idle_streak < self.cfg.idle_ticks:
            return
        if not self._cooldown_ok(now):
            return
        victim = self._scale_in_victim(live)
        if victim is None:
            self.idle_streak = 0
            return
        self._last_scale_ts = now
        self.idle_streak = 0
        self.counters["scale_ins"] += 1
        self._event("scale_in", victim,
                    f"idle for {self.cfg.idle_ticks} ticks")
        self._spawn_drain(victim, "scale_in")

    def _scale_in_victim(self, live: list[str]) -> str | None:
        """Least-loaded retirement candidate, preferring replicas the
        launcher owns (those can actually be terminated; a configured
        static replica is only drained out of routing)."""
        def load(addr: str) -> float:
            st = self.picker.state.get(addr)
            if st is None:
                return 0.0
            return (st.active_slots + st.queued
                    + float(getattr(st, "migratable_slots", 0)) * 0.01
                    # prefer retiring the replica with the least batch
                    # backlog to wait out (its state is replica-local)
                    + float(getattr(st, "batch_queued", 0)) * 0.1)

        owned = [a for a in live
                 if self.launcher is not None and self.launcher.owns(a)]
        pool = owned or list(live)
        return min(pool, key=load) if pool else None

    # -- actuation --------------------------------------------------------
    def _spawn_launch(self, reason: str) -> None:
        task = asyncio.create_task(self._launch(reason))
        self._launches.add(task)
        task.add_done_callback(self._launches.discard)

    async def _launch(self, reason: str) -> None:
        try:
            addr = await self.launcher.launch()
        except Exception as e:  # noqa: BLE001 — a failed launch is a
            # counted event, not a dead control loop
            self.counters["launch_failures"] += 1
            self._event("launch_failed", reason=f"{reason}: {e}")
            return
        self.picker.add_endpoint(addr)
        self._event("launch", addr, reason)

    def _spawn_drain(self, addr: str, reason: str) -> None:
        if addr in self._drains:
            return
        task = asyncio.create_task(self.drain_and_retire(addr, reason))
        self._drains[addr] = task
        task.add_done_callback(lambda _t: self._drains.pop(addr, None))

    async def drain_and_retire(self, addr: str,
                               reason: str = "operator") -> bool:
        """The lossless-drain protocol: (1) flip the replica draining on
        BOTH sides — ``POST /drain`` makes tpuserve refuse new
        admissions with 503 and report ``draining: true`` on /state,
        the fleet mark makes the picker stop routing immediately (new
        streams never land on it); (2) the gateway's migration
        orchestrator moves every live migration-capable stream off
        (draining sources export unconditionally); (3) wait until the
        replica reports zero active slots and an empty queue, or the
        drain budget runs out; (4) terminate (launcher-owned) and
        remove from the pool. Returns True when the replica was
        verifiably empty at retirement."""
        self.counters["drains"] += 1
        self._event("drain_start", addr, reason)
        posted = await self._post_drain(addr, True)
        if not posted:
            self._event("drain_post_failed", addr,
                        "replica /drain unreachable; gateway-side only")
        deadline = time.monotonic() + self.cfg.drain_timeout_s
        drained = False
        while time.monotonic() < deadline:
            # re-assert each pass: a poll of a replica that doesn't
            # report `draining` on /state (stubs, old builds) would
            # otherwise clear the overlay between passes
            self.picker.fleet.mark_draining(addr, True)
            st = self.picker.state.get(addr)
            if st is None:
                break  # removed underneath us
            if self._health_of(addr) == DOWN:
                break  # died mid-drain: nothing left to wait for
            if (st.healthy and st.active_slots == 0 and st.queued == 0
                    # batch backlog drains BEFORE retirement (ISSUE
                    # 19): queued + parked offline work is replica-
                    # local in-memory state — pulling the plug early
                    # would strand it, so the soak finishes first
                    and st.batch_queued == 0 and st.batch_active == 0
                    and st.staleness_s() >= 0):
                drained = True
                break
            await asyncio.sleep(self._drain_poll_s)
        self._event("drain_complete" if drained else "drain_timeout",
                    addr)
        if self.launcher is not None and self.launcher.owns(addr):
            await self.launcher.terminate(addr)
        self.picker.remove_endpoint(addr)
        self.counters["retires"] += 1
        self._event("retire", addr, reason)
        return drained

    async def _post_drain(self, addr: str, on: bool) -> bool:
        try:
            session = await self._get_session()
            async with session.post(f"http://{addr}/drain",
                                    json={"on": on}) as r:
                return r.status == 200
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            return False

    # -- read side --------------------------------------------------------
    def gauge_values(self) -> dict[str, Any]:
        """Flat numeric view for obs.metrics.CONTROLLER_GAUGES."""
        return {
            **self.counters,
            "launches_in_flight": len(self._launches),
            "drains_in_progress": len(self._drains),
            "replicas_min": self.cfg.min_replicas,
            "replicas_max": self.cfg.max_replicas,
            "replicas_live": len(self.live_addrs()),
            "idle_streak": self.idle_streak,
        }

    def snapshot(self) -> dict[str, Any]:
        """The ``controller`` block of ``/fleet/state`` (and the
        fleetwatch table's controller lines)."""
        return {
            "enabled": self.cfg.enabled,
            "min_replicas": self.cfg.min_replicas,
            "max_replicas": self.cfg.max_replicas,
            "launcher": (type(self.launcher).__name__
                         if self.launcher is not None else ""),
            "counters": dict(self.counters),
            "launches_in_flight": len(self._launches),
            "drains_in_progress": sorted(self._drains),
            "replicas_live": sorted(self.live_addrs()),
            "idle_streak": self.idle_streak,
            "events": list(self.events),
        }


def build_launcher(value: dict | None) -> ReplicaLauncher | None:
    """Launcher from the config block's ``launcher`` mapping (the
    config layer froze it; thaw defensively)."""
    if not value:
        return None
    from aigw_tpu.config.model import _thaw

    v = _thaw(value) if not isinstance(value, dict) else dict(value)
    kind = str(v.get("kind", "local"))
    if kind == "local":
        return LocalProcessLauncher.from_config(v)
    raise ValueError(f"unknown launcher kind {kind!r}")
