"""Per-backend circuit breaker (Envoy outlier-detection parity).

The reference data plane gets passive health checking from Envoy (outlier
ejection on consecutive 5xx, reference cluster config); natively: after
``threshold`` consecutive failures a backend's circuit opens for
``cooldown`` seconds and the selector skips it, except when every
candidate is open (fail-static: better to try a suspect backend than to
reject outright). Any success closes the circuit.

Unified with the fleet health machine (ISSUE 14): the gateway keys the
same breaker by replica address for picked endpoints, installs an
``on_transition`` hook that lands every open/close in the fleet event
ring, and the endpoint picker consults ``is_open`` through its merged
routability view — a breaker-open replica can no longer be scored
healthy just because its /state polls still answer.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable


@dataclass
class _State:
    consecutive_failures: int = 0
    open_until: float = 0.0
    #: whether the last recorded transition was an open (so close events
    #: fire once, not on every success)
    open_recorded: bool = False


#: transition hook signature: (key, opened, consecutive_failures)
TransitionHook = Callable[[str, bool, int], None]


class CircuitBreaker:
    def __init__(self, threshold: int = 5, cooldown: float = 15.0,
                 on_transition: TransitionHook | None = None):
        self.threshold = threshold
        self.cooldown = cooldown
        #: called on every open/close transition — the gateway wires it
        #: into the fleet event rings; exceptions are the caller's bug
        #: (the hook must be non-raising bookkeeping)
        self.on_transition = on_transition
        self._states: dict[str, _State] = {}

    def _state(self, backend: str) -> _State:
        st = self._states.get(backend)
        if st is None:
            st = _State()
            self._states[backend] = st
        return st

    def record_success(self, backend: str) -> None:
        st = self._state(backend)
        st.consecutive_failures = 0
        st.open_until = 0.0
        if st.open_recorded:
            st.open_recorded = False
            if self.on_transition is not None:
                self.on_transition(backend, False, 0)

    def record_failure(self, backend: str, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        st = self._state(backend)
        st.consecutive_failures += 1
        if st.consecutive_failures >= self.threshold:
            st.open_until = now + self.cooldown
            if not st.open_recorded:
                st.open_recorded = True
                if self.on_transition is not None:
                    self.on_transition(backend, True,
                                       st.consecutive_failures)

    def is_open(self, backend: str, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        st = self._states.get(backend)
        return st is not None and now < st.open_until

    def snapshot(self) -> dict[str, dict]:
        now = time.monotonic()
        return {
            name: {
                "consecutive_failures": st.consecutive_failures,
                "open_for_s": max(0.0, round(st.open_until - now, 1)),
            }
            for name, st in self._states.items()
            if st.consecutive_failures or st.open_until > now
        }
