"""Fleet-wide KV page index — chain hash → replicas that hold it.

The gateway half of the KV memory hierarchy (ISSUE 11): every tpuserve
replica advertises a digest of the content chain hashes it can serve KV
for (resident prefix-cache entries + host-spilled pages) on ``/state``
— the endpoint picker's existing poll loop feeds those digests in here.
The index answers one question: *which replicas already hold the KV for
this prompt chain?* Two consumers:

- the picker prices **fleet-hit locality** into its score (a bounded
  bonus toward replicas holding the request's chain — below session
  stickiness, above adapter affinity), and
- the gateway names those replicas in the ``x-aigw-kv-peers`` request
  header, so a prefix miss on the chosen replica becomes a cross-
  replica page fetch over ``POST /kv/pages`` instead of a re-prefill —
  Mooncake-style KV-centric serving.

Merge semantics are replace-per-replica: each poll swaps the replica's
advertised key set wholesale (digests are bounded snapshots, not
deltas). A replica that dies or goes stale is removed outright — a
fetch pointed at a dead sibling would only waste the fetch timeout.
Pure bookkeeping, no I/O, not thread-safe beyond the event loop it
lives on (the picker's).
"""

from __future__ import annotations


class KVIndex:
    """chain-hash (hex) → set of replica addresses."""

    #: per-replica digest bound — a misbehaving replica cannot balloon
    #: the gateway's memory. Sized for the LONG-CONTEXT geometry: the
    #: replica-side export bound is geometry-aware now (tpuserve
    #: Engine.kv_digest_max() scales with max_pages_per_seq off the
    #: KV_DIGEST_MAX=4096 floor — a single 128k chain at 128-token
    #: pages is 1024 keys, so the old flat 4096 truncated the fleet
    #: index to ~4 long chains per replica and long-prefix fleet hits
    #: silently vanished). The gateway accepts the largest digest any
    #: supported geometry exports: 8 chains × 8192 pages (1M tokens at
    #: 128-token pages). ~64 B/key ⇒ ≤4 MiB per replica, still a
    #: memory bound, not a truncation in practice.
    MAX_KEYS_PER_REPLICA = 65536

    def __init__(self) -> None:
        self._by_addr: dict[str, frozenset[str]] = {}
        self._by_key: dict[str, set[str]] = {}

    def update(self, addr: str, keys) -> None:
        """Replace ``addr``'s advertised chain set with ``keys``."""
        new = frozenset(
            str(k) for i, k in enumerate(keys)
            if i < self.MAX_KEYS_PER_REPLICA)
        old = self._by_addr.get(addr, frozenset())
        for k in old - new:
            holders = self._by_key.get(k)
            if holders is not None:
                holders.discard(addr)
                if not holders:
                    del self._by_key[k]
        for k in new - old:
            self._by_key.setdefault(k, set()).add(addr)
        if new:
            self._by_addr[addr] = new
        else:
            self._by_addr.pop(addr, None)

    def remove(self, addr: str) -> None:
        """Drop every entry for a dead/stale replica (expiry)."""
        self.update(addr, ())

    def replicas(self, key: str) -> frozenset:
        """Replicas advertising this chain hash (frozen snapshot)."""
        return frozenset(self._by_key.get(key, ()))

    @property
    def chains(self) -> int:
        """Distinct chain hashes indexed fleet-wide."""
        return len(self._by_key)

    @property
    def replicas_indexed(self) -> int:
        return len(self._by_addr)
