"""Gateway-side fleet observability plane (ISSUE 12).

PRs 8–11 made the system a *fleet* — SLO routing, mid-stream migration,
cross-replica KV fetch — whose behavior was still only visible one
replica ``/state`` at a time. This module is the one-pane-of-glass
aggregation layer, fed by the endpoint picker's existing ``_poll_one``
loop (no new polling traffic):

- :class:`ReplicaHealth` — a per-replica health state machine
  (``up / degraded / draining / down``) with hysteresis over consecutive
  poll failures and sustained SLO overshoot, every transition recorded
  with its timestamp in a bounded event ring;
- :class:`FleetState` — per-backend aggregation: health map, last-good
  replica telemetry, fleet rollups (slots, worst/mean KV occupancy,
  worst device-memory fraction, spill/fetch/migration totals, resident
  adapter union) and the :class:`~aigw_tpu.obs.slomon.SLOMonitor` feed;
- :class:`DecisionRing` — the routing-decision audit ring behind
  ``GET /debug/decisions``: every ``pick(explain=)`` dict in full
  (candidates, scores, predicted-TTFT map, affinity terms, shed events
  with Retry-After, migration triggers), keyed by the replica's
  ``x-aigw-request-id`` so a gateway decision joins the tpuserve
  flight-recorder timeline PR 5 already serves;
- :func:`relabel_exposition` — the Prometheus federation rewriter
  behind ``GET /fleet/metrics``: every replica's ``tpuserve_*`` samples
  re-exported with a ``replica`` label (the DEVICE_GAUGES labeled-render
  pattern from PR 10, applied fleet-wide) so ONE scrape covers the
  whole fleet.

Pure bookkeeping — no I/O, event-loop-confined like the picker state it
aggregates (DecisionRing appends are GIL-atomic deque ops).
"""

from __future__ import annotations

import collections
import re
import time
from typing import Any

from aigw_tpu.obs.slomon import SLOMonitor, sum_buckets

#: health states, in degradation order
UP = "up"
DEGRADED = "degraded"
DRAINING = "draining"
DOWN = "down"
UNKNOWN = "unknown"


class ReplicaHealth:
    """Health state machine for one replica, driven by poll outcomes.

    Hysteresis, both directions: the FIRST failed poll only degrades
    (one dropped packet must not fail a replica out of the pool),
    ``FAILURES_DOWN`` consecutive failures mark it down, and a down
    replica needs ``RECOVERY_POLLS`` consecutive good polls before it
    is trusted up again (a replica flapping through restart must not
    oscillate the fleet view every poll). ``draining`` is an operator /
    control-plane overlay (ROADMAP item 2's lossless drain): polls
    still succeed, the state machine reports DRAINING until released.
    Sustained SLO overshoot (the slomon predicate) degrades a replica
    that answers every poll but is burning its error budget.
    """

    FAILURES_DOWN = 3
    RECOVERY_POLLS = 2
    EVENTS_MAX = 32

    __slots__ = ("state", "since", "failures", "successes", "draining",
                 "replica_id", "events", "breaker_open")

    def __init__(self) -> None:
        self.state = UNKNOWN
        self.since = time.time()
        self.failures = 0    # consecutive failed polls
        self.successes = 0   # consecutive good polls
        self.draining = False
        self.replica_id = ""  # identity from /state; change = restart
        # circuit-breaker overlay (ISSUE 14): the gateway's per-replica
        # breaker feeds its open/close transitions here so the fleet
        # view and the breaker can never disagree about a replica that
        # answers /state polls but fails every request
        self.breaker_open = False
        self.events: collections.deque = collections.deque(
            maxlen=self.EVENTS_MAX)

    def _to(self, new: str, reason: str) -> None:
        if new == self.state:
            return
        self.events.append({
            "ts": round(time.time(), 3),
            "from": self.state,
            "to": new,
            "reason": reason,
        })
        self.state = new
        self.since = time.time()

    def note_success(self, replica_id: str = "",
                     slo_overshoot: bool = False) -> None:
        self.failures = 0
        self.successes += 1
        if replica_id and self.replica_id and replica_id != self.replica_id:
            # same address, new process: record the restart in the ring
            # (counters reset; the slomon anchor guard handles deltas)
            self.events.append({
                "ts": round(time.time(), 3),
                "event": "restart",
                "old_replica_id": self.replica_id,
                "new_replica_id": replica_id,
            })
        if replica_id:
            self.replica_id = replica_id
        if self.state == DOWN and self.successes < self.RECOVERY_POLLS:
            return  # one good poll doesn't resurrect a down replica
        if self.draining:
            self._to(DRAINING, "drain_requested")
        elif slo_overshoot:
            self._to(DEGRADED, "slo_overshoot_sustained")
        else:
            self._to(UP, "poll_ok")

    def note_failure(self) -> None:
        self.successes = 0
        self.failures += 1
        if self.failures >= self.FAILURES_DOWN:
            self._to(DOWN, f"poll_failures={self.failures}")
        elif self.state != DOWN:
            self._to(DEGRADED, f"poll_failures={self.failures}")

    def set_draining(self, on: bool = True) -> None:
        self.draining = on
        if on and self.state not in (DOWN,):
            self._to(DRAINING, "drain_requested")
        # released: the next successful poll restores up/degraded

    def note_breaker(self, opened: bool, failures: int = 0) -> None:
        """Circuit-breaker transition for this replica: the open/close
        lands in the same event ring as health transitions, and the
        ``breaker_open`` flag joins the picker's merged routability
        view (a breaker-open replica is never scored healthy)."""
        if opened == self.breaker_open:
            return
        self.breaker_open = opened
        self.events.append({
            "ts": round(time.time(), 3),
            "event": "breaker_open" if opened else "breaker_closed",
            "consecutive_failures": failures,
        })

    def to_dict(self) -> dict[str, Any]:
        return {
            "state": self.state,
            "since": round(self.since, 3),
            "consecutive_failures": self.failures,
            "draining": self.draining,
            "breaker_open": self.breaker_open,
            "replica_id": self.replica_id,
            "events": list(self.events),
        }


class FleetState:
    """Per-backend fleet aggregation, fed by the picker's poll loop."""

    def __init__(self, slomon: SLOMonitor | None = None):
        self.slomon = slomon
        self.health: dict[str, ReplicaHealth] = {}
        # last successfully polled /state payload per replica — the
        # rollup's counter source (kept across failures so a flapping
        # replica's totals don't flicker to zero)
        self.last_state: dict[str, dict] = {}
        # latest cumulative TTFT buckets per live replica (fleet sum)
        self._cum: dict[str, dict] = {}

    # -- write side (the picker's _poll_one) ------------------------------
    def note_poll(self, addr: str, ok: bool,
                  data: dict | None = None,
                  ts: float | None = None) -> None:
        h = self.health.setdefault(addr, ReplicaHealth())
        if not ok:
            h.note_failure()
            if h.state == DOWN:
                # its cumulative counters will restart from zero; drop
                # the fleet-sum contribution and the window anchors
                self._cum.pop(addr, None)
                if self.slomon is not None:
                    self.slomon.forget(addr)
            return
        data = data or {}
        overshoot = False
        if self.slomon is not None:
            buckets = data.get("ttft_hist_buckets") or {}
            if isinstance(buckets, dict) and buckets:
                self.slomon.observe(addr, buckets, ts)
                self._cum[addr] = dict(buckets)
                self.slomon.observe(SLOMonitor.FLEET_KEY,
                                    sum_buckets(self._cum.values()), ts)
            overshoot = self.slomon.sustained(addr)
        h.note_success(replica_id=str(data.get("replica_id", "") or ""),
                       slo_overshoot=overshoot)
        if bool(data.get("draining", False)) != h.draining:
            # a replica may announce its own drain on /state (the
            # control-plane overlay, ROADMAP item 2)
            h.set_draining(bool(data.get("draining", False)))
        if data:
            self.last_state[addr] = data

    def mark_draining(self, addr: str, on: bool = True) -> None:
        self.health.setdefault(addr, ReplicaHealth()).set_draining(on)

    def mark_breaker(self, addr: str, opened: bool,
                     failures: int = 0) -> None:
        """Circuit-breaker transition feed (ISSUE 14 unification)."""
        self.health.setdefault(addr, ReplicaHealth()).note_breaker(
            opened, failures)

    def forget(self, addr: str) -> None:
        """Drop a retired replica entirely (controller scale-in): its
        health machine, cached telemetry, and SLO windows — the replica
        is gone on purpose, not flapping."""
        self.health.pop(addr, None)
        self.last_state.pop(addr, None)
        self._cum.pop(addr, None)
        if self.slomon is not None:
            self.slomon.forget(addr)

    # -- read side --------------------------------------------------------
    def health_of(self, addr: str) -> str:
        h = self.health.get(addr)
        return h.state if h is not None else UNKNOWN

    def rollup(self, picker_state: dict[str, Any]) -> dict[str, Any]:
        """Fleet rollups over the replicas this aggregator has seen.
        Keys track ``FLEET_GAUGES`` (obs/metrics.py) — the drift smoke
        asserts the two sides agree, and the ``gauge-drift`` lint pass
        checks every FLEET_GAUGES key against this dict's literal keys
        at analysis time (make lint), so keep the return a literal."""
        counts = {UP: 0, DEGRADED: 0, DRAINING: 0, DOWN: 0, UNKNOWN: 0}
        for addr in picker_state:
            counts[self.health_of(addr)] += 1
        serving = [addr for addr in picker_state
                   if self.health_of(addr) in (UP, DEGRADED, DRAINING)]
        occs = []
        slots_total = slots_free = queued = 0
        hbm_worst = 0.0
        for addr in serving:
            st = picker_state[addr]
            occs.append(float(st.worst_kv_occupancy()
                              if hasattr(st, "worst_kv_occupancy")
                              else getattr(st, "kv_occupancy", 0.0)))
            slots_total += int(getattr(st, "max_slots", 0))
            slots_free += max(0, int(getattr(st, "max_slots", 0))
                              - int(getattr(st, "active_slots", 0)))
            queued += int(getattr(st, "queued", 0))
            hbm_worst = max(hbm_worst,
                            float(st.worst_hbm_frac()
                                  if hasattr(st, "worst_hbm_frac")
                                  else 0.0))

        def csum(key: str) -> int:
            return sum(int(d.get(key, 0) or 0)
                       for d in self.last_state.values())

        adapters: set[str] = set()
        for d in self.last_state.values():
            adapters.update(str(a) for a in
                            (d.get("adapters_resident") or ()))
        slo = (self.slomon.snapshot(SLOMonitor.FLEET_KEY)
               if self.slomon is not None else {})
        return {
            "replicas_total": len(picker_state),
            "replicas_up": counts[UP],
            "replicas_degraded": counts[DEGRADED],
            "replicas_draining": counts[DRAINING],
            "replicas_down": counts[DOWN],
            "slots_total": slots_total,
            "slots_free": slots_free,
            "queued_total": queued,
            "kv_occupancy_worst": round(max(occs, default=0.0), 4),
            "kv_occupancy_mean": round(
                sum(occs) / len(occs), 4) if occs else 0.0,
            "device_memory_frac_worst": round(hbm_worst, 4),
            "kv_spills_total": csum("kv_spills"),
            "kv_revives_total": csum("kv_revives"),
            "kv_fetch_pages_in_total": csum("kv_fetch_pages_in"),
            "kv_fetch_pages_out_total": csum("kv_fetch_pages_out"),
            "migrations_in_total": csum("migrations_in"),
            "migrations_out_total": csum("migrations_out"),
            "adapters_resident": len(adapters),
            "slo_goodput": slo.get("goodput", -1.0),
            "slo_burn_rate": slo.get("burn_rate", -1.0),
            "slo_overshoot_sustained": int(
                bool(slo.get("sustained_overshoot", False))),
        }

    def snapshot(self, picker_state: dict[str, Any]) -> dict[str, Any]:
        """The per-backend half of ``GET /fleet/state``: every replica
        with its health, staleness stamps, key gauges, and burn-rate
        view, plus the fleet rollup and the fleet-wide SLO window."""
        now = time.monotonic()
        replicas: dict[str, Any] = {}
        for addr, st in picker_state.items():
            h = self.health.setdefault(addr, ReplicaHealth())
            last = self.last_state.get(addr, {})
            ok_ts = float(getattr(st, "last_poll_ok_ts", 0.0) or 0.0)
            entry = {
                "health": h.to_dict(),
                "healthy": bool(getattr(st, "healthy", False)),
                # staleness stamp: seconds since the last GOOD poll
                # (-1 = never polled successfully) — consumers must
                # treat stale numbers as history, not current truth
                "staleness_s": (round(max(0.0, now - ok_ts), 3)
                                if ok_ts else -1.0),
                "poll_failures": int(
                    getattr(st, "poll_failures", 0) or 0),
                "replica_id": str(getattr(st, "replica_id", "") or ""),
                "uptime_s": float(getattr(st, "uptime_s", 0.0) or 0.0),
                "model": str(getattr(st, "model", "") or ""),
                "queued": int(getattr(st, "queued", 0)),
                "active_slots": int(getattr(st, "active_slots", 0)),
                "max_slots": int(getattr(st, "max_slots", 0)),
                "queue_wait_ms": float(
                    getattr(st, "queue_wait_ms", 0.0)),
                "kv_occupancy": float(
                    st.worst_kv_occupancy()
                    if hasattr(st, "worst_kv_occupancy")
                    else getattr(st, "kv_occupancy", 0.0)),
                "device_memory_frac_worst": float(
                    st.worst_hbm_frac()
                    if hasattr(st, "worst_hbm_frac") else 0.0),
                "migratable_slots": int(
                    getattr(st, "migratable_slots", 0)),
                # priority-tiered serving (ISSUE 19): the offline
                # class's per-replica footprint — fleetwatch's batch
                # columns and the controller's retire-drain read these
                "batch_queued": int(getattr(st, "batch_queued", 0)),
                "batch_active": int(getattr(st, "batch_active", 0)),
                "batch_preemptions": int(
                    getattr(st, "batch_preemptions", 0)),
                "adapters_resident": sorted(
                    getattr(st, "adapters_resident", ()) or ()),
                "kv_spills": int(last.get("kv_spills", 0) or 0),
                "kv_fetch_pages_in": int(
                    last.get("kv_fetch_pages_in", 0) or 0),
                "kv_fetch_pages_out": int(
                    last.get("kv_fetch_pages_out", 0) or 0),
                "migrations_in": int(last.get("migrations_in", 0) or 0),
                "migrations_out": int(
                    last.get("migrations_out", 0) or 0),
            }
            if self.slomon is not None:
                entry["slo"] = self.slomon.snapshot(addr)
            replicas[addr] = entry
        out: dict[str, Any] = {
            "replicas": replicas,
            "rollup": self.rollup(picker_state),
        }
        if self.slomon is not None:
            out["slo"] = self.slomon.snapshot(SLOMonitor.FLEET_KEY)
        return out


def merge_rollups(rollups: list[dict]) -> dict[str, Any]:
    """Cross-backend fleet rollup for the top-level ``fleet`` block:
    counters and counts sum, ``*_worst`` take the max, means weight by
    replica count, the SLO fields follow the worst-burning backend."""
    if not rollups:
        return {}
    if len(rollups) == 1:
        return dict(rollups[0])
    out: dict[str, Any] = {}
    n_total = sum(r.get("replicas_total", 0) for r in rollups) or 1
    for key in rollups[0]:
        vals = [r.get(key, 0) for r in rollups]
        if key.endswith("_worst"):
            out[key] = max(vals)
        elif key == "kv_occupancy_mean":
            out[key] = round(sum(
                r.get(key, 0.0) * r.get("replicas_total", 0)
                for r in rollups) / n_total, 4)
        elif key in ("slo_goodput", "slo_burn_rate"):
            # the fleet is as healthy as its worst-burning backend
            burns = [(r.get("slo_burn_rate", -1.0), r) for r in rollups]
            worst = max(burns, key=lambda t: t[0])[1]
            out[key] = worst.get(key, -1.0)
        elif key == "slo_overshoot_sustained":
            out[key] = int(any(r.get(key, 0) for r in rollups))
        else:
            out[key] = sum(vals)
    return out


class DecisionRing:
    """Bounded ring of routing decisions — the gateway's answer to the
    tpuserve flight recorder. One entry per pick (routed OR shed),
    carrying the full ``pick(explain=)`` dict; the upstream request id
    (``x-aigw-request-id``) is attached once the replica responds, and
    a migration stamps the entry mid-stream — entries are mutable dicts
    precisely so the decision's afterlife lands on the decision."""

    def __init__(self, capacity: int = 512):
        self.capacity = max(1, capacity)
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._recorded = 0

    def record(self, **fields: Any) -> dict[str, Any]:
        entry = {"ts": round(time.time(), 3), **fields}
        self._ring.append(entry)
        self._recorded += 1
        return entry

    def snapshot(self, rid: str = "", limit: int = 100) -> list[dict]:
        """Newest-first decisions; ``rid`` filters by the upstream
        request id (the flight-recorder join key) or the client's
        x-request-id."""
        out = []
        for e in reversed(self._ring):
            if rid and rid not in (e.get("upstream_request_id", ""),
                                   e.get("request_id", "")):
                continue
            out.append(e)
            if len(out) >= limit:
                break
        return out

    @property
    def recorded(self) -> int:
        return self._recorded

    def __len__(self) -> int:
        return len(self._ring)


#: sample-line shape: name, optional {labels}, rest (value + optional
#: OpenMetrics exemplar suffix — preserved verbatim)
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})?( .*)$')


def relabel_exposition(text: str, replica: str,
                       seen_families: set | None = None,
                       keep_prefixes: tuple = ("tpuserve_",)) -> str:
    """Rewrite one replica's /metrics exposition for fleet federation:
    keep only families matching ``keep_prefixes`` and inject a
    ``replica="addr"`` label into every sample (first position, ahead
    of existing labels like ``device=`` or ``le=``). ``seen_families``
    dedupes ``# TYPE``/``# HELP`` header lines across replicas so the
    concatenated scrape stays a valid exposition."""
    out: list[str] = []
    esc = replica.replace("\\", r"\\").replace('"', r'\"')
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 3 and parts[1] in ("TYPE", "HELP"):
                fam = parts[2]
                if not fam.startswith(keep_prefixes):
                    continue
                if seen_families is not None:
                    if fam in seen_families:
                        continue
                    seen_families.add(fam)
                out.append(line)
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, labels, rest = m.groups()
        if not name.startswith(keep_prefixes):
            continue
        merged = f'replica="{esc}"' + (f",{labels}" if labels else "")
        out.append(f"{name}{{{merged}}}{rest}")
    return "\n".join(out) + ("\n" if out else "")
