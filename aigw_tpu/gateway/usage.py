"""Engine-truth usage metering ledger (ISSUE 20).

PR 20's tentpole: cost used to be computed from response-mined
``TokenUsage`` and immediately discarded into rate-limit metadata. This
module keeps it — the gateway folds every finished request's
``MeterRecord`` (the engine-emitted truth riding ``usage.aigw_meter``)
into windowed per-tenant/per-model ledgers, with

- **crash-safe JSONL journaling**: one flushed line per record; replay
  reconstructs the exact totals and tolerates a torn final line (the
  only thing a crash mid-append can produce);
- **exact reconciliation by construction**: token counts are ints, and
  the page·byte·second residency floats are accumulated in integer
  MICRO units (the MeterRecord rounds them to 6 decimals, so micros
  are exact) — sums are associative/commutative and the ledger's
  totals equal the engine's ``meter_*`` /state counters token for
  token regardless of arrival order;
- **slomon-style budget burn**: per tenant, ``burn = window_cost /
  budget`` over the ledger's closed windows, with a K-consecutive-
  windows sustained flag (idle gaps clear the streak, exactly like the
  SLO monitor — sustained must mean sustained SPEND, not stale
  history).

The ``snapshot()`` literal dict is the ``USAGE_GAUGES`` twin
(obs/metrics.py) — the ``gauge-drift`` lint pass checks the two
statically, same contract as /state ↔ ENGINE_GAUGES.

Pure bookkeeping plus an append-only file handle; no event-loop I/O
(callers journal from the request path — a single ``write`` + ``flush``
of one short line).
"""

from __future__ import annotations

import collections
import json
import time
from typing import Any, TextIO

from aigw_tpu.gateway.costs import TokenUsage

#: integer fields of one ledger window / journal line (summed exactly)
INT_FIELDS: tuple[str, ...] = (
    "records",
    "prefill_tokens",
    "prefill_padded_tokens",
    "prefix_reused_tokens",
    "decode_tokens",
    "spec_drafted",
    "spec_accepted",
    "cost",
)

#: residency fields: journal lines carry the 6-decimal floats the
#: MeterRecord rounds to; windows accumulate them as exact micro ints
#: (``*_u`` keys) so merge order can never change a total
FLOAT_FIELDS: tuple[str, ...] = ("hbm_page_byte_s", "host_page_byte_s")

_MICRO = 1_000_000


def _micros(v: Any) -> int:
    try:
        return int(round(float(v) * _MICRO))
    except (TypeError, ValueError):
        return 0


def _unmicros(u: int) -> float:
    return round(u / _MICRO, 6)


def zero_window(t0: float = 0.0, t1: float = 0.0) -> dict:
    """An empty ledger window — the merge identity."""
    w: dict[str, Any] = {"t0": round(t0, 3), "t1": round(t1, 3)}
    for f in INT_FIELDS:
        w[f] = 0
    for f in FLOAT_FIELDS:
        w[f + "_u"] = 0
    return w


def merge_windows(a: dict, b: dict) -> dict:
    """Field-wise sum of two windows; the time span is the union.

    Associative AND commutative (the property test asserts both): every
    summed field is an int — token counts natively, residency in micro
    page·byte·seconds — so float rounding can never make grouping
    matter."""
    t0s = [t for t in (a.get("t0", 0.0), b.get("t0", 0.0)) if t]
    out: dict[str, Any] = {
        "t0": min(t0s) if t0s else 0.0,
        "t1": max(a.get("t1", 0.0), b.get("t1", 0.0)),
    }
    for f in INT_FIELDS:
        out[f] = int(a.get(f, 0)) + int(b.get(f, 0))
    for f in FLOAT_FIELDS:
        k = f + "_u"
        out[k] = int(a.get(k, 0)) + int(b.get(k, 0))
    return out


def window_view(w: dict) -> dict:
    """External view of a window: micro ints rendered back to the
    6-decimal floats the MeterRecord speaks."""
    out = {k: v for k, v in w.items() if not k.endswith("_u")}
    for f in FLOAT_FIELDS:
        out[f] = _unmicros(int(w.get(f + "_u", 0)))
    return out


def line_from(tenant: str, model: str, usage: TokenUsage, cost: int = 0,
              ts: float | None = None) -> dict:
    """One journal line from a finished request.

    With an engine MeterRecord on the usage, every field is engine
    truth; provider backends (no meter) degrade to the mined token
    counts so external traffic still lands in the ledger."""
    m = dict(usage.meter)
    if m:
        prefill = int(m.get("prefill_real", 0) or 0)
        decode = int(m.get("decode_tokens", 0) or 0)
    else:
        prefill = usage.input_tokens
        decode = usage.output_tokens
    return {
        "ts": round(time.time() if ts is None else ts, 3),
        "tenant": tenant,
        "model": model,
        "records": 1,
        "prefill_tokens": prefill,
        "prefill_padded_tokens": int(m.get("prefill_padded", 0) or 0),
        "prefix_reused_tokens": int(m.get("prefix_reused", 0) or 0),
        "decode_tokens": decode,
        "spec_drafted": int(m.get("spec_drafted", 0) or 0),
        "spec_accepted": int(m.get("spec_accepted", 0) or 0),
        "hbm_page_byte_s": round(float(m.get("hbm_page_byte_s", 0.0) or 0.0), 6),
        "host_page_byte_s": round(float(m.get("host_page_byte_s", 0.0) or 0.0), 6),
        "cost": int(cost),
    }


def reconciles(usage: TokenUsage) -> bool:
    """Meter ↔ mined-usage agreement for one response.

    The engine's ``decode_tokens`` counts every token it GENERATED,
    including a consumed stop token the stream never emitted — so the
    mined ``output_tokens`` must sit within one stop token per stream
    segment of the engine count. Responses without a meter (provider
    backends) vacuously reconcile."""
    m = dict(usage.meter)
    if not m:
        return True
    decode = int(m.get("decode_tokens", 0) or 0)
    slack = max(1, int(m.get("segments", 1) or 1))
    return usage.output_tokens <= decode <= usage.output_tokens + slack


class _BurnState:
    __slots__ = ("streak", "burn", "over")

    def __init__(self) -> None:
        self.streak = 0
        self.burn = 0.0
        self.over = False


class UsageLedger:
    """Windowed per-tenant/per-model usage + cost ledger.

    Records fold into the open window of their ``(tenant, model)`` key
    (window index = ``ts // window_s``); a record landing in a later
    window closes the stale one into a bounded ring. A parallel
    per-tenant window stream drives the budget burn machine."""

    def __init__(self, path: str | None = None, *,
                 window_s: float = 60.0, retain_windows: int = 64,
                 budgets: dict[str, float] | None = None,
                 burn_windows: int = 3):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0 (got {window_s})")
        self.window_s = float(window_s)
        self.burn_windows = max(1, int(burn_windows))
        self.budgets: dict[str, float] = {
            str(k): float(v) for k, v in (budgets or {}).items()}
        self.path = path or None
        self._fh: TextIO | None = None
        #: (tenant, model) → (window index, open window)
        self._open: dict[tuple[str, str], tuple[int, dict]] = {}
        #: closed windows, oldest → newest, each stamped tenant/model
        self._closed: collections.deque = collections.deque(
            maxlen=max(1, int(retain_windows)))
        #: per-tenant cross-model window stream for the burn machine
        self._tenant_open: dict[str, tuple[int, dict]] = {}
        self._burn: dict[str, _BurnState] = {}
        self._totals = zero_window()
        self._tenants: set[str] = set()
        self.windows_closed = 0
        self.journal_lines = 0
        self.reconcile_mismatches = 0

    # -- journal ----------------------------------------------------------
    @classmethod
    def replay(cls, path: str, **kwargs: Any) -> "UsageLedger":
        """Rebuild a ledger from its JSONL journal, then keep appending
        to the same file. A torn final line (crash mid-append) stops the
        replay at the last complete record — exactly what was durable."""
        led = cls(path=None, **kwargs)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                for raw in fh:
                    raw = raw.strip()
                    if not raw:
                        continue
                    try:
                        line = json.loads(raw)
                    except ValueError:
                        break  # torn tail — everything before it counted
                    led._fold(line)
                    led.journal_lines += 1
        except OSError:
            pass  # no journal yet — fresh ledger
        led.path = path
        return led

    def _append(self, line: dict) -> None:
        if self.path is None:
            return
        if self._fh is None:
            self._fh = open(self.path, "a", encoding="utf-8")
        self._fh.write(json.dumps(line, sort_keys=True) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # -- write side -------------------------------------------------------
    def record(self, tenant: str, model: str, usage: TokenUsage,
               cost: int = 0, ts: float | None = None) -> dict:
        """Journal + fold one finished request. Returns the line."""
        line = line_from(tenant, model, usage, cost, ts)
        self._append(line)
        self.journal_lines += 1
        if not reconciles(usage):
            self.reconcile_mismatches += 1
        self._fold(line)
        return line

    def _fold(self, line: dict) -> None:
        ts = float(line.get("ts", 0.0))
        tenant = str(line.get("tenant", ""))
        model = str(line.get("model", ""))
        wi = int(ts // self.window_s)
        w = zero_window(ts, ts)
        for f in INT_FIELDS:
            w[f] = int(line.get(f, 0) or 0)
        for f in FLOAT_FIELDS:
            w[f + "_u"] = _micros(line.get(f, 0.0))
        self._tenants.add(tenant)
        self._totals = merge_windows(self._totals, w)

        key = (tenant, model)
        cur = self._open.get(key)
        if cur is not None and cur[0] != wi:
            closed = dict(cur[1])
            closed.update(tenant=tenant, model=model)
            self._closed.append(closed)
            self.windows_closed += 1
            cur = None
        self._open[key] = (
            wi, w if cur is None else merge_windows(cur[1], w))

        tcur = self._tenant_open.get(tenant)
        if tcur is not None and tcur[0] != wi:
            self._close_tenant_window(tenant, tcur[1], wi - tcur[0])
            tcur = None
        self._tenant_open[tenant] = (
            wi, w if tcur is None else merge_windows(tcur[1], w))

    def _close_tenant_window(self, tenant: str, w: dict,
                             gap: int) -> None:
        budget = self.budgets.get(tenant, 0.0)
        if budget <= 0:
            return
        st = self._burn.setdefault(tenant, _BurnState())
        if gap > 1:
            # idle windows between the closed one and now: no spend is
            # not an overshoot — the streak restarts from this window
            st.streak = 0
        burn = w["cost"] / budget
        st.burn = round(burn, 4)
        st.over = burn > 1.0
        st.streak = st.streak + 1 if st.over else 0

    # -- read side --------------------------------------------------------
    def sustained(self, tenant: str) -> bool:
        """K consecutive closed windows over budget — the alert flag."""
        st = self._burn.get(tenant)
        return st is not None and st.streak >= self.burn_windows

    def burn(self, tenant: str) -> dict:
        st = self._burn.get(tenant)
        return {
            "budget": self.budgets.get(tenant, 0.0),
            "burn_rate": st.burn if st is not None else -1.0,
            "over_budget": st.over if st is not None else False,
            "over_streak": st.streak if st is not None else 0,
            "sustained": self.sustained(tenant),
        }

    def totals(self) -> dict:
        """Cumulative ledger totals (the engine-counter reconciliation
        surface: these equal the replica ``meter_*`` /state counters
        summed over the fleet, token for token)."""
        return window_view(self._totals)

    def query(self, since: float = 0.0, tenant: str = "",
              model: str = "") -> dict:
        """The ``GET /usage`` payload: filtered windows (closed ring +
        open), per-tenant aggregates with budget burn, and the grand
        totals."""
        windows: list[dict] = []
        for w in self._closed:
            if tenant and w.get("tenant") != tenant:
                continue
            if model and w.get("model") != model:
                continue
            if w.get("t1", 0.0) < since:
                continue
            windows.append(window_view(w))
        for (t, mdl), (_wi, w) in sorted(self._open.items()):
            if tenant and t != tenant:
                continue
            if model and mdl != model:
                continue
            if w.get("t1", 0.0) < since:
                continue
            v = window_view(w)
            v.update(tenant=t, model=mdl, open=True)
            windows.append(v)

        tenants: dict[str, dict] = {}
        for (t, mdl), (_wi, w) in self._open.items():
            agg = tenants.setdefault(t, zero_window())
            tenants[t] = merge_windows(agg, w)
        for w in self._closed:
            t = str(w.get("tenant", ""))
            agg = tenants.setdefault(t, zero_window())
            tenants[t] = merge_windows(agg, w)
        per_tenant = {}
        for t in sorted(tenants):
            if tenant and t != tenant:
                continue
            v = window_view(tenants[t])
            v["budget"] = self.burn(t)
            per_tenant[t] = v

        return {
            "window_s": self.window_s,
            "retained_windows": len(self._closed),
            "windows": windows,
            "tenants": per_tenant,
            "totals": self.totals(),
        }

    def snapshot(self) -> dict:
        """The ``USAGE_GAUGES`` twin — literal keys, drift-checked by
        the ``gauge-drift`` lint pass against obs/metrics.py."""
        t = self._totals
        return {
            "records_total": t["records"],
            "prefill_tokens_total": t["prefill_tokens"],
            "prefill_padded_tokens_total": t["prefill_padded_tokens"],
            "prefix_reused_tokens_total": t["prefix_reused_tokens"],
            "decode_tokens_total": t["decode_tokens"],
            "spec_drafted_total": t["spec_drafted"],
            "spec_accepted_total": t["spec_accepted"],
            "hbm_page_byte_s_total": _unmicros(t["hbm_page_byte_s_u"]),
            "host_page_byte_s_total": _unmicros(t["host_page_byte_s_u"]),
            "cost_total": t["cost"],
            "tenants": len(self._tenants),
            "windows_closed_total": self.windows_closed,
            "journal_lines_total": self.journal_lines,
            "reconcile_mismatches_total": self.reconcile_mismatches,
            "over_budget_tenants": sum(
                1 for st in self._burn.values() if st.over),
            "burn_sustained_tenants": sum(
                1 for t_ in self._burn if self.sustained(t_)),
        }
