"""Token-budget quotas and rate limiting.

Equivalent of the reference's QuotaPolicy CRD + Envoy ratelimit service leg
(api/v1alpha1/quota_policy.go:26-165, internal/ratelimit/translator —
descriptor trees keyed backend/model/client selectors) collapsed into one
in-process engine, keeping the reference's semantics:

- **Enforcement at request time, consumption at end-of-stream**: token
  costs are only known after the response completes, so a request is
  admitted if its descriptor buckets currently have budget, and the actual
  cost is drawn down afterwards (Envoy's ``apply_on_stream_done``,
  filterconfig.go:84-87). A burst can therefore overshoot one window by
  in-flight requests — the same behavior as the reference.
- **Descriptors**: (rule, model, backend, client-key) tuples; the client
  key comes from a configurable request header.
- **Fixed windows** aligned to the unit boundary, like the Envoy ratelimit
  service's per-unit counters.
- **Shared enforcement** (the reference's dedicated ratelimit service fed
  by xDS, internal/ratelimit/runner/runner.go:36-38): when AIGW_QUOTA_DIR
  is set, counters live in flock'd files so one budget is enforced across
  SO_REUSEPORT workers — and across replicas given a shared directory.
  The multi-worker CLI sets this automatically.
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any

from aigw_tpu.config.model import ConfigError


@dataclass(frozen=True)
class QuotaRule:
    """One quota: budget of a cost metric per time window, optionally
    scoped to model/backend and keyed by a client header."""

    name: str
    metadata_key: str  # which LLMRequestCost metric to draw down
    limit: int
    window_seconds: float = 60.0
    model: str = ""  # "" = any
    backend: str = ""  # "" = any
    client_key_header: str = ""  # "" = one global bucket
    # QuotaPolicy "Shared" mode (quotapolicies CRD): rules carrying the
    # same non-empty group are charged together but the request is
    # ALLOWED if at least one of them still has headroom. "" = an
    # independent cap (deny when exhausted), the native default.
    shared_group: str = ""

    @staticmethod
    def parse(value: dict[str, Any]) -> "QuotaRule":
        try:
            rule = QuotaRule(
                name=value["name"],
                metadata_key=value["metadata_key"],
                limit=int(value["limit"]),
                window_seconds=float(value.get("window_seconds", 60.0)),
                model=value.get("model", ""),
                backend=value.get("backend", ""),
                client_key_header=str(
                    value.get("client_key_header", "")
                ).lower(),
                shared_group=str(value.get("shared_group", "")),
            )
        except KeyError as e:
            raise ConfigError(f"quota rule missing field {e}") from None
        if rule.limit <= 0 or rule.window_seconds <= 0:
            raise ConfigError(f"quota {rule.name}: limit/window must be > 0")
        return rule


@dataclass
class _Window:
    start: float
    used: int


class FileQuotaBackend:
    """Shared quota counters: one flock'd JSON file per rule.

    The reference routes token budgets through a *shared* ratelimit
    service precisely so limits are global across Envoy replicas
    (internal/ratelimit/runner/runner.go:36-38). Here the shared store
    is the filesystem: SO_REUSEPORT workers on one host share it
    automatically, and replicas share it when pointed at a common
    directory (AIGW_QUOTA_DIR). Fixed windows are aligned to the unit
    boundary, so every process computes the same window start and the
    file needs only {start, used-per-client-key}.
    """

    def __init__(self, directory: str):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, rule_name: str) -> str:
        safe = "".join(
            c if c.isalnum() or c in "-_" else "_" for c in rule_name
        )
        # short hash of the raw name: sanitization alone would collapse
        # distinct rules ('a b' vs 'a_b') onto one file, silently merging
        # their budgets
        digest = hashlib.sha256(rule_name.encode()).hexdigest()[:8]
        path = os.path.join(self._dir, f"quota_{safe}_{digest}.json")
        # one-time migration from the pre-hash filename so live spent
        # budgets survive an upgrade (rename is atomic; losers of the
        # race see the file already gone and just use the new path)
        legacy = os.path.join(self._dir, f"quota_{safe}.json")
        if not os.path.exists(path) and os.path.exists(legacy):
            try:
                os.rename(legacy, path)
            except OSError:
                pass
        return path

    @staticmethod
    def _load(f) -> dict:
        f.seek(0)
        raw = f.read()
        empty = {"start": -1.0, "used": {}}
        if not raw:
            return empty
        try:
            state = json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError):
            return empty
        # shape-validate too: valid-JSON-wrong-shape (external edit) must
        # reset, not crash every quota-matched request forever
        if not isinstance(state, dict) or \
                not isinstance(state.get("used"), dict):
            return empty
        return state

    def get(self, rule_name: str, client_key: str,
            window_start: float) -> int:
        try:
            with open(self._path(rule_name), "r") as f:
                fcntl.flock(f, fcntl.LOCK_SH)
                state = self._load(f)
        except FileNotFoundError:
            return 0
        if state.get("start") != window_start:
            return 0
        used = state.get("used", {}).get(client_key, 0)
        return int(used) if isinstance(used, (int, float)) else 0

    def add(self, rule_name: str, client_key: str, window_start: float,
            amount: int) -> int:
        with open(self._path(rule_name), "a+") as f:
            fcntl.flock(f, fcntl.LOCK_EX)
            state = self._load(f)
            if state.get("start") != window_start:
                state = {"start": window_start, "used": {}}
            used = state["used"]
            used[client_key] = int(used.get(client_key, 0)) + int(amount)
            f.seek(0)
            f.truncate()
            json.dump(state, f)
            f.flush()
            return used[client_key]


class HTTPQuotaBackend:
    """Network quota mode: counters live behind a tiny quota service
    (`aigw quota-service`) so multi-*node* replicas with no shared
    filesystem still enforce ONE budget — the role of the reference's
    over-the-network ratelimit service fed by xDS
    (internal/ratelimit/runner/runner.go:36-38). Selected with
    AIGW_QUOTA_URL (takes precedence over AIGW_QUOTA_DIR).

    Failure semantics are Envoy's ratelimit-filter default: **fail
    open** — an unreachable quota service admits traffic (and skips the
    draw-down) rather than turning a telemetry outage into an API
    outage; every failure is logged.
    """

    def __init__(self, base_url: str, timeout: float = 3.0):
        import threading
        import urllib.parse

        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        parts = urllib.parse.urlsplit(self.base_url)
        self._https = parts.scheme == "https"
        self._netloc = parts.netloc
        self._prefix = parts.path.rstrip("/")
        # keep-alive connection per calling thread (check/consume run on
        # executor threads): per-call urlopen would cost a fresh TCP
        # connect per quota operation and pile up TIME_WAIT sockets on
        # the quota service at gateway QPS
        self._local = threading.local()

    def _conn(self):
        import http.client

        conn = getattr(self._local, "conn", None)
        if conn is None:
            cls = (http.client.HTTPSConnection if self._https
                   else http.client.HTTPConnection)
            conn = cls(self._netloc, timeout=self.timeout)
            self._local.conn = conn
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass
            self._local.conn = None

    def _request(self, method: str, rule_name: str,
                 payload: dict[str, Any]) -> int | None:
        import logging
        import urllib.parse

        path = (f"{self._prefix}/v1/quota/"
                f"{urllib.parse.quote(rule_name, safe='')}")
        body = None
        headers = {}
        if method == "GET":
            path += "?" + urllib.parse.urlencode(payload)
        else:
            body = json.dumps(payload).encode()
            headers["content-type"] = "application/json"
        # one retry on a fresh connection: a keep-alive socket the
        # service closed between calls fails the first attempt benignly
        for attempt in (0, 1):
            try:
                conn = self._conn()
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                if resp.status >= 400:
                    raise OSError(f"HTTP {resp.status}")
                return int(json.loads(data).get("used", 0))
            except Exception as e:  # noqa: BLE001 — fail open
                self._drop_conn()
                if attempt == 1:
                    logging.getLogger(__name__).warning(
                        "quota service %s %s failed (%s: %s); "
                        "failing open", method, path,
                        type(e).__name__, e)
        return None

    def get(self, rule_name: str, client_key: str,
            window_start: float) -> int:
        used = self._request("GET", rule_name, {
            "key": client_key, "start": window_start})
        return 0 if used is None else used

    def add(self, rule_name: str, client_key: str, window_start: float,
            amount: int) -> int:
        used = self._request("POST", rule_name, {
            "key": client_key, "start": window_start,
            "amount": int(amount)})
        return 0 if used is None else used


def quota_service_app(directory: str):
    """The quota service itself: an aiohttp app exposing
    FileQuotaBackend's two operations over HTTP. State stays in flock'd
    files, so the service can itself run replicated over a shared volume
    — or singly, giving budget-sharing to gateways with no shared
    filesystem at all. Run with `aigw quota-service`."""
    import asyncio as _asyncio

    from aiohttp import web

    store = FileQuotaBackend(directory)

    async def get_used(request: "web.Request") -> "web.Response":
        rule = request.match_info["rule"]
        key = request.query.get("key", "")
        try:
            start = float(request.query.get("start", "0"))
        except ValueError:
            return web.json_response({"error": "bad start"}, status=400)
        used = await _asyncio.to_thread(store.get, rule, key, start)
        return web.json_response({"used": used})

    async def add_used(request: "web.Request") -> "web.Response":
        rule = request.match_info["rule"]
        try:
            body = json.loads(await request.read())
            if not isinstance(body, dict):
                raise ValueError("body must be an object")
            key = str(body.get("key", ""))
            start = float(body["start"])
            amount = int(body["amount"])
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            return web.json_response({"error": "bad body"}, status=400)
        used = await _asyncio.to_thread(
            store.add, rule, key, start, amount)
        return web.json_response({"used": used})

    async def health(_request: "web.Request") -> "web.Response":
        return web.json_response({"status": "ok"})

    app = web.Application()
    app.router.add_get("/v1/quota/{rule}", get_used)
    app.router.add_post("/v1/quota/{rule}", add_used)
    app.router.add_get("/health", health)
    return app


class RateLimiter:
    """In-process descriptor-keyed fixed-window limiter."""

    _SWEEP_EVERY = 1024  # bucket insertions between stale-window sweeps

    def __init__(self, rules: list[QuotaRule],
                 backend: FileQuotaBackend | None = None):
        self.rules = rules
        self.backend = backend  # shared store: workers/replicas see one budget
        self._windows: dict[tuple[str, str], _Window] = {}
        self._inserts = 0
        self._window_by_rule = {r.name: r.window_seconds for r in rules}

    def adopt(self, previous: "RateLimiter | None") -> "RateLimiter":
        """Carry in-flight window counters across a config hot reload so
        a reload never refills exhausted budgets (rules are matched by
        name+shape; changed rules start fresh)."""
        if previous is None:
            return self
        if self.backend is not None:
            # shared counters live in the store, not this object; a hot
            # reload keeps them by construction
            return self
        prev_rules = {r.name: r for r in previous.rules}
        keep = {
            r.name for r in self.rules if prev_rules.get(r.name) == r
        }
        for key, window in previous._windows.items():
            if key[0] in keep:
                self._windows[key] = window
        return self

    @staticmethod
    def from_config_value(value: Any) -> "RateLimiter":
        rules = [QuotaRule.parse(v) for v in (value or ())]
        backend = None
        quota_url = os.environ.get("AIGW_QUOTA_URL")
        quota_dir = os.environ.get("AIGW_QUOTA_DIR")
        if rules and quota_url:
            # network mode wins: one budget across nodes with no shared
            # filesystem (the reference's ratelimit-service topology)
            backend = HTTPQuotaBackend(quota_url)
        elif rules and quota_dir:
            backend = FileQuotaBackend(quota_dir)
        return RateLimiter(rules, backend=backend)

    def _matching(self, model: str, backend: str) -> list[QuotaRule]:
        return [
            r
            for r in self.rules
            if (not r.model or r.model == model)
            and (not r.backend or r.backend == backend)
        ]

    def _bucket(self, rule: QuotaRule, client_key: str,
                now: float) -> _Window:
        key = (rule.name, client_key)
        w = self._windows.get(key)
        window_start = now - (now % rule.window_seconds)
        if w is None or w.start != window_start:
            w = _Window(start=window_start, used=0)
            self._windows[key] = w
            self._inserts += 1
            if self._inserts % self._SWEEP_EVERY == 0:
                self._sweep(now)
        return w

    def _sweep(self, now: float) -> None:
        """Evict expired windows so client-controlled keys can't grow
        memory without bound."""
        dead = [
            k
            for k, w in self._windows.items()
            if now - w.start > 2 * self._window_by_rule.get(k[0], 3600.0)
        ]
        for k in dead:
            del self._windows[k]

    def check(
        self,
        model: str,
        backend: str,
        headers: dict[str, str],
        now: float | None = None,
    ) -> tuple[bool, "QuotaRule | None"]:
        """(True, None) if the request may proceed; otherwise
        (False, the violated rule). Independent rules deny when
        exhausted; same-shared_group rules deny only when EVERY member
        is exhausted (QuotaPolicy Shared mode)."""
        now = time.time() if now is None else now
        group_ok: dict[str, bool] = {}
        group_violated: dict[str, QuotaRule] = {}
        for rule in self._matching(model, backend):
            client_key = headers.get(rule.client_key_header, "") \
                if rule.client_key_header else ""
            if self.backend is not None:
                start = now - (now % rule.window_seconds)
                used = self.backend.get(rule.name, client_key, start)
            else:
                used = self._bucket(rule, client_key, now).used
            ok = used < rule.limit
            if rule.shared_group:
                g = rule.shared_group
                group_ok[g] = group_ok.get(g, False) or ok
                if not ok:
                    group_violated.setdefault(g, rule)
            elif not ok:
                return False, rule
        for g, any_ok in group_ok.items():
            if not any_ok:
                return False, group_violated[g]
        return True, None

    def consume(
        self,
        costs: dict[str, int],
        model: str,
        backend: str,
        headers: dict[str, str],
        now: float | None = None,
    ) -> None:
        """Draw down matched buckets at end-of-stream."""
        now = time.time() if now is None else now
        for rule in self._matching(model, backend):
            cost = costs.get(rule.metadata_key)
            if not cost:
                continue
            client_key = headers.get(rule.client_key_header, "") \
                if rule.client_key_header else ""
            if self.backend is not None:
                start = now - (now % rule.window_seconds)
                self.backend.add(rule.name, client_key, start, cost)
            else:
                self._bucket(rule, client_key, now).used += cost

    def remaining(
        self, rule_name: str, client_key: str = "", now: float | None = None
    ) -> int | None:
        for rule in self.rules:
            if rule.name == rule_name:
                now = time.time() if now is None else now
                if self.backend is not None:
                    start = now - (now % rule.window_seconds)
                    used = self.backend.get(rule.name, client_key, start)
                else:
                    used = self._bucket(rule, client_key, now).used
                return max(0, rule.limit - used)
        return None
