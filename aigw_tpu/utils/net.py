"""Small socket/transport helpers shared by the serving surfaces."""

from __future__ import annotations

import socket


def set_tcp_nodelay(transport) -> None:
    """Disable Nagle on a (possibly wrapped) asyncio transport's socket.

    The first-token fast path writes two small SSE frames back to back
    (role frame, then the first content delta); with Nagle enabled the
    second frame can sit in the kernel until the first is ACKed — pure
    added TTFT. aiohttp enables TCP_NODELAY on most server transports
    already; this makes the latency-critical streams explicit and
    covers transports (SSL wrappers, proxies) where it may not hold.
    No-ops on non-TCP transports (unix sockets, tests' mocks).
    """
    if transport is None:
        return
    sock = transport.get_extra_info("socket")
    if sock is None:
        return
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except (OSError, ValueError):
        pass  # non-TCP socket family / already closed
