"""shard_map compatibility across the ``jax.lax.pvary`` deprecation arc.

Ring attention and the pipeline stage loop carry accumulators through a
``lax.scan`` whose body runs collectives (``ppermute``) over a manual
mesh axis. Newer shard_map implementations statically track which
values vary over manual axes and reject a replicated-typed carry that a
collective made varying; the old workaround was tagging the initial
accumulators with ``jax.lax.pvary`` — an API that does not exist on
older jax (0.4.x), moved between releases, and is deprecated in favour
of opting out of the check itself. This module is the single resolution
point: ``shard_map_untyped_carry`` disables the varying-manual-axes
validation via whichever keyword the installed shard_map understands
(``check_vma`` on the stabilized ``jax.shard_map``, ``check_rep`` on
the experimental one), so kernel code carries no version shims and no
pvary calls. Numerics are unaffected — only the static check is off.
"""

from __future__ import annotations

import inspect

import jax

_shard_map_impl = getattr(jax, "shard_map", None)
if _shard_map_impl is None:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map_impl

try:
    _PARAMS = set(inspect.signature(_shard_map_impl).parameters)
except (TypeError, ValueError):  # pragma: no cover - exotic builds
    _PARAMS = set()

if "check_vma" in _PARAMS:
    _CHECK_OFF = {"check_vma": False}
elif "check_rep" in _PARAMS:
    _CHECK_OFF = {"check_rep": False}
else:  # pragma: no cover - future signature change
    _CHECK_OFF = {}


def shard_map_untyped_carry(f, *, mesh, in_specs, out_specs):
    """``shard_map`` with the varying-manual-axes check disabled — the
    supported replacement for pvary-tagging scan carries (see module
    docstring)."""
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **_CHECK_OFF,
    )
