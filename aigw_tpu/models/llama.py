"""Llama-family transformer as a pure functional JAX program.

TPU-first design decisions (not a port of any torch implementation):

- **bfloat16 everywhere** except RMSNorm accumulation and attention
  softmax, which run in float32 — keeps the MXU fed while preserving
  numerics (pallas_guide.md tiling: bf16 tiles are (16, 128)).
- **Static shapes**: prefill is bucketed by padded sequence length, decode
  is a fixed [max_batch, 1] step — each shape compiles exactly once.
- **Paged KV cache**: the cache is a flat page pool
  ``[L, 2, n_pages * page_size, n_kv_heads, head_dim]``; sequences own
  pages via an int32 page table. Flattening pages makes cache writes one
  scatter and cache reads one gather — both XLA-native ops that fuse well,
  and the same layout the Pallas paged-attention kernel consumes
  (PAPERS.md: Ragged Paged Attention for TPU).
- **GQA**: K/V heads are kept un-repeated in the cache (HBM bandwidth is
  the bottleneck); Q heads are grouped over KV heads inside attention.

Weight layout is a flat dict pytree so `jax.sharding` partition specs can
be assigned per-leaf by name (aigw_tpu/parallel/sharding.py).
"""

from __future__ import annotations

import dataclasses
import math
import os
from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from aigw_tpu.models import kvq
from aigw_tpu.models.lora import lora_delta


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    # QKV projection bias (the Qwen2 family uses it; Llama doesn't)
    attn_bias: bool = False

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


# Published Llama-3 architecture shapes (public model cards).
LLAMA3_8B = LlamaConfig()
LLAMA3_70B = LlamaConfig(
    dim=8192, n_layers=80, n_heads=64, n_kv_heads=8, ffn_dim=28672
)
# Qwen2 family: Llama skeleton + QKV bias (+ tied embeddings on small
# sizes). Published architecture shapes.
QWEN2_7B = LlamaConfig(
    vocab_size=152064, dim=3584, n_layers=28, n_heads=28, n_kv_heads=4,
    ffn_dim=18944, rope_theta=1e6, max_seq_len=32768, attn_bias=True,
)
QWEN2_05B = LlamaConfig(
    vocab_size=151936, dim=896, n_layers=24, n_heads=14, n_kv_heads=2,
    ffn_dim=4864, rope_theta=1e6, max_seq_len=32768, attn_bias=True,
    tie_embeddings=True,
)

#: Tiny config for tests / CPU fake-chip mode (reference's testupstream role)
TINY = LlamaConfig(
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    ffn_dim=128, max_seq_len=512, rope_theta=10000.0,
)


def init_params(
    key: jax.Array, cfg: LlamaConfig, dtype: Any = jnp.bfloat16
) -> dict[str, jax.Array]:
    """Random-init weights (testing / tiny-random serving)."""
    keys = iter(jax.random.split(key, 4 + cfg.n_layers * 9))

    def dense(shape, scale=None):
        scale = scale or 1.0 / math.sqrt(shape[0])
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(
            dtype
        )

    p: dict[str, jax.Array] = {
        "embed": dense((cfg.vocab_size, cfg.dim), scale=0.02),
        "norm_f": jnp.ones((cfg.dim,), dtype),
    }
    if not cfg.tie_embeddings:
        p["lm_head"] = dense((cfg.dim, cfg.vocab_size))
    hd = cfg.head_dim
    for i in range(cfg.n_layers):
        p[f"l{i}.attn_norm"] = jnp.ones((cfg.dim,), dtype)
        p[f"l{i}.wq"] = dense((cfg.dim, cfg.n_heads * hd))
        p[f"l{i}.wk"] = dense((cfg.dim, cfg.n_kv_heads * hd))
        p[f"l{i}.wv"] = dense((cfg.dim, cfg.n_kv_heads * hd))
        if cfg.attn_bias:
            p[f"l{i}.bq"] = jnp.zeros((cfg.n_heads * hd,), dtype)
            p[f"l{i}.bk"] = jnp.zeros((cfg.n_kv_heads * hd,), dtype)
            p[f"l{i}.bv"] = jnp.zeros((cfg.n_kv_heads * hd,), dtype)
        p[f"l{i}.wo"] = dense((cfg.n_heads * hd, cfg.dim))
        p[f"l{i}.mlp_norm"] = jnp.ones((cfg.dim,), dtype)
        p[f"l{i}.w_gate"] = dense((cfg.dim, cfg.ffn_dim))
        p[f"l{i}.w_up"] = dense((cfg.dim, cfg.ffn_dim))
        p[f"l{i}.w_down"] = dense((cfg.ffn_dim, cfg.dim))
    return p


def _w(p: dict[str, jax.Array], key: str) -> jax.Array:
    """Resolve a weight that may be stored bf16, int8+per-channel scale
    (W8A16), or int4+group scale (W4A16) — self-describing on q.dtype
    (models/quant.py). The convert-and-scale sits on the matmul operand
    so XLA fuses it; HBM traffic is the packed int8/int4 bytes."""
    q = p.get(key + ".q")
    if q is None:
        return p[key]
    scale = p[key + ".scale"]
    if q.dtype == jnp.int4:
        # group-wise scales along the input axis: scale [..., in/G, out]
        *lead, n_in, n_out = q.shape
        groups = scale.shape[-2]
        wf = q.astype(jnp.bfloat16).reshape(
            *lead, groups, n_in // groups, n_out)
        wf = wf * scale.astype(jnp.bfloat16)[..., :, None, :]
        return wf.reshape(*lead, n_in, n_out)
    return q.astype(jnp.bfloat16) * scale.astype(jnp.bfloat16)


def _embed_rows(p: dict[str, jax.Array], tokens: jax.Array) -> jax.Array:
    q = p.get("embed.q")
    if q is None:
        return jnp.take(p["embed"], tokens, axis=0)
    rows = jnp.take(q, tokens, axis=0).astype(jnp.bfloat16)
    scales = jnp.take(p["embed.scale"][:, 0], tokens, axis=0)
    return rows * scales[..., None].astype(jnp.bfloat16)


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * w


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embeddings. x: [..., S, H, D], positions broadcastable [..., S]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = (
        positions.astype(jnp.float32)[..., :, None, None] * freqs[None, None, :]
    )  # [..., S, 1, D/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., ::2], x[..., 1::2]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    o1 = xf1 * cos - xf2 * sin
    o2 = xf2 * cos + xf1 * sin
    out = jnp.stack([o1, o2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def _attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, T, Hkv, D]
    v: jax.Array,  # [B, T, Hkv, D]
    mask: jax.Array,  # [B, S, T] bool, True = attend
) -> jax.Array:
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    qg = q.reshape(B, S, Hkv, group, D)
    logits = jnp.einsum(
        "bshgd,bthd->bhgst", qg, k, preferred_element_type=jnp.float32
    )
    logits = logits / math.sqrt(D)
    logits = jnp.where(mask[:, None, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhgst,bthd->bshgd", probs.astype(v.dtype), v)
    return out.reshape(B, S, H * D)


def _matmul(p: dict[str, jax.Array], key: str, x: jax.Array) -> jax.Array:
    """``x @ weight`` with the W8A16 Pallas fast path.

    For quantized weights at decode shapes (small M, aligned K/N) the
    fused kernel streams int8 and applies the scale to the accumulator
    (ops/pallas/qmatmul.py); other shapes — prefill, unaligned, or
    AIGW_PALLAS_QMATMUL=off — fall back to dequant-then-matmul via
    ``_w`` (XLA fuses the dequant as the matmul's producer)."""
    q = p.get(key + ".q")
    if q is None or q.dtype != jnp.int8 or os.environ.get(
            "AIGW_PALLAS_QMATMUL", "on").lower() in ("0", "false", "off"):
        # int4 carries GROUP-wise scales the per-column W8A16 kernel
        # would silently misapply — int4 always dequants via _w
        return x @ _w(p, key)
    from aigw_tpu.ops.pallas import qmatmul

    lead, k = x.shape[:-1], x.shape[-1]
    m = math.prod(lead)
    n = q.shape[-1]
    if not qmatmul.supported(m, k, n):
        return x @ _w(p, key)
    y = qmatmul.w8a16_matmul(x.reshape(m, k), q, p[key + ".scale"])
    return y.reshape(*lead, n)


def _wo_project(p, i, attn, lora=None, adapter_idx=None):
    """Attention out-projection with optional per-slot LoRA delta."""
    out = _matmul(p, f"l{i}.wo", attn)
    d = lora_delta(lora, f"l{i}.wo", attn, adapter_idx)
    return out if d is None else out + d


def _project_qkv(p, i, x, positions, cfg, lora=None, adapter_idx=None,
                 apply_rope=True):
    hd = cfg.head_dim
    B, S, _ = x.shape
    q = _matmul(p, f"l{i}.wq", x)
    k = _matmul(p, f"l{i}.wk", x)
    v = _matmul(p, f"l{i}.wv", x)
    for name, ref in (("wq", "q"), ("wk", "k"), ("wv", "v")):
        d = lora_delta(lora, f"l{i}.{name}", x, adapter_idx)
        if d is not None:
            if ref == "q":
                q = q + d
            elif ref == "k":
                k = k + d
            else:
                v = v + d
    if cfg.attn_bias:
        q, k, v = q + p[f"l{i}.bq"], k + p[f"l{i}.bk"], v + p[f"l{i}.bv"]
    q = q.reshape(B, S, cfg.n_heads, hd)
    k = k.reshape(B, S, cfg.n_kv_heads, hd)
    v = v.reshape(B, S, cfg.n_kv_heads, hd)
    if apply_rope:  # the fused decode kernel ropes Q/K in-kernel
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
    return q, k, v


def _mlp(p, i, x, lora=None, adapter_idx=None):
    def with_delta(y, name, inp):
        d = lora_delta(lora, f"l{i}.{name}", inp, adapter_idx)
        return y if d is None else y + d

    gate = jax.nn.silu(with_delta(_matmul(p, f"l{i}.w_gate", x),
                                  "w_gate", x))
    up = with_delta(_matmul(p, f"l{i}.w_up", x), "w_up", x)
    h = gate * up
    return with_delta(_matmul(p, f"l{i}.w_down", h), "w_down", h)


def _logits(p: dict[str, jax.Array], cfg: LlamaConfig, x: jax.Array) -> jax.Array:
    if cfg.tie_embeddings:
        return (x @ _w(p, "embed").T).astype(jnp.float32)
    return _matmul(p, "lm_head", x).astype(jnp.float32)


def prefill(
    p: dict[str, jax.Array],
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, S] int32, right-padded
    seq_lens: jax.Array,  # [B] int32 true lengths
    kv_cache: jax.Array,  # [L, 2, P*page, Hkv, D]
    page_table: jax.Array,  # [B, max_pages] int32 page ids
    page_size: int,
    mlp=None,  # pluggable feed-forward (MoE families override; see mixtral)
    lora=None,
    adapter_idx=None,
) -> tuple[jax.Array, jax.Array]:
    """Process prompts; returns (last-position logits [B, V], updated cache).

    Prompt self-attention never reads the cache (the prompt is
    self-contained); K/V are computed in-registers and scattered into the
    page pool once at the end — one HBM write per layer.
    """
    B, S = tokens.shape
    positions = jnp.arange(S, dtype=jnp.int32)[None, :].repeat(B, 0)
    valid = positions < seq_lens[:, None]  # [B, S]
    causal = positions[:, :, None] >= positions[:, None, :]
    mask = causal & valid[:, None, :]

    # flat cache slot per (b, s): page_table[b, s // page] * page + s % page
    n_slots = kvq.n_slots(kv_cache)
    slot = (
        jnp.take_along_axis(page_table, positions // page_size, axis=1) * page_size
        + positions % page_size
    )  # [B, S]
    x = _embed_rows(p, tokens)
    for i in range(cfg.n_layers):
        h = rms_norm(x, p[f"l{i}.attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(p, i, h, positions, cfg, lora, adapter_idx)
        # padded positions scatter to an out-of-bounds slot, which
        # mode="drop" discards (negative indices would wrap instead)
        flat = jnp.where(valid, slot, n_slots)
        kv_cache = kvq.scatter_kv(kv_cache, i, flat, k, v)
        attn = _attention(q, k, v, mask)
        x = x + _wo_project(p, i, attn, lora, adapter_idx)
        h = rms_norm(x, p[f"l{i}.mlp_norm"], cfg.norm_eps)
        x = x + (mlp(p, i, h) if mlp is not None
                 else _mlp(p, i, h, lora, adapter_idx))
    x = rms_norm(x, p["norm_f"], cfg.norm_eps)
    last = jnp.take_along_axis(
        x, (seq_lens - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]
    return _logits(p, cfg, last), kv_cache


def prefill_sp(
    p: dict[str, jax.Array],
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, S] int32, right-padded; S divisible by sp
    seq_lens: jax.Array,  # [B] int32 true lengths
    kv_cache: jax.Array,
    page_table: jax.Array,  # [B, max_pages]
    page_size: int,
    *,
    mesh,  # jax.sharding.Mesh with an "sp" axis
    strategy: str = "ring",  # "ring" | "ulysses"
    mlp=None,
    lora=None,
    adapter_idx=None,
) -> tuple[jax.Array, jax.Array]:
    """Sequence-parallel prefill: context parallelism for prompts whose
    attention working set exceeds one chip's HBM budget (SURVEY.md §5
    long-context). Identical to ``prefill`` except attention runs as ring
    attention over the ``sp`` mesh axis (ops/ring_attention.py) — each
    device holds S/sp of the sequence and K/V blocks rotate over ICI
    neighbors.

    Correctness under right padding: ring attention is causal-only (no
    validity mask), but padding sits at positions >= seq_len, so a valid
    query at position i < seq_len only ever attends keys <= i, all valid.
    Outputs at padded positions are garbage and are never read (logits are
    taken at seq_lens-1; padded K/V scatters are dropped)."""
    from aigw_tpu.ops.ring_attention import ring_attention

    B, S = tokens.shape
    positions = jnp.arange(S, dtype=jnp.int32)[None, :].repeat(B, 0)
    valid = positions < seq_lens[:, None]
    n_slots = kvq.n_slots(kv_cache)
    slot = (
        jnp.take_along_axis(page_table, positions // page_size, axis=1)
        * page_size
        + positions % page_size
    )
    x = _embed_rows(p, tokens)
    for i in range(cfg.n_layers):
        h = rms_norm(x, p[f"l{i}.attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(p, i, h, positions, cfg, lora, adapter_idx)
        flat = jnp.where(valid, slot, n_slots)
        kv_cache = kvq.scatter_kv(kv_cache, i, flat, k, v)
        attn = ring_attention(
            q, k.astype(q.dtype), v.astype(q.dtype),
            mesh=mesh, causal=True, strategy=strategy,
        ).astype(x.dtype)
        x = x + _wo_project(p, i, attn, lora, adapter_idx)
        h = rms_norm(x, p[f"l{i}.mlp_norm"], cfg.norm_eps)
        x = x + (mlp(p, i, h) if mlp is not None
                 else _mlp(p, i, h, lora, adapter_idx))
    x = rms_norm(x, p["norm_f"], cfg.norm_eps)
    last = jnp.take_along_axis(
        x, (seq_lens - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]
    return _logits(p, cfg, last), kv_cache


def prefill_sp_suffix(
    p: dict[str, jax.Array],
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, S] chunk tokens, right-padded; S % sp == 0
    prefix_lens: jax.Array,  # [B] int32 — tokens already in the cache
    seq_lens: jax.Array,  # [B] int32 — TOTAL length incl. prefix
    kv_cache: jax.Array,
    page_table: jax.Array,  # [B, pages]; pages*page_size % sp == 0
    page_size: int,
    *,
    mesh,  # jax.sharding.Mesh with an "sp" axis
    mlp=None,
    lora=None,
    adapter_idx=None,
) -> tuple[jax.Array, jax.Array]:
    """Sequence-parallel chunked prefill resuming at an arbitrary
    page-aligned offset: ``prefill_suffix`` semantics with ring attention
    over the ``sp`` axis (ops/ring_attention.ring_attention_prefix).

    Per layer the chunk's K/V scatter into the pool first (so the next
    chunk's window pass sees them), then attention runs two ring passes
    under one online-softmax carry: chunk-causal over the in-register
    K/V, plus the gathered page window masked to ``t < prefix_len``.
    With ``prefix_lens == 0`` the window pass is fully masked and this
    degenerates to ``prefill_sp`` over one chunk. Padded queries are
    garbage-out (never read); their scatters drop via the OOB slot.
    """
    from aigw_tpu.ops.ring_attention import ring_attention_prefix

    B, S = tokens.shape
    T = page_table.shape[1] * page_size
    n_slots = kvq.n_slots(kv_cache)
    positions = prefix_lens[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    valid = positions < seq_lens[:, None]  # [B, S]

    slot = (
        jnp.take_along_axis(page_table, positions // page_size, axis=1)
        * page_size
        + positions % page_size
    )
    flat = jnp.where(valid, slot, n_slots)  # OOB → dropped by scatter

    gslot = page_table[:, :, None] * page_size + jnp.arange(
        page_size, dtype=jnp.int32
    )
    gslot = gslot.reshape(B, T)

    x = _embed_rows(p, tokens)
    for i in range(cfg.n_layers):
        h = rms_norm(x, p[f"l{i}.attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(p, i, h, positions, cfg, lora, adapter_idx)
        kv_cache = kvq.scatter_kv(kv_cache, i, flat, k, v)
        k_all, v_all = kvq.gather_kv(kv_cache, i, gslot)
        attn = ring_attention_prefix(
            q, k.astype(q.dtype), v.astype(q.dtype),
            k_all.astype(q.dtype), v_all.astype(q.dtype),
            prefix_lens, mesh=mesh,
        ).astype(x.dtype)
        x = x + _wo_project(p, i, attn, lora, adapter_idx)
        h = rms_norm(x, p[f"l{i}.mlp_norm"], cfg.norm_eps)
        x = x + (mlp(p, i, h) if mlp is not None
                 else _mlp(p, i, h, lora, adapter_idx))
    x = rms_norm(x, p["norm_f"], cfg.norm_eps)
    last = jnp.take_along_axis(
        x, (seq_lens - prefix_lens - 1)[:, None, None].astype(jnp.int32),
        axis=1,
    )[:, 0]
    return _logits(p, cfg, last), kv_cache


def decode_step(
    p: dict[str, jax.Array],
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B] int32 current token per slot
    positions: jax.Array,  # [B] int32 position of `tokens`
    kv_cache: jax.Array,
    page_table: jax.Array,  # [B, max_pages]
    page_size: int,
    active: jax.Array,  # [B] bool slot occupied
    mlp=None,  # pluggable feed-forward (MoE families override)
    lora=None,  # stacked adapters (models/lora.py)
    adapter_idx=None,  # [B] int32 adapter row per slot
    attn_impl: str = "",  # see below
    mesh=None,  # jax Mesh — required by attn_impl="fused" on a mesh
) -> tuple[jax.Array, jax.Array]:
    """One continuous-batching decode step; returns (logits [B, V], cache).

    The hot loop: fixed shapes, inactive slots masked (their K/V writes
    drop). ``attn_impl`` selects the decode-attention rung (resolved by
    tpuserve/attention.py's fallback matrix, never directly by users):

    - ``""`` — XLA gather: the full padded window [B, T_max] is
      gathered per slot and runs dense attention (dequantizing at the
      gather when the pool is int8/int4).
    - ``"pallas"`` — the chained ragged paged-attention kernel
      (ops/pallas/paged_attention.py): scatter first, kernel reads the
      pool. Native-dtype pools only.
    - ``"fused"`` — the fused-step XLA reference
      (ops/pallas/decode_fused.paged_decode_walk): scatter (quantizing
      in-pass), then online-softmax page walk — memory bounded at
      [B, page], never the padded window. With ``mesh`` the walk runs
      per head-shard inside shard_map: each device walks its LOCAL
      pool shard — no GSPMD gather.
    - ``"fused-pallas"`` — ONE kernel per dispatch
      (ops/pallas/decode_fused.fused_paged_decode): RoPE + quantized
      append + paged attention fused; requires the engine's reserved
      dump page (last pool page) for inactive-slot writes.
    """
    B = tokens.shape[0]
    max_pages = page_table.shape[1]
    T = max_pages * page_size
    pos1 = positions[:, None]  # [B, 1]

    n_slots = kvq.n_slots(kv_cache)
    slot = (
        jnp.take_along_axis(page_table, pos1 // page_size, axis=1) * page_size
        + pos1 % page_size
    )  # [B, 1]
    slot = jnp.where(active[:, None], slot, n_slots)  # OOB → dropped

    use_pallas = attn_impl == "pallas"
    use_fused_kernel = attn_impl == "fused-pallas"
    use_fused_walk = attn_impl == "fused"
    if use_pallas and kvq.is_quantized(kv_cache):
        raise NotImplementedError(
            "the chained Pallas decode kernel has no quantized-pool "
            "rung — the fallback matrix resolves int8/int4 to fused")
    if not (use_pallas or use_fused_kernel or use_fused_walk):
        # gather the full (padded) KV window for each slot
        t_idx = jnp.arange(T, dtype=jnp.int32)[None, :].repeat(B, 0)
        gslot = page_table[:, :, None] * page_size + jnp.arange(
            page_size, dtype=jnp.int32
        )
        gslot = gslot.reshape(B, T)  # [B, T] flat cache indices
        attend = t_idx <= pos1  # causal within the sequence window
    elif use_pallas:
        from aigw_tpu.ops.pallas._compat import is_tpu_backend
        from aigw_tpu.ops.pallas.paged_attention import (
            paged_attention_decode_v2,
        )

        lengths = jnp.where(active, positions + 1, 0)
        interp = not is_tpu_backend()
    elif use_fused_walk:
        from aigw_tpu.ops.pallas.decode_fused import (
            paged_decode_walk,
            paged_decode_walk_spmd,
        )

        lengths = jnp.where(active, positions + 1, 0)
    else:
        from aigw_tpu.ops.pallas._compat import is_tpu_backend
        from aigw_tpu.ops.pallas.decode_fused import fused_paged_decode

        interp = not is_tpu_backend()

    HD = cfg.n_heads * cfg.head_dim
    x = _embed_rows(p, tokens[:, None])  # [B, 1, dim]
    for i in range(cfg.n_layers):
        h = rms_norm(x, p[f"l{i}.attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(p, i, h, pos1, cfg, lora, adapter_idx,
                               apply_rope=not use_fused_kernel)
        if use_fused_kernel:
            # RoPE + append + attention in ONE kernel; the pool leaves
            # come back with the new row already written
            kr, ksc = kvq.layer_pool(kv_cache, i, 0)
            vr, vsc = kvq.layer_pool(kv_cache, i, 1)
            outs = fused_paged_decode(
                q[:, 0], k[:, 0], v[:, 0], kr, vr, page_table,
                positions, active, k_scale=ksc, v_scale=vsc,
                rope_theta=cfg.rope_theta, page_size=page_size,
                interpret=interp)
            attn = outs[0].reshape(B, 1, HD)
            kv_cache = kvq.set_layer_pool(kv_cache, i, *outs[1:])
        else:
            kv_cache = kvq.scatter_kv(kv_cache, i, slot, k, v)
            if use_pallas:
                attn = paged_attention_decode_v2(
                    q[:, 0], kv_cache[i, 0], kv_cache[i, 1], page_table,
                    lengths, page_size=page_size, interpret=interp,
                ).reshape(B, 1, HD)
            elif use_fused_walk:
                kr, ksc = kvq.layer_pool(kv_cache, i, 0)
                vr, vsc = kvq.layer_pool(kv_cache, i, 1)
                if mesh is not None:
                    attn = paged_decode_walk_spmd(
                        q[:, 0], kr, vr, page_table, lengths,
                        mesh=mesh, page_size=page_size,
                        k_scale=ksc, v_scale=vsc)
                else:
                    attn = paged_decode_walk(
                        q[:, 0], kr, vr, page_table, lengths,
                        page_size=page_size, k_scale=ksc, v_scale=vsc)
                attn = attn.reshape(B, 1, HD)
            else:
                k_all, v_all = kvq.gather_kv(kv_cache, i, gslot)
                attn = _attention(q, k_all, v_all, attend[:, None, :])
        x = x + _wo_project(p, i, attn, lora, adapter_idx)
        h = rms_norm(x, p[f"l{i}.mlp_norm"], cfg.norm_eps)
        x = x + (mlp(p, i, h) if mlp is not None
                 else _mlp(p, i, h, lora, adapter_idx))
    x = rms_norm(x, p["norm_f"], cfg.norm_eps)
    return _logits(p, cfg, x[:, 0]), kv_cache


def verify_step(
    p: dict[str, jax.Array],
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, S] pending token + S-1 draft tokens
    positions: jax.Array,  # [B] int32 position of tokens[:, 0]
    kv_cache: jax.Array,
    page_table: jax.Array,  # [B, max_pages]
    page_size: int,
    active: jax.Array,  # [B] bool slot occupied
    limits: jax.Array,  # [B] int32 exclusive max write position
    mlp=None,
    lora=None,
    adapter_idx=None,
    attn_impl: str = "",  # "" = XLA gather; "pallas" = ragged kernel
) -> tuple[jax.Array, jax.Array]:
    """Speculative-decoding verifier: score S candidate positions in one
    step, returning logits at EVERY position ([B, S, V]) so the engine can
    accept the longest draft prefix that matches the model's own samples.

    KV safety (the reason draft rejection is free on this layout): K/V for
    all S positions are scattered, but a later step re-scatters any
    position it revisits *before* the causal gather (``t <= pos``) can see
    it, so stale writes from rejected drafts are never read. Writes are
    fenced by ``limits`` exactly like the decode step's page-safety fence.
    """
    B, S = tokens.shape
    T = page_table.shape[1] * page_size
    n_slots = kvq.n_slots(kv_cache)
    start = positions
    positions = positions[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    valid = active[:, None] & (positions < limits[:, None])  # [B, S]

    slot = (
        jnp.take_along_axis(page_table, positions // page_size, axis=1)
        * page_size
        + positions % page_size
    )
    flat = jnp.where(valid, slot, n_slots)  # OOB → dropped by scatter

    use_pallas = attn_impl == "pallas"
    if use_pallas and kvq.is_quantized(kv_cache):
        raise NotImplementedError(
            "the Pallas verify kernel has no quantized-pool rung — the "
            "fallback matrix keeps int8/int4 on the gather-dequant path")
    if not use_pallas:
        gslot = page_table[:, :, None] * page_size + jnp.arange(
            page_size, dtype=jnp.int32
        )
        gslot = gslot.reshape(B, T)
        t_idx = jnp.arange(T, dtype=jnp.int32)[None, :]
    else:
        from aigw_tpu.ops.pallas._compat import is_tpu_backend
        from aigw_tpu.ops.pallas.paged_attention import (
            paged_attention_verify,
        )

        # inactive slots: start <= -(S+1) → zero attendable keys
        # (the kernel's page gate is pos0 + S - p*page_size)
        pal_pos = jnp.where(active, start, -(S + 1))
        interp = not is_tpu_backend()

    x = _embed_rows(p, tokens)
    for i in range(cfg.n_layers):
        h = rms_norm(x, p[f"l{i}.attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(p, i, h, positions, cfg, lora, adapter_idx)
        kv_cache = kvq.scatter_kv(kv_cache, i, flat, k, v)
        if use_pallas:
            attn = paged_attention_verify(
                q, kv_cache[i, 0], kv_cache[i, 1], page_table, pal_pos,
                page_size=page_size, interpret=interp,
            ).reshape(B, S, cfg.n_heads * cfg.head_dim)
        else:
            k_all, v_all = kvq.gather_kv(kv_cache, i, gslot)
            mask = (t_idx[:, None, :] <= positions[:, :, None]) \
                & valid[..., None]
            attn = _attention(q, k_all, v_all, mask)
        x = x + _wo_project(p, i, attn, lora, adapter_idx)
        h = rms_norm(x, p[f"l{i}.mlp_norm"], cfg.norm_eps)
        x = x + (mlp(p, i, h) if mlp is not None
                 else _mlp(p, i, h, lora, adapter_idx))
    x = rms_norm(x, p["norm_f"], cfg.norm_eps)
    return _logits(p, cfg, x), kv_cache


def _ragged_window_attention(
    q: jax.Array,  # [T, H, D] packed queries (f32/bf16)
    k_pool: jax.Array,  # [n_slots, Hkv, D] (native or int8/int4)
    v_pool: jax.Array,
    pt_rows: jax.Array,  # [T, P] page ids of each token's sequence
    positions: jax.Array,  # [T] absolute position per token
    valid: jax.Array,  # [T] bool — False for padding rows
    page_size: int,
    k_scale: jax.Array | None = None,  # [n_slots, Hkv] (quantized pool)
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """XLA reference for the ragged prefill attention: online softmax
    over the page window, one page per loop step — the same math as the
    Pallas kernel (ops/pallas/paged_attention.ragged_prefill_attention)
    with memory bounded at [T, page] instead of [T, window], so the
    CPU/interpret fallback never materializes the full padded window.
    Returns [T, H * D] in q's dtype."""
    T, H, D = q.shape
    Hkv = k_pool.shape[1]
    grp = H // Hkv
    P = pt_rows.shape[1]
    qf = q.astype(jnp.float32).reshape(T, Hkv, grp, D) / math.sqrt(D)
    offs = jnp.arange(page_size, dtype=jnp.int32)

    def body(p, carry):
        m, l, acc = carry
        slots = pt_rows[:, p][:, None] * page_size + offs[None, :]
        k = k_pool[slots].astype(jnp.float32)  # [T, page, Hkv, D]
        v = v_pool[slots].astype(jnp.float32)
        if k_scale is not None:  # quantized pages: dequant at the read
            k = k * k_scale[slots][..., None]
            v = v * v_scale[slots][..., None]
        logits = jnp.einsum("thgd,tshd->thgs", qf, k)  # [T, Hkv, grp, page]
        kp = p * page_size + offs
        mask = (kp[None, :] <= positions[:, None]) & valid[:, None]
        logits = jnp.where(mask[:, None, None, :], logits, -1e30)
        m_new = jnp.maximum(m, logits.max(-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        probs = jnp.exp(logits - m_new)
        l_new = alpha * l + probs.sum(-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("thgs,tshd->thgd", probs, v)
        return m_new, l_new, acc_new

    m0 = jnp.full((T, Hkv, grp, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((T, Hkv, grp, 1), jnp.float32)
    acc0 = jnp.zeros((T, Hkv, grp, D), jnp.float32)
    # traced upper bound: pages past the highest attended position are
    # fully masked — skip them instead of walking the whole window
    # (the XLA analogue of the kernel's ragged DMA skip)
    max_pos = jnp.max(jnp.where(valid, positions, 0))
    p_hi = jnp.minimum(max_pos // page_size + 1, P)
    _, l, acc = lax.fori_loop(0, p_hi, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(T, H * D).astype(q.dtype)


def prefill_ragged(
    p: dict[str, jax.Array],
    cfg: LlamaConfig,
    tokens: jax.Array,  # [T] int32 — PACKED new tokens, all sequences
    row_seq: jax.Array,  # [T] int32 — sequence row per token; >= B = padding
    positions: jax.Array,  # [T] int32 — absolute position per token
    last_rows: jax.Array,  # [B] int32 — packed index of each row's last token
    kv_cache: jax.Array,
    page_table: jax.Array,  # [B, max_pages]
    page_size: int,
    *,
    attn_impl: str = "",  # "" = XLA windowed reference; "pallas" = kernel
    mlp=None,
    lora=None,
    adapter_idx=None,  # [B] int32 adapter row per sequence row
) -> tuple[jax.Array, jax.Array]:
    """Ragged prefill: ONE program for any admission-burst geometry.

    The packed layout replaces per-sequence bucket padding: sequence b's
    new tokens occupy a contiguous run of packed rows (grouped and
    ascending in b, padding rows at the tail with ``row_seq >= B``), at
    absolute positions ``positions`` — nonzero first positions make
    offset-resumed prefill (prefix-cache partial hits, chunked-prefill
    continuations) first-class. Per layer the chunk's K/V are scattered
    into the page pool, then every packed query attends its own
    sequence's page window under a global causal mask — semantically
    ``prefill_suffix`` with the batch dimension flattened away. Returns
    (logits at each row's last packed token [B, V], updated cache);
    rows whose segment does not end the prompt carry don't-care logits
    the engine ignores.
    """
    T = tokens.shape[0]
    B, P = page_table.shape
    valid = row_seq < B
    rs = jnp.minimum(row_seq, B - 1)
    n_slots = kvq.n_slots(kv_cache)
    pt_rows = page_table[rs]  # [T, P]
    slot = (
        jnp.take_along_axis(
            pt_rows, (positions // page_size)[:, None], axis=1)[:, 0]
        * page_size
        + positions % page_size
    )
    flat = jnp.where(valid, slot, n_slots)[:, None]  # [T, 1]; OOB drops
    atok = adapter_idx[rs] if adapter_idx is not None else None

    use_pallas = attn_impl == "pallas"
    if use_pallas and kvq.is_quantized(kv_cache):
        raise NotImplementedError(
            "the Pallas ragged-prefill kernel has no quantized-pool "
            "rung — the fallback matrix keeps int8/int4 on the XLA "
            "windowed path")
    if use_pallas:
        from aigw_tpu.ops.pallas._compat import is_tpu_backend
        from aigw_tpu.ops.pallas.paged_attention import (
            ragged_prefill_attention,
        )

        interp = not is_tpu_backend()
        # the kernel's scalar-prefetch metadata, derived from the packed
        # layout (rows grouped and ascending in b, padding at the tail)
        cu = jnp.searchsorted(
            row_seq, jnp.arange(B + 1, dtype=jnp.int32), side="left"
        ).astype(jnp.int32)
        start = positions[jnp.minimum(cu[:B], T - 1)]

    # per-token layout [T, 1, ...]: every existing helper (rope, LoRA
    # deltas, projections) treats the packed rows as batch entries
    x = _embed_rows(p, tokens[:, None])  # [T, 1, dim]
    pos2 = positions[:, None]
    for i in range(cfg.n_layers):
        h = rms_norm(x, p[f"l{i}.attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(p, i, h, pos2, cfg, lora, atok)
        kv_cache = kvq.scatter_kv(kv_cache, i, flat, k, v)
        if use_pallas:
            attn = ragged_prefill_attention(
                q[:, 0], kv_cache[i, 0], kv_cache[i, 1], page_table,
                cu, start, page_size=page_size, interpret=interp,
            ).reshape(T, 1, cfg.n_heads * cfg.head_dim)
        else:
            kr, ksc = kvq.layer_pool(kv_cache, i, 0)
            vr, vsc = kvq.layer_pool(kv_cache, i, 1)
            attn = _ragged_window_attention(
                q[:, 0], kr, vr, pt_rows, positions, valid, page_size,
                k_scale=ksc, v_scale=vsc,
            ).reshape(T, 1, -1)
        x = x + _wo_project(p, i, attn, lora, atok)
        h = rms_norm(x, p[f"l{i}.mlp_norm"], cfg.norm_eps)
        x = x + (mlp(p, i, h) if mlp is not None
                 else _mlp(p, i, h, lora, atok))
    x = rms_norm(x, p["norm_f"], cfg.norm_eps)
    last = x[jnp.clip(last_rows, 0, T - 1), 0]  # [B, dim]
    return _logits(p, cfg, last), kv_cache


def hidden_states(
    p: dict[str, jax.Array],
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, S]
    seq_lens: jax.Array,  # [B]
    mlp=None,  # pluggable feed-forward (MoE families override)
    lora=None,
    adapter_idx=None,
) -> jax.Array:
    """Mean-pooled final hidden states (the /v1/embeddings path)."""
    B, S = tokens.shape
    positions = jnp.arange(S, dtype=jnp.int32)[None, :].repeat(B, 0)
    valid = positions < seq_lens[:, None]
    causal = positions[:, :, None] >= positions[:, None, :]
    mask = causal & valid[:, None, :]
    x = _embed_rows(p, tokens)
    for i in range(cfg.n_layers):
        h = rms_norm(x, p[f"l{i}.attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(p, i, h, positions, cfg, lora, adapter_idx)
        x = x + _wo_project(p, i, _attention(q, k, v, mask), lora,
                            adapter_idx)
        h = rms_norm(x, p[f"l{i}.mlp_norm"], cfg.norm_eps)
        x = x + (mlp(p, i, h) if mlp is not None
                 else _mlp(p, i, h, lora, adapter_idx))
    x = rms_norm(x, p["norm_f"], cfg.norm_eps)
    w = valid[..., None].astype(jnp.float32)
    pooled = (x.astype(jnp.float32) * w).sum(1) / jnp.maximum(w.sum(1), 1.0)
    return pooled


def prefill_suffix(
    p: dict[str, jax.Array],
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, S] suffix tokens, right-padded
    prefix_lens: jax.Array,  # [B] int32 — tokens already in the cache
    seq_lens: jax.Array,  # [B] int32 — TOTAL length incl. prefix
    kv_cache: jax.Array,
    page_table: jax.Array,  # [B, max_pages]
    page_size: int,
    mlp=None,
    lora=None,
    adapter_idx=None,
) -> tuple[jax.Array, jax.Array]:
    """Prefill only the suffix of a prompt whose prefix K/V already sits in
    cache pages (prefix caching / chunked prefill). Per layer: suffix K/V
    are scattered into the pool first, then attention gathers the full
    page window — so suffix queries see both the cached prefix and the
    suffix itself under a global causal mask. With ``prefix_lens == 0``
    this degenerates to (a gather-based) full prefill.
    """
    B, S = tokens.shape
    T = page_table.shape[1] * page_size
    n_slots = kvq.n_slots(kv_cache)
    positions = prefix_lens[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    valid = positions < seq_lens[:, None]  # [B, S]

    slot = (
        jnp.take_along_axis(page_table, positions // page_size, axis=1)
        * page_size
        + positions % page_size
    )
    flat = jnp.where(valid, slot, n_slots)  # OOB → dropped by scatter

    gslot = page_table[:, :, None] * page_size + jnp.arange(
        page_size, dtype=jnp.int32
    )
    gslot = gslot.reshape(B, T)
    t_idx = jnp.arange(T, dtype=jnp.int32)[None, :]

    x = _embed_rows(p, tokens)
    for i in range(cfg.n_layers):
        h = rms_norm(x, p[f"l{i}.attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(p, i, h, positions, cfg, lora, adapter_idx)
        kv_cache = kvq.scatter_kv(kv_cache, i, flat, k, v)
        k_all, v_all = kvq.gather_kv(kv_cache, i, gslot)
        # causal over global positions; padded queries masked by `valid`
        mask = (t_idx[:, None, :] <= positions[:, :, None]) & valid[..., None]
        attn = _attention(q, k_all, v_all, mask)
        x = x + _wo_project(p, i, attn, lora, adapter_idx)
        h = rms_norm(x, p[f"l{i}.mlp_norm"], cfg.norm_eps)
        x = x + (mlp(p, i, h) if mlp is not None
                 else _mlp(p, i, h, lora, adapter_idx))
    x = rms_norm(x, p["norm_f"], cfg.norm_eps)
    last = jnp.take_along_axis(
        x, (seq_lens - prefix_lens - 1)[:, None, None].astype(jnp.int32),
        axis=1,
    )[:, 0]
    return _logits(p, cfg, last), kv_cache
