"""Multi-LoRA serving: per-request low-rank adapters, batched.

Adapters live stacked on device — ``l{i}.{kind}.lora_a`` is
``[n_adapters, r, in]`` and ``…lora_b`` is ``[n_adapters, out, r]`` — and
every batch slot carries an adapter index, so ONE compiled program serves
any mix of adapters (the vLLM multi-LoRA idea, implemented for this
engine's [B]-slot decode geometry):

    delta = (x @ A[idx]ᵀ) @ B[idx]ᵀ      (two thin matmuls per target)

Row ``n_adapters`` (the last row) is the all-zeros "no adapter" row;
requests without an adapter point there, so base-model behavior is exact
(not merely approximate). The α/r scaling folds into A at load time.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class LoRAConfig:
    rank: int = 16
    alpha: float = 32.0
    # matmul targets by weight kind (classic attention-only default)
    targets: tuple[str, ...] = ("wq", "wv")


_DIMS = {
    "wq": lambda c: (c.dim, c.n_heads * c.head_dim),
    "wk": lambda c: (c.dim, c.n_kv_heads * c.head_dim),
    "wv": lambda c: (c.dim, c.n_kv_heads * c.head_dim),
    "wo": lambda c: (c.n_heads * c.head_dim, c.dim),
    "w_gate": lambda c: (c.dim, c.ffn_dim),
    "w_up": lambda c: (c.dim, c.ffn_dim),
    "w_down": lambda c: (c.ffn_dim, c.dim),
}


def init_lora_adapters(
    key: jax.Array,
    model_cfg,
    lora_cfg: LoRAConfig,
    n_adapters: int,
    dtype=jnp.bfloat16,
    random_b: bool = False,
) -> dict[str, jax.Array]:
    """Stacked adapter weights (+1 trailing all-zero row).

    B matrices init to zero (the LoRA convention — adapters start as
    no-ops); ``random_b`` fills them for tests that need visible deltas.
    """
    scale = lora_cfg.alpha / lora_cfg.rank
    out: dict[str, jax.Array] = {}
    keys = iter(jax.random.split(key, model_cfg.n_layers * len(_DIMS) * 2))
    rows = n_adapters + 1  # + zero row
    for i in range(model_cfg.n_layers):
        for kind in lora_cfg.targets:
            d_in, d_out = _DIMS[kind](model_cfg)
            # both keys are ALWAYS drawn, so the A matrices are identical
            # whether random_b is on or off — seeded tests comparing the
            # two modes see the same adapter geometry, not a shifted key
            # stream (B is zero in the off mode, so the unused key is
            # free)
            key_a, key_b = next(keys), next(keys)
            a = (
                jax.random.normal(key_a, (rows, lora_cfg.rank, d_in),
                                  jnp.float32)
                / math.sqrt(d_in) * scale
            )
            if random_b:
                b = jax.random.normal(key_b,
                                      (rows, d_out, lora_cfg.rank),
                                      jnp.float32) / math.sqrt(lora_cfg.rank)
            else:
                b = jnp.zeros((rows, d_out, lora_cfg.rank), jnp.float32)
            # zero row: base-model passthrough
            a = a.at[n_adapters].set(0.0)
            b = b.at[n_adapters].set(0.0)
            out[f"l{i}.{kind}.lora_a"] = a.astype(dtype)
            out[f"l{i}.{kind}.lora_b"] = b.astype(dtype)
    return out


def validate_adapter_params(params: dict, name: str = "") -> None:
    """Fail fast on malformed adapter dicts: every ``X.lora_a`` must pair
    with an ``X.lora_b`` of a matching rank (and vice versa). Without
    this, a missing half surfaced as a bare KeyError deep inside the
    batched matmul path — useless for diagnosing which adapter/tensor
    was broken. Called at adapter registration (tpuserve/adapters.py)
    and defensively by ``lora_delta``."""
    label = f"adapter {name!r}: " if name else ""
    for k in params:
        if k.endswith(".lora_a"):
            base = k[: -len(".lora_a")]
            other = base + ".lora_b"
            if other not in params:
                raise ValueError(f"{label}{k} has no matching {other}")
            r_a = params[k].shape[-2]  # [.., r, in]
            r_b = params[other].shape[-1]  # [.., out, r]
            if r_a != r_b:
                raise ValueError(
                    f"{label}rank mismatch for {base}: lora_a rank "
                    f"{r_a} vs lora_b rank {r_b}")
        elif k.endswith(".lora_b"):
            base = k[: -len(".lora_b")]
            if base + ".lora_a" not in params:
                raise ValueError(
                    f"{label}{k} has no matching {base}.lora_a")
        else:
            raise ValueError(
                f"{label}unexpected tensor {k!r} (expected "
                "'<layer>.<kind>.lora_a/.lora_b' keys)")


def lora_delta(
    lora: dict[str, jax.Array] | None,
    key: str,
    x: jax.Array,  # [B, S, in]
    idx: jax.Array | None,  # [B] int32 adapter row per slot
) -> jax.Array | None:
    """Per-slot adapter contribution for ``x @ W[key]``, or None."""
    if lora is None or idx is None:
        return None
    a = lora.get(key + ".lora_a")
    if a is None:
        return None
    b = lora.get(key + ".lora_b")
    if b is None:
        # half an adapter pair would otherwise be a bare KeyError with
        # no tensor name — deep inside a traced matmul stack
        raise ValueError(
            f"adapter tensor {key}.lora_b missing while {key}.lora_a "
            "is present (malformed adapter dict)")
    a_sel = a[idx]  # [B, r, in]
    b_sel = b[idx]  # [B, out, r]
    t = jnp.einsum("bsd,brd->bsr", x, a_sel)
    return jnp.einsum("bsr,bor->bso", t, b_sel)
