"""Mixtral-family (sparse MoE) transformer, TPU-first.

Same GQA attention/paged-KV skeleton as the Llama family (the attention
internals are imported from models/llama.py — one implementation, two
families); the MLP is a top-2 mixture of experts implemented GShard-style
with **dispatch/combine einsums** and a fixed expert capacity:

    gate probs → top-k → position-in-expert (cumsum) → one-hot dispatch
    [T, E, C] → x_e = einsum(dispatch, x) → batched expert MLP over E →
    combine = einsum(dispatch·weights, y_e)

Everything is static-shaped, so the whole MoE compiles to einsums that the
MXU eats, and **expert parallelism is a sharding annotation**: expert
weights carry PartitionSpec("ep", ...) and GSPMD turns the dispatch /
combine einsums into all-to-alls over the ``ep`` mesh axis
(aigw_tpu/parallel/sharding.py::mixtral_param_specs).

Capacity overflow drops tokens from that expert (they keep their other
top-k expert + the residual path) — the standard trade for static shapes.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from aigw_tpu.models import llama
from aigw_tpu.models.llama import LlamaConfig


@dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 2.0
    rope_theta: float = 1e6
    norm_eps: float = 1e-5
    max_seq_len: int = 32768

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def as_llama(self) -> LlamaConfig:
        """The attention-relevant view consumed by the shared skeleton."""
        return LlamaConfig(
            vocab_size=self.vocab_size,
            dim=self.dim,
            n_layers=self.n_layers,
            n_heads=self.n_heads,
            n_kv_heads=self.n_kv_heads,
            ffn_dim=self.ffn_dim,
            rope_theta=self.rope_theta,
            norm_eps=self.norm_eps,
            max_seq_len=self.max_seq_len,
        )


MIXTRAL_8X7B = MixtralConfig()
TINY_MOE = MixtralConfig(
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    ffn_dim=128, n_experts=4, experts_per_token=2, max_seq_len=512,
    rope_theta=10000.0,
)


def init_params(key: jax.Array, cfg: MixtralConfig,
                dtype=jnp.bfloat16) -> dict[str, jax.Array]:
    keys = iter(jax.random.split(key, 4 + cfg.n_layers * 8))

    def dense(shape, scale=None):
        scale = scale or 1.0 / math.sqrt(shape[-2] if len(shape) > 1 else shape[0])
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale
                ).astype(dtype)

    p: dict[str, jax.Array] = {
        "embed": dense((cfg.vocab_size, cfg.dim), scale=0.02),
        "norm_f": jnp.ones((cfg.dim,), dtype),
        "lm_head": dense((cfg.dim, cfg.vocab_size)),
    }
    hd = cfg.head_dim
    E = cfg.n_experts
    for i in range(cfg.n_layers):
        p[f"l{i}.attn_norm"] = jnp.ones((cfg.dim,), dtype)
        p[f"l{i}.wq"] = dense((cfg.dim, cfg.n_heads * hd))
        p[f"l{i}.wk"] = dense((cfg.dim, cfg.n_kv_heads * hd))
        p[f"l{i}.wv"] = dense((cfg.dim, cfg.n_kv_heads * hd))
        p[f"l{i}.wo"] = dense((cfg.n_heads * hd, cfg.dim))
        p[f"l{i}.mlp_norm"] = jnp.ones((cfg.dim,), dtype)
        p[f"l{i}.gate"] = dense((cfg.dim, E))
        p[f"l{i}.w_gate"] = dense((E, cfg.dim, cfg.ffn_dim))
        p[f"l{i}.w_up"] = dense((E, cfg.dim, cfg.ffn_dim))
        p[f"l{i}.w_down"] = dense((E, cfg.ffn_dim, cfg.dim))
    return p


def moe_mlp(p: dict[str, jax.Array], i: int, x: jax.Array,
            cfg: MixtralConfig, tape: list | None = None) -> jax.Array:
    """Top-k sparse MLP over flattened tokens. x: [B, S, D] → [B, S, D].

    ``tape`` is a trace-time accumulator: when a list is passed, each
    layer appends one ``[E + 1]`` int32 vector — per-expert placed
    (token, k) assignments followed by the count the capacity fence
    dropped — which the family entry points stack into the ``[L, E+1]``
    routing-stats leaf behind their ``moe_stats`` kwarg. Counts are
    over every row the program processed, padding included: they are
    truthful to device compute, not to prompt text."""
    B, S, D = x.shape
    T = B * S
    E, K = cfg.n_experts, cfg.experts_per_token
    C = max(K, int(math.ceil(T * K / E * cfg.capacity_factor)))
    C = min(C, T)
    xt = x.reshape(T, D)

    logits = (xt.astype(jnp.float32) @ p[f"l{i}.gate"].astype(jnp.float32))
    topv, topi = jax.lax.top_k(logits, K)  # [T, K]
    weights = jax.nn.softmax(topv, axis=-1)  # normalize over chosen experts

    # one-hot expert choice per (token, k): [T, K, E]
    choice = jax.nn.one_hot(topi, E, dtype=jnp.float32)
    # position of each (t, k) within its expert: cumulative count over the
    # flattened (t, k) order
    flat_choice = choice.reshape(T * K, E)
    pos = (jnp.cumsum(flat_choice, axis=0) - flat_choice).reshape(T, K, E)
    pos = jnp.sum(pos * choice, axis=-1).astype(jnp.int32)  # [T, K]
    keep = pos < C  # capacity fence
    pos_oh = jax.nn.one_hot(pos, C, dtype=jnp.float32) * keep[..., None]
    # dispatch [T, E, C]
    dispatch = jnp.einsum("tke,tkc->tec", choice, pos_oh)
    combine = jnp.einsum("tke,tkc,tk->tec", choice, pos_oh, weights)
    if tape is not None:
        placed = jnp.sum(dispatch, axis=(0, 2)).astype(jnp.int32)  # [E]
        dropped = jnp.sum(~keep).astype(jnp.int32)
        tape.append(jnp.concatenate([placed, dropped[None]]))

    xe = jnp.einsum("tec,td->ecd", dispatch, xt.astype(jnp.float32))
    xe = xe.astype(x.dtype)
    # expert weights resolve through llama._w so W8A16/W4A16 params
    # ([E, in, out] int8 per-channel / int4 group scales) dequantize at
    # the einsum operand — XLA fuses it; HBM streams the packed bytes
    gate = jax.nn.silu(jnp.einsum(
        "ecd,edf->ecf", xe, llama._w(p, f"l{i}.w_gate")))
    up = jnp.einsum("ecd,edf->ecf", xe, llama._w(p, f"l{i}.w_up"))
    ye = jnp.einsum("ecf,efd->ecd", gate * up,
                    llama._w(p, f"l{i}.w_down"))
    out = jnp.einsum("tec,ecd->td", combine, ye.astype(jnp.float32))
    return out.astype(x.dtype).reshape(B, S, D)


def _mlp_fn(cfg: MixtralConfig, tape: list | None = None):
    return lambda p, i, x: moe_mlp(p, i, x, cfg, tape=tape)


def _with_moe(out, tape):
    """(logits, kv) + a traced tape → (logits, kv, [L, E+1] stats)."""
    logits, kv_cache = out
    return logits, kv_cache, jnp.stack(tape)


# Every entry point of the llama skeleton is delegated with the MoE MLP
# plugged in — full feature parity (ragged prefill, chunked suffix
# resume, sequence-parallel prefill, fused decode, spec-decode verify),
# no family rows left in the fallback matrices. The static ``moe_stats``
# kwarg turns on the routing-stats leaf: the engine jits its programs
# with moe_stats=True for MoE families, so per-expert load and
# capacity drops ride the results it already fetches — no extra
# device→host sync. LoRA is llama-family-only for now; the args are
# accepted for interface parity.


def prefill(p, cfg: MixtralConfig, tokens, seq_lens, kv_cache, page_table,
            page_size, lora=None, adapter_idx=None, moe_stats=False):
    tape: list | None = [] if moe_stats else None
    out = llama.prefill(p, cfg.as_llama(), tokens, seq_lens, kv_cache,
                        page_table, page_size, mlp=_mlp_fn(cfg, tape))
    return _with_moe(out, tape) if moe_stats else out


def prefill_suffix(p, cfg: MixtralConfig, tokens, prefix_lens, seq_lens,
                   kv_cache, page_table, page_size, lora=None,
                   adapter_idx=None, moe_stats=False):
    tape: list | None = [] if moe_stats else None
    out = llama.prefill_suffix(p, cfg.as_llama(), tokens, prefix_lens,
                               seq_lens, kv_cache, page_table, page_size,
                               mlp=_mlp_fn(cfg, tape))
    return _with_moe(out, tape) if moe_stats else out


def prefill_sp(p, cfg: MixtralConfig, tokens, seq_lens, kv_cache,
               page_table, page_size, *, mesh, strategy="ring", lora=None,
               adapter_idx=None, moe_stats=False):
    tape: list | None = [] if moe_stats else None
    out = llama.prefill_sp(p, cfg.as_llama(), tokens, seq_lens, kv_cache,
                           page_table, page_size, mesh=mesh,
                           strategy=strategy, mlp=_mlp_fn(cfg, tape))
    return _with_moe(out, tape) if moe_stats else out


def prefill_sp_suffix(p, cfg: MixtralConfig, tokens, prefix_lens, seq_lens,
                      kv_cache, page_table, page_size, *, mesh, lora=None,
                      adapter_idx=None, moe_stats=False):
    tape: list | None = [] if moe_stats else None
    out = llama.prefill_sp_suffix(p, cfg.as_llama(), tokens, prefix_lens,
                                  seq_lens, kv_cache, page_table,
                                  page_size, mesh=mesh,
                                  mlp=_mlp_fn(cfg, tape))
    return _with_moe(out, tape) if moe_stats else out


def prefill_ragged(p, cfg: MixtralConfig, tokens, row_seq, positions,
                   last_rows, kv_cache, page_table, page_size, *,
                   attn_impl="", lora=None, adapter_idx=None,
                   moe_stats=False):
    # the packed [T, 1, D] token stream reuses the per-token rope/matmul
    # helpers; the dispatch/combine einsums are shape-agnostic over the
    # flattened token axis, so MoE rides the ragged stream unchanged
    tape: list | None = [] if moe_stats else None
    out = llama.prefill_ragged(p, cfg.as_llama(), tokens, row_seq,
                               positions, last_rows, kv_cache, page_table,
                               page_size, attn_impl=attn_impl,
                               mlp=_mlp_fn(cfg, tape))
    return _with_moe(out, tape) if moe_stats else out


def decode_step(p, cfg: MixtralConfig, tokens, positions, kv_cache,
                page_table, page_size, active, lora=None, adapter_idx=None,
                attn_impl="", mesh=None, moe_stats=False):
    tape: list | None = [] if moe_stats else None
    out = llama.decode_step(p, cfg.as_llama(), tokens, positions, kv_cache,
                            page_table, page_size, active,
                            mlp=_mlp_fn(cfg, tape), attn_impl=attn_impl,
                            mesh=mesh)
    return _with_moe(out, tape) if moe_stats else out


def hidden_states(p, cfg: MixtralConfig, tokens, seq_lens):
    return llama.hidden_states(p, cfg.as_llama(), tokens, seq_lens,
                               mlp=_mlp_fn(cfg))


def verify_step(p, cfg: MixtralConfig, tokens, positions, kv_cache,
                page_table, page_size, active, limits,
                lora=None, adapter_idx=None, attn_impl="",
                moe_stats=False):
    tape: list | None = [] if moe_stats else None
    out = llama.verify_step(p, cfg.as_llama(), tokens, positions, kv_cache,
                            page_table, page_size, active, limits,
                            mlp=_mlp_fn(cfg, tape), attn_impl=attn_impl)
    return _with_moe(out, tape) if moe_stats else out
