"""Model registry: name → (family, config, weight source).

The serving engine resolves ``--model`` through this registry. Weight
sources: ``random`` (tiny test models — the fake-chip mode the reference
achieves with testupstream), ``orbax:<path>`` sharded checkpoints, or
``hf:<path>`` local safetensors (no network).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from aigw_tpu.models import llama


@dataclass(frozen=True)
class ModelSpec:
    name: str
    family: str  # "llama" | "mixtral"
    config: Any
    weights: str = "random"  # "random" | "orbax:<dir>" | "hf:<dir>"
    tokenizer: str = "byte"  # "byte" | path to tokenizer.json
    chat_template: str = "llama3"  # "llama3" | "chatml"


@dataclass(frozen=True)
class ModelFns:
    """The functional surface the serving engine drives — uniform across
    model families (prefill/decode share the paged-KV contract)."""

    init_params: Any
    prefill: Any
    decode_step: Any
    hidden_states: Any
    # chunked prefill over cached prefix pages; None disables the engine's
    # prefix cache for the family
    prefill_suffix: Any = None
    # sequence-parallel (ring-attention) prefill for long prompts; None
    # disables the engine's sp prefill path for the family
    prefill_sp: Any = None
    # sequence-parallel chunked prefill resuming at a page-aligned
    # offset (ring attention + cached-window pass); None falls the sp
    # path back to the monolithic full-rung program
    prefill_sp_suffix: Any = None
    # multi-position verifier for speculative decoding; None disables the
    # engine's prompt-lookup speculation for the family
    verify_step: Any = None
    # packed variable-length prefill (one program per token-budget
    # chunk). Every registered family provides it; None remains only as
    # the hand-built-ModelFns escape hatch (it falls the attention
    # backend back to xla-bucketed)
    prefill_ragged: Any = None
    # static kwarg contract: entry points accept ``moe_stats=True`` and
    # return a trailing [L, E+1] int32 routing-stats leaf (per-expert
    # placed counts + capacity drops per layer). The engine turns it on
    # for MoE families (configs carrying ``n_experts``)
    moe_stats: bool = False


def family_fns(family: str) -> ModelFns:
    if family == "llama":
        return ModelFns(llama.init_params, llama.prefill, llama.decode_step,
                        llama.hidden_states,
                        prefill_suffix=llama.prefill_suffix,
                        prefill_sp=llama.prefill_sp,
                        prefill_sp_suffix=llama.prefill_sp_suffix,
                        verify_step=llama.verify_step,
                        prefill_ragged=llama.prefill_ragged)
    if family == "mixtral":
        from aigw_tpu.models import mixtral

        return ModelFns(mixtral.init_params, mixtral.prefill,
                        mixtral.decode_step, mixtral.hidden_states,
                        prefill_suffix=mixtral.prefill_suffix,
                        prefill_sp=mixtral.prefill_sp,
                        prefill_sp_suffix=mixtral.prefill_sp_suffix,
                        verify_step=mixtral.verify_step,
                        prefill_ragged=mixtral.prefill_ragged,
                        moe_stats=True)
    raise KeyError(f"unknown model family {family!r}")


_REGISTRY: dict[str, ModelSpec] = {}


def register_model(spec: ModelSpec) -> None:
    _REGISTRY[spec.name] = spec


def get_model_spec(name: str) -> ModelSpec:
    if name in _REGISTRY:
        return _REGISTRY[name]
    raise KeyError(
        f"unknown model {name!r}; registered: {sorted(_REGISTRY)}"
    )


register_model(ModelSpec("tiny-random", "llama", llama.TINY))


def _register_mixtral() -> None:
    from aigw_tpu.models import mixtral

    register_model(ModelSpec("tiny-moe", "mixtral", mixtral.TINY_MOE))
    register_model(ModelSpec("mixtral-8x7b", "mixtral",
                             mixtral.MIXTRAL_8X7B,
                             weights="orbax:checkpoints/mixtral-8x7b"))


_register_mixtral()
register_model(ModelSpec("llama-3-8b", "llama", llama.LLAMA3_8B,
                         weights="orbax:checkpoints/llama-3-8b"))
register_model(ModelSpec("qwen2-7b", "llama", llama.QWEN2_7B,
                         weights="orbax:checkpoints/qwen2-7b",
                         chat_template="chatml"))
register_model(ModelSpec("qwen2-0.5b", "llama", llama.QWEN2_05B,
                         weights="orbax:checkpoints/qwen2-0.5b",
                         chat_template="chatml"))
register_model(ModelSpec(
    "tiny-qwen", "llama",
    llama.LlamaConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, ffn_dim=128, max_seq_len=512,
                      rope_theta=10000.0, attn_bias=True,
                      tie_embeddings=True),
))
register_model(ModelSpec("llama-3-70b", "llama", llama.LLAMA3_70B,
                         weights="orbax:checkpoints/llama-3-70b"))
