"""Model registry: name → (family, config, weight source).

The serving engine resolves ``--model`` through this registry. Weight
sources: ``random`` (tiny test models — the fake-chip mode the reference
achieves with testupstream), ``orbax:<path>`` sharded checkpoints, or
``hf:<path>`` local safetensors (no network).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from aigw_tpu.models import llama


@dataclass(frozen=True)
class ModelSpec:
    name: str
    family: str  # "llama" | "mixtral"
    config: Any
    weights: str = "random"  # "random" | "orbax:<dir>" | "hf:<dir>"
    tokenizer: str = "byte"  # "byte" | path to tokenizer.json


_REGISTRY: dict[str, ModelSpec] = {}


def register_model(spec: ModelSpec) -> None:
    _REGISTRY[spec.name] = spec


def get_model_spec(name: str) -> ModelSpec:
    if name in _REGISTRY:
        return _REGISTRY[name]
    raise KeyError(
        f"unknown model {name!r}; registered: {sorted(_REGISTRY)}"
    )


register_model(ModelSpec("tiny-random", "llama", llama.TINY))
register_model(ModelSpec("llama-3-8b", "llama", llama.LLAMA3_8B,
                         weights="orbax:checkpoints/llama-3-8b"))
register_model(ModelSpec("llama-3-70b", "llama", llama.LLAMA3_70B,
                         weights="orbax:checkpoints/llama-3-70b"))
