"""Quantized paged KV pool: int8/int4 pages + per-page scale blocks.

The serving pool (models/llama.py) is a flat page array
``[L, 2, n_slots, Hkv, D]``. With ``kv_cache_dtype`` in
{"int8", "int4"} the pool becomes a TWO-leaf pytree:

    {"q":     int8|int4  [L, 2, n_slots, Hkv, D],
     "scale": float32    [L, 2, n_slots, Hkv]}

Every token row of a page carries one symmetric absmax scale per KV
head — the page's *scale block* ``[page_size, Hkv]`` lives in a pool
paged exactly like the data (same slot axis), so a page and its scales
always move together: spill, revive, migration, cross-replica fetch and
copy-on-write all slice axis 2 and are layout-agnostic (they tree_map
over the leaves). Per-row scales make the append a single quantized row
write — no page-wide requantization, so already-written rows never
re-round as a sequence grows (deterministic, order-independent pages).

Quantization is symmetric round-to-nearest-even in float32:

    scale = absmax / qmax   (1.0 when the row is all-zero)
    q     = clip(round(x / scale), -qmax, qmax)

with qmax 127 (int8) / 7 (int4; -8 unused keeps the grid symmetric).
Dequantization is ``q * scale`` in float32 — done *in-kernel* by the
fused decode kernel (ops/pallas/decode_fused.py) and at the gather site
by the XLA paths, so the quantized layout never round-trips through HBM
at full width.

Byte math per token across the stack (D = head_dim):
    native bf16:  L * 2 * Hkv * D * 2
    int8:         L * 2 * Hkv * (D + 4)      (~0.52x at D=128)
    int4:         L * 2 * Hkv * (D/2 + 4)    (~0.27x at D=128)

The native ("bfloat16"/"float32") pool stays a bare array — every
helper here degenerates to exactly the pre-quantization op sequence, so
native programs and their jit cache keys are unchanged.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

#: valid EngineConfig.kv_cache_dtype values
KV_DTYPES = ("bfloat16", "float32", "int8", "int4")
QUANT_DTYPES = ("int8", "int4")

_QMAX = {"int8": 127.0, "int4": 7.0}
_QDTYPE = {"int8": jnp.int8, "int4": jnp.int4}


def is_quantized_dtype(kv_cache_dtype: str) -> bool:
    return kv_cache_dtype in QUANT_DTYPES


def is_quantized(kv: Any) -> bool:
    """True when ``kv`` is the two-leaf quantized pool pytree."""
    return isinstance(kv, dict)


def quant_bits(kv_cache_dtype: str) -> int:
    """Bits per stored KV element (the ``kv_quant_bits`` gauge)."""
    return {"float32": 32, "bfloat16": 16, "int8": 8, "int4": 4}[
        kv_cache_dtype]


def bytes_per_kv_element(kv_cache_dtype: str) -> float:
    """HBM bytes per stored element INCLUDING the amortized scale
    (per-row, per-head f32 → 4/D extra bytes per element; the caller
    multiplies by D so the page math stays exact)."""
    return {"float32": 4.0, "bfloat16": 2.0, "int8": 1.0,
            "int4": 0.5}[kv_cache_dtype]


def compute_dtype(kv_cache_dtype: str):
    """jnp dtype of the DATA leaf."""
    if kv_cache_dtype in _QDTYPE:
        return _QDTYPE[kv_cache_dtype]
    return jnp.float32 if kv_cache_dtype == "float32" else jnp.bfloat16


def make_pool(kv_shape: tuple, kv_cache_dtype: str):
    """Zero-initialized pool: bare array (native) or {"q","scale"}
    pytree (quantized). ``kv_shape`` = [L, 2, n_slots, Hkv, D]."""
    if not is_quantized_dtype(kv_cache_dtype):
        return jnp.zeros(kv_shape, compute_dtype(kv_cache_dtype))
    return {
        "q": jnp.zeros(kv_shape, _QDTYPE[kv_cache_dtype]),
        "scale": jnp.zeros(kv_shape[:-1], jnp.float32),
    }


def pool_sharding_tree(kv: Any, mesh, data_spec) -> Any:
    """NamedSharding pytree matching ``kv``: the data leaf takes
    ``data_spec`` ([L, 2, slots, Hkv, D] — heads on "tp"); the scale
    leaf drops the trailing head_dim axis of that spec."""
    from jax.sharding import NamedSharding, PartitionSpec

    data = NamedSharding(mesh, data_spec)
    if not is_quantized(kv):
        return data
    scale = NamedSharding(mesh, PartitionSpec(*data_spec[:-1]))
    return {"q": data, "scale": scale}


def quantize_rows(x: jax.Array, kv_cache_dtype: str):
    """Quantize K or V rows ``[..., Hkv, D]`` → (q same shape,
    scale [..., Hkv] f32). Symmetric absmax per (row, head);
    deterministic (round-half-even in f32)."""
    qmax = _QMAX[kv_cache_dtype]
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0.0, amax / qmax, 1.0)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -qmax, qmax)
    return q.astype(_QDTYPE[kv_cache_dtype]), scale


def dequantize_rows(q: jax.Array, scale: jax.Array) -> jax.Array:
    """(q [..., Hkv, D], scale [..., Hkv]) → float32 rows."""
    return q.astype(jnp.float32) * scale[..., None]


# -- model-side pool ops --------------------------------------------------
def n_slots(kv: Any) -> int:
    """Row count of the pool (the OOB scatter-drop target)."""
    return (kv["q"] if is_quantized(kv) else kv).shape[2]


def kv_dtype_of(kv: Any) -> str:
    """The kv_cache_dtype string a live pool was built with (wire/
    validation helper)."""
    d = (kv["q"] if is_quantized(kv) else kv).dtype
    if d == jnp.int8:
        return "int8"
    if d == jnp.int4:
        return "int4"
    return "float32" if d == jnp.float32 else "bfloat16"


def scatter_kv(kv: Any, layer: int, flat: jax.Array, k: jax.Array,
               v: jax.Array) -> Any:
    """Write K/V rows at flat slot indices (mode="drop" — OOB rows are
    padding). Native: the exact pre-quantization scatter. Quantized:
    rows are quantized and land with their scale rows in one pass."""
    if not is_quantized(kv):
        kv = kv.at[layer, 0, flat].set(k, mode="drop")
        return kv.at[layer, 1, flat].set(v, mode="drop")
    dt = kv_dtype_of(kv)
    qk, sk = quantize_rows(k, dt)
    qv, sv = quantize_rows(v, dt)
    pool = kv["q"].at[layer, 0, flat].set(qk, mode="drop")
    pool = pool.at[layer, 1, flat].set(qv, mode="drop")
    scale = kv["scale"].at[layer, 0, flat].set(sk, mode="drop")
    scale = scale.at[layer, 1, flat].set(sv, mode="drop")
    return {"q": pool, "scale": scale}


def gather_kv(kv: Any, layer: int, gslot: jax.Array):
    """Read K/V rows at flat slot indices. Native: the exact
    pre-quantization gather (pool dtype out). Quantized: gathers the
    int rows + their scales, dequantizes in f32 at the gather site
    (HBM traffic is the packed bytes) and rounds to bf16 — the serving
    compute dtype, so a quantized pool never silently promotes the
    activation stack to f32."""
    if not is_quantized(kv):
        return kv[layer, 0][gslot], kv[layer, 1][gslot]
    k = dequantize_rows(kv["q"][layer, 0][gslot],
                        kv["scale"][layer, 0][gslot])
    v = dequantize_rows(kv["q"][layer, 1][gslot],
                        kv["scale"][layer, 1][gslot])
    return k.astype(jnp.bfloat16), v.astype(jnp.bfloat16)


def layer_pool(kv: Any, layer: int, which: int):
    """(rows [n_slots, Hkv, D], scale [n_slots, Hkv] | None) — the flat
    per-layer pool view the paged-attention walks/kernels consume."""
    if not is_quantized(kv):
        return kv[layer, which], None
    return kv["q"][layer, which], kv["scale"][layer, which]


def set_layer_pool(kv: Any, layer: int, k_rows, v_rows, k_scale=None,
                   v_scale=None) -> Any:
    """Write back a layer's (possibly kernel-updated) pool leaves."""
    if not is_quantized(kv):
        kv = kv.at[layer, 0].set(k_rows)
        return kv.at[layer, 1].set(v_rows)
    pool = kv["q"].at[layer, 0].set(k_rows)
    pool = pool.at[layer, 1].set(v_rows)
    scale = kv["scale"].at[layer, 0].set(k_scale)
    scale = scale.at[layer, 1].set(v_scale)
    return {"q": pool, "scale": scale}


# -- host-side page helpers (wire / spill / migration) --------------------
def page_to_host(rows: Any) -> Any:
    """Device page slice → host representation: np array (native) or
    {"q": np, "scale": np} (quantized). Bit-exact — quantized pages
    travel at native dtype + scales, never re-rounded."""
    if is_quantized(rows):
        return {"q": np.asarray(rows["q"]),
                "scale": np.asarray(rows["scale"])}
    return np.asarray(rows)


def page_nbytes(rows: Any) -> int:
    """Byte size of a host-side page (HostKVTier budget accounting).
    np int4 reports 1 byte/element — charge the PACKED size the device
    layout implies, so the host budget mirrors HBM math."""
    if isinstance(rows, dict):
        q = rows["q"]
        qb = q.size // 2 if q.dtype.name == "int4" else q.nbytes
        return int(qb + rows["scale"].nbytes)
    n = getattr(rows, "nbytes", None)
    return int(n) if n is not None else len(rows)


def page_shape_ok(rows: Any, want: tuple) -> bool:
    """Validate an imported page against the engine's
    (L, 2, page_size, Hkv, D) geometry (both layouts)."""
    if isinstance(rows, dict):
        return (tuple(rows["q"].shape) == want
                and tuple(rows["scale"].shape) == want[:-1])
    return tuple(rows.shape) == want


def page_matches_dtype(rows: Any, kv_cache_dtype: str) -> bool:
    """An imported page must match the pool's dtype family — a
    quantized page cannot scatter into a native pool (or vice versa)
    without silently changing its bytes."""
    if isinstance(rows, dict):
        return str(rows["q"].dtype) == kv_cache_dtype
    return not is_quantized_dtype(kv_cache_dtype)
