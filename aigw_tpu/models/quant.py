"""Weight-only quantization: int8 (W8A16) and int4 (W4A16).

Decode on TPU is weight-streaming-bound (every step reads every weight
from HBM); int8 halves that traffic, int4 quarters it, while activations
stay bf16. Inside the jitted step the packed block is converted and
scaled right at the matmul operand, which XLA fuses — HBM sees
int8/int4 bytes, the MXU sees bf16.

- **int8**: symmetric per-output-channel (scale per column; per row for
  the embedding since it is consumed by row gather).
- **int4**: symmetric GROUP-WISE along the input axis (one scale per
  ``GROUP4`` input rows per output channel — per-channel int4 is too
  lossy; group-128 is the standard W4 recipe). XLA's native ``int4``
  dtype packs two nibbles per byte in HBM.

Quantized params replace each matrix ``name`` with ``name.q`` (int8 or
int4) and ``name.scale``; the representation is self-describing (the
model resolver keys on ``q.dtype``). Norms and biases stay bf16.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

#: weight-name suffixes eligible for quantization (matmul-path matrices)
_MATRIX_KINDS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")

#: int4 group size along the input axis (one scale per group per
#: output channel) — the standard W4 recipe
GROUP4 = 128


@partial(jax.jit, static_argnames=("axis",))
def _quantize_matrix(w: jax.Array, axis: int) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 along ``axis`` (the preserved/output axis).

    Jitted so the f32 upcast fuses into the reduction and the rounding —
    eager dispatch would materialize a full f32 copy (2GB for an 8B
    embedding), which busts HBM when quantizing a 16GB bf16 model in
    place on a 16GB chip."""
    wf = w.astype(jnp.float32)
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    amax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


@jax.jit
def _quantize_matrix_int8_channels(
    w: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 with one scale per output channel per LEADING
    index: only the input axis (ndim-2) is reduced, so an [E, in, out]
    expert stack gets per-expert scales [E, 1, out] — one outlier-heavy
    expert must not coarsen every other expert's steps ([in, out]
    matrices reduce to [1, out], identical to before)."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=w.ndim - 2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


@partial(jax.jit, static_argnames=("group",))
def _quantize_matrix_int4(
    w: jax.Array, group: int,
) -> tuple[jax.Array, jax.Array]:
    """Symmetric int4, group-wise along the input axis (ndim-2): one
    f32 scale per ``group`` input rows per output channel. Returns
    (q int4 [..., in, out], scale f32 [..., in/group, out])."""
    wf = w.astype(jnp.float32)
    *lead, n_in, n_out = wf.shape
    g = wf.reshape(*lead, n_in // group, group, n_out)
    amax = jnp.max(jnp.abs(g), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(g / scale), -7, 7).astype(jnp.int4)
    return (q.reshape(*lead, n_in, n_out),
            scale.squeeze(-2).astype(jnp.float32))


def quantize_params(
    params: dict[str, jax.Array], consume: bool = False,
    mode: str = "int8",
) -> dict[str, jax.Array]:
    """bf16 param dict → W8A16 / W4A16 dict (un-quantized leaves pass
    through). ``mode`` is "int8" or "int4".

    ``consume=True`` removes each bf16 tensor from ``params`` as soon as
    its quantized replacement is materialized, bounding peak HBM to
    bf16-model + one tensor instead of two full copies — required to
    quantize an 8B bf16 model in place on a 16GB chip.
    """
    if mode not in ("int8", "int4"):
        raise ValueError(f"unknown quantization mode {mode!r}")
    out: dict[str, jax.Array] = {}
    for name in list(params):
        w = params.pop(name) if consume else params[name]
        kind = name.rsplit(".", 1)[-1]
        if kind in _MATRIX_KINDS and w.ndim >= 2:
            # output channels = last axis for [in, out] (and [E, in, out])
            if mode == "int4" and w.shape[-2] % GROUP4 == 0:
                q, scale = _quantize_matrix_int4(w, GROUP4)
            else:  # int8, or input dim not groupable
                q, scale = _quantize_matrix_int8_channels(w)
            out[name + ".q"] = q
            out[name + ".scale"] = scale
        elif name == "lm_head":
            if mode == "int4" and w.shape[0] % GROUP4 == 0:
                q, scale = _quantize_matrix_int4(w, GROUP4)
            else:
                q, scale = _quantize_matrix_int8_channels(w)
            out["lm_head.q"] = q
            out["lm_head.scale"] = scale
        elif name == "embed":
            # consumed by row gather: per-row scales either mode
            q, scale = _quantize_matrix(w, axis=0)
            out["embed.q"] = q
            out["embed.scale"] = scale
        else:
            out[name] = w
    return out


def is_quantized(params: dict[str, jax.Array]) -> bool:
    return any(k.endswith(".q") for k in params)
