"""Typed request schemas per endpoint + vendor-specific fields.

The reference types every endpoint's request body and rejects malformed
ones at the gateway (internal/apischema/openai/openai.go: CompletionRequest
:2073, EmbeddingRequest union :1757-1836, ImageGenerationRequest :2276,
cohere/rerank_v2.go:11, tokenize/), and threads *vendor-specific fields*
through the unified OpenAI surface (docs/proposals/004-vendor-specific-
fields/proposal.md): ``thinking`` (ThinkingUnion, openai.go:931-960),
``generationConfig``/``safetySettings`` (GCPVertexAIVendorFields,
openai.go:2004-2022) and the embedding vendor triple
``auto_truncate``/``task_type``/``title`` (openai.go:1840-1854).

This module declares those request types with the ``spec`` engine and
exposes one ``validate_request(endpoint, body)`` entry the gateway calls
before route selection — every JSON endpoint now rejects malformed
bodies before any upstream traffic, with JSON-path error locations.
Unknown fields still pass through (that is the vendor-fields contract).
"""

from __future__ import annotations

from typing import Any

from aigw_tpu.schemas.openai import SchemaError, validate_chat_request
from aigw_tpu.schemas.spec import Field, Spec, validate_object

# ---------------------------------------------------------------------------
# shared unions

#: prompt/input token forms: string | [string] | [int] | [[int]]
_TEXT_OR_TOKENS = Field(union=(
    Field(type="string"),
    Field(type="array", min_len=1, item=Field(union=(
        Field(type="string"),
        Field(type="integer"),
        Field(type="array", item=Field(type="integer")),
    ))),
))

_STOP = Field(union=(
    Field(type="string"),
    Field(type="array", max_len=4, item=Field(type="string")),
))

_STREAM_OPTIONS = Field(type="object", spec=Spec(fields={
    "include_usage": Field(type="boolean"),
}))

# ---------------------------------------------------------------------------
# vendor-specific fields (proposal 004)

#: Anthropic/Gemini reasoning config (ThinkingUnion, openai.go:931-1010):
#: discriminated on "type" — enabled|disabled|adaptive.
def _check_thinking(value: dict, path: str) -> None:
    t = value.get("type")
    if t not in ("enabled", "disabled", "adaptive"):
        raise SchemaError(
            f"{path}.type: must be one of ['adaptive', 'disabled', "
            f"'enabled'], got {t!r}")
    if t == "enabled":
        validate_object(value, Spec(fields={
            "budget_tokens": Field(type="integer", required=True, ge=0),
            "includeThoughts": Field(type="boolean"),
            "display": Field(type="string",
                             enum=("summarized", "omitted")),
        }), path)
    elif t == "adaptive":
        validate_object(value, Spec(fields={
            "display": Field(type="string",
                             enum=("summarized", "omitted")),
        }), path)


THINKING = Field(type="object", check=_check_thinking)

#: GCP Vertex AI chat vendor fields (openai.go:2004-2022). Category /
#: threshold values are typed as strings, not closed enums — the genai
#: enum set grows and the reference's string-typed genai enums accept
#: any value at unmarshal time too.
GCP_VERTEXAI_VENDOR = {
    "generationConfig": Field(type="object", spec=Spec(fields={
        "media_resolution": Field(type="string"),
        "thinkingConfig": Field(type="object", spec=Spec(fields={
            "includeThoughts": Field(type="boolean"),
            "thinkingBudget": Field(type="integer", ge=0),
        })),
    })),
    "safetySettings": Field(type="array", item=Field(
        type="object", spec=Spec(fields={
            "category": Field(type="string", required=True),
            "threshold": Field(type="string", required=True),
            "method": Field(type="string"),
        }))),
}

#: GCP Vertex AI embedding vendor fields (openai.go:1840-1854; wire
#: mapping per endpoint lives in translate/embeddings.py)
EMBEDDING_TASK_TYPES = (
    "RETRIEVAL_QUERY", "RETRIEVAL_DOCUMENT", "SEMANTIC_SIMILARITY",
    "CLASSIFICATION", "CLUSTERING", "QUESTION_ANSWERING",
    "FACT_VERIFICATION", "CODE_RETRIEVAL_QUERY",
)
GCP_EMBEDDING_VENDOR = {
    "auto_truncate": Field(type="boolean"),
    "task_type": Field(type="string", enum=EMBEDDING_TASK_TYPES),
    "title": Field(type="string"),
}

# ---------------------------------------------------------------------------
# /v1/completions (CompletionRequest, openai.go:2073-2161)

COMPLETIONS = Spec(fields={
    "model": Field(type="string", required=True, min_len=1),
    "prompt": Field(required=True, union=_TEXT_OR_TOKENS.union),
    "best_of": Field(type="integer", ge=0, le=20),
    "echo": Field(type="boolean"),
    "frequency_penalty": Field(type="number", ge=-2, le=2),
    "logit_bias": Field(type="object"),
    "logprobs": Field(type="integer", ge=0, le=5),
    "max_tokens": Field(type="integer", ge=0),
    "n": Field(type="integer", ge=1, le=128),
    "presence_penalty": Field(type="number", ge=-2, le=2),
    "seed": Field(type="integer"),
    "stop": _STOP,
    "stream": Field(type="boolean"),
    "stream_options": _STREAM_OPTIONS,
    "suffix": Field(type="string"),
    "temperature": Field(type="number", ge=0, le=2),
    "top_p": Field(type="number", ge=0, le=1),
    "user": Field(type="string"),
})

# ---------------------------------------------------------------------------
# /v1/embeddings (EmbeddingRequest discriminated union,
# openai.go:1781-1836: "input" → completion-style, "messages" →
# chat-style/multimodal, never both; input items may be objects carrying
# content/task_type/title, openai.go:408-432)

_EMBEDDING_INPUT_ITEM_OBJ = Field(type="object", spec=Spec(fields={
    "content": Field(required=True, union=(
        Field(type="string"),
        Field(type="array", item=Field(type="string")),
    )),
    "task_type": Field(type="string", enum=EMBEDDING_TASK_TYPES),
    "title": Field(type="string"),
}))

_EMBEDDING_INPUT = Field(union=(
    Field(type="string"),
    Field(type="array", min_len=1, item=Field(union=(
        Field(type="string"),
        Field(type="integer"),
        Field(type="array", item=Field(type="integer")),
        _EMBEDDING_INPUT_ITEM_OBJ,
    ))),
))


def _check_embeddings_variant(body: dict, _path: str) -> None:
    has_input = "input" in body
    has_messages = "messages" in body
    if has_input and has_messages:
        raise SchemaError(
            "embedding request must have either 'input' or 'messages', "
            "not both")
    if not has_input and not has_messages:
        raise SchemaError("input: is required")


EMBEDDINGS = Spec(
    fields={
        "model": Field(type="string", required=True, min_len=1),
        "input": _EMBEDDING_INPUT,
        "messages": Field(type="array", min_len=1, item=Field(
            type="object", spec=Spec(fields={
                "role": Field(type="string", required=True),
            }))),
        "encoding_format": Field(type="string",
                                 enum=("float", "base64")),
        "dimensions": Field(type="integer", ge=1),
        "user": Field(type="string"),
        **GCP_EMBEDDING_VENDOR,
    },
    checks=(_check_embeddings_variant,),
)

# ---------------------------------------------------------------------------
# /v1/images/generations (ImageGenerationRequest, openai.go:2276-2316)

IMAGES_GENERATIONS = Spec(fields={
    "prompt": Field(type="string", required=True, min_len=1),
    "model": Field(type="string"),
    "n": Field(type="integer", ge=1, le=10),
    "quality": Field(type="string", enum=(
        "auto", "standard", "hd", "low", "medium", "high")),
    "response_format": Field(type="string", enum=("url", "b64_json")),
    "size": Field(type="string"),
    "style": Field(type="string", enum=("vivid", "natural")),
    "user": Field(type="string"),
    "output_format": Field(type="string", enum=("png", "jpeg", "webp")),
    "output_compression": Field(type="integer", ge=0, le=100),
    "background": Field(type="string",
                        enum=("auto", "transparent", "opaque")),
    "moderation": Field(type="string", enum=("auto", "low")),
})

# ---------------------------------------------------------------------------
# /v2/rerank (cohere/rerank_v2.go:11-24)

RERANK = Spec(fields={
    "model": Field(type="string", required=True, min_len=1),
    "query": Field(type="string", required=True),
    "documents": Field(type="array", required=True, min_len=1,
                       item=Field(union=(
                           Field(type="string"),
                           Field(type="object", spec=Spec(fields={
                               "text": Field(type="string",
                                             required=True),
                           })),
                       ))),
    "top_n": Field(type="integer", ge=1),
    "max_tokens_per_doc": Field(type="integer", ge=1),
    "return_documents": Field(type="boolean"),
})

# ---------------------------------------------------------------------------
# /v1/audio/speech (OpenAI createSpeech; the reference routes it as one
# of its 12 endpoint processors, mainlib/main.go)

AUDIO_SPEECH = Spec(fields={
    "model": Field(type="string", required=True, min_len=1),
    "input": Field(type="string", required=True, min_len=1),
    "voice": Field(type="string", required=True, min_len=1),
    "instructions": Field(type="string"),
    "response_format": Field(type="string", enum=(
        "mp3", "opus", "aac", "flac", "wav", "pcm")),
    "speed": Field(type="number", ge=0.25, le=4.0),
    "stream_format": Field(type="string", enum=("sse", "audio")),
})

# ---------------------------------------------------------------------------
# /tokenize (vLLM-compatible; reference tokenize/, mainlib/main.go:326)

TOKENIZE = Spec(
    fields={
        "model": Field(type="string", required=True, min_len=1),
        "prompt": Field(type="string"),
        "messages": Field(type="array", item=Field(type="object")),
        "add_special_tokens": Field(type="boolean"),
    },
    checks=(lambda body, _p: (_ for _ in ()).throw(SchemaError(
        "tokenize request must have either 'prompt' or 'messages', "
        "not both")) if "prompt" in body and "messages" in body else None,),
)

# ---------------------------------------------------------------------------
# /v1/responses — input item unions typed deeply (r4 verdict: the
# earlier spec was "typed shallowly"). Discriminated on "type"; known
# types validate their full shape, unknown type strings pass (the item
# set grows — same forward-compat posture as vendor fields). An item
# with no "type" is a message iff it carries a role (the API accepts
# bare {role, content} items).

_RESPONSES_CONTENT_PARTS: dict[str, Spec] = {
    "input_text": Spec(fields={
        "text": Field(type="string", required=True, nullable=False)}),
    "output_text": Spec(fields={
        "text": Field(type="string", required=True, nullable=False),
        "annotations": Field(type="array"),
    }),
    "refusal": Spec(fields={
        "refusal": Field(type="string", required=True, nullable=False)}),
    "input_image": Spec(fields={
        "image_url": Field(type="string"),
        "file_id": Field(type="string"),
        "detail": Field(type="string", enum=("low", "high", "auto")),
    }),
    "input_file": Spec(fields={
        "file_id": Field(type="string"),
        "filename": Field(type="string"),
        "file_data": Field(type="string"),
        "file_url": Field(type="string"),
    }),
}


def _check_responses_content_part(value: dict, path: str) -> None:
    t = value.get("type")
    if not isinstance(t, str) or not t:
        raise SchemaError(f"{path}.type: is required")
    spec = _RESPONSES_CONTENT_PARTS.get(t)
    if spec is not None:
        validate_object(value, spec, path)


_RESPONSES_MESSAGE_ITEM = Spec(fields={
    "role": Field(type="string", required=True, nullable=False, enum=(
        "user", "assistant", "system", "developer")),
    "content": Field(required=True, nullable=False, union=(
        Field(type="string"),
        Field(type="array", min_len=1, item=Field(
            type="object", check=_check_responses_content_part)),
    )),
    "status": Field(type="string"),
})

_RESPONSES_INPUT_ITEMS: dict[str, Spec] = {
    "message": _RESPONSES_MESSAGE_ITEM,
    "function_call": Spec(fields={
        "call_id": Field(type="string", required=True, nullable=False),
        "name": Field(type="string", required=True, nullable=False),
        "arguments": Field(type="string", required=True, nullable=False),
        "status": Field(type="string"),
    }),
    "function_call_output": Spec(fields={
        "call_id": Field(type="string", required=True, nullable=False),
        "output": Field(required=True, nullable=False, union=(
            Field(type="string"),
            Field(type="array"),
        )),
        "status": Field(type="string"),
    }),
    "reasoning": Spec(fields={
        "summary": Field(type="array", required=True, item=Field(
            type="object", spec=Spec(fields={
                "type": Field(type="string", required=True),
                "text": Field(type="string"),
            }))),
        "encrypted_content": Field(type="string"),
        "status": Field(type="string"),
    }),
    "item_reference": Spec(fields={
        "id": Field(type="string", required=True, nullable=False),
    }),
}


def _check_responses_input_item(value: dict, path: str) -> None:
    t = value.get("type")
    if t is None:
        # bare {role, content} message item
        validate_object(value, _RESPONSES_MESSAGE_ITEM, path)
        return
    if not isinstance(t, str) or not t:
        raise SchemaError(f"{path}.type: must be string")
    spec = _RESPONSES_INPUT_ITEMS.get(t)
    if spec is not None:
        validate_object(value, spec, path)


def _check_responses_tool(value: dict, path: str) -> None:
    t = value.get("type")
    if not isinstance(t, str) or not t:
        raise SchemaError(f"{path}.type: is required")
    if t == "function":
        validate_object(value, Spec(fields={
            "name": Field(type="string", required=True, nullable=False,
                          min_len=1),
            "parameters": Field(type="object"),
            "strict": Field(type="boolean"),
            "description": Field(type="string"),
        }), path)


RESPONSES = Spec(
    fields={
        "model": Field(type="string", required=True, min_len=1),
        "input": Field(union=(
            Field(type="string"),
            Field(type="array", item=Field(
                type="object", check=_check_responses_input_item)),
        )),
        "instructions": Field(type="string"),
        "max_output_tokens": Field(type="integer", ge=1),
        "previous_response_id": Field(type="string"),
        "store": Field(type="boolean"),
        "stream": Field(type="boolean"),
        "temperature": Field(type="number", ge=0, le=2),
        "top_p": Field(type="number", ge=0, le=1),
        "parallel_tool_calls": Field(type="boolean"),
        "truncation": Field(type="string", enum=("auto", "disabled")),
        "reasoning": Field(type="object", spec=Spec(fields={
            "effort": Field(type="string", enum=(
                "minimal", "low", "medium", "high")),
            "summary": Field(type="string", enum=(
                "auto", "concise", "detailed")),
        })),
        "tool_choice": Field(union=(
            Field(type="string"), Field(type="object"))),
        "tools": Field(type="array", item=Field(
            type="object", check=_check_responses_tool)),
    },
)

# ---------------------------------------------------------------------------
# chat vendor-field overlay (validate_chat_request covers the core chat
# shape; this adds the proposal-004 fields on top)

_CHAT_VENDOR = Spec(fields={
    "thinking": THINKING,
    **GCP_VERTEXAI_VENDOR,
})


def validate_chat_with_vendor(body: dict[str, Any]) -> None:
    validate_chat_request(body)
    validate_object(body, _CHAT_VENDOR)


# ---------------------------------------------------------------------------
# dispatch

_BY_ENDPOINT: dict[str, Spec] = {
    "/v1/completions": COMPLETIONS,
    "/v1/embeddings": EMBEDDINGS,
    "/v1/images/generations": IMAGES_GENERATIONS,
    "/v2/rerank": RERANK,
    "/v1/audio/speech": AUDIO_SPEECH,
    "/tokenize": TOKENIZE,
    "/v1/responses": RESPONSES,
}


def validate_request(endpoint_path: str, body: dict[str, Any]) -> None:
    """Validate a JSON request body for ``endpoint_path``; raises
    SchemaError (→ client 400) on the first violation. Endpoints without
    a registered spec pass through (multipart endpoints are validated
    form-side in the gateway)."""
    if endpoint_path == "/v1/chat/completions":
        validate_chat_with_vendor(body)
        return
    spec = _BY_ENDPOINT.get(endpoint_path)
    if spec is not None:
        validate_object(body, spec)
