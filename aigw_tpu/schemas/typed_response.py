"""Typed RESPONSE schemas per endpoint — what the gateway re-emits.

Round-4 typed every request body; this module closes the other half
(r4 verdict missing #1): the reference types the full response surface
— non-stream bodies and SSE chunks — in
``internal/apischema/openai/openai.go`` (ChatCompletionResponse,
ChatCompletionResponseChunk, EmbeddingResponse, the Responses API
unions) and ``anthropic.go`` (Messages responses + stream events), so a
malformed upstream body fails typed unmarshalling inside the translator
and surfaces as an upstream error (``translator.go:42-77``
ResponseError semantics) instead of reaching the client.

Here the same contract is enforced with the declarative ``spec``
engine: the gateway validates the FRONT-schema body it is about to
re-emit — non-streaming bodies 502 on violation; streamed events
surface the stream-error event and stop the relay. Unknown fields pass
(providers add fields weekly; the reference's Go structs likewise
ignore unknown keys), but known fields must carry the right shapes.

Discriminated unions (Responses API output items, Anthropic stream
events) validate known ``type`` values deeply and let unknown type
strings pass — forward compatibility with the same posture as the
request-side vendor-field contract.
"""

from __future__ import annotations

from typing import Any

from aigw_tpu.schemas.openai import SchemaError
from aigw_tpu.schemas.spec import Field, Spec, validate_object
from aigw_tpu.translate.base import Endpoint

# ---------------------------------------------------------------------------
# shared pieces

_USAGE = Field(type="object", spec=Spec(fields={
    "prompt_tokens": Field(type="integer", ge=0),
    "completion_tokens": Field(type="integer", ge=0),
    "total_tokens": Field(type="integer", ge=0),
}))

# finish_reason: typed as a string, NOT an enum. OpenAI-compatible
# upstreams legitimately emit values beyond the canonical five
# ("recitation", "error", "safety", vendor extensions …); rejecting
# them 502'd valid non-stream bodies and aborted live SSE streams
# (advisor finding, round 5). Shape is enforced; the value set is the
# upstream's — same forward-compat posture as unknown fields.
_FINISH = Field(type="string")

_TOOL_CALL = Field(type="object", spec=Spec(fields={
    "id": Field(type="string"),
    "type": Field(type="string"),
    "function": Field(type="object", spec=Spec(fields={
        "name": Field(type="string"),
        "arguments": Field(type="string"),
    })),
}))

_LOGPROBS = Field(type="object", spec=Spec(fields={
    "content": Field(type="array", item=Field(type="object", spec=Spec(
        fields={
            "token": Field(type="string", required=True),
            "logprob": Field(type="number", required=True),
            "top_logprobs": Field(type="array", item=Field(
                type="object", spec=Spec(fields={
                    "token": Field(type="string", required=True),
                    "logprob": Field(type="number", required=True),
                }))),
        }))),
}))

# ---------------------------------------------------------------------------
# /v1/chat/completions (ChatCompletionResponse, openai.go)

_CHAT_MESSAGE = Field(type="object", spec=Spec(fields={
    "role": Field(type="string"),
    "content": Field(type="string"),  # nullable (tool-call-only turns)
    "tool_calls": Field(type="array", item=_TOOL_CALL),
    "reasoning_content": Field(type="string"),
    "refusal": Field(type="string"),
}))

CHAT_RESPONSE = Spec(fields={
    "id": Field(type="string"),
    "object": Field(type="string"),
    "created": Field(type="integer"),
    "model": Field(type="string"),
    "choices": Field(type="array", required=True, item=Field(
        type="object", spec=Spec(fields={
            "index": Field(type="integer", ge=0),
            "message": Field(type="object", required=True,
                             spec=_CHAT_MESSAGE.spec),
            "finish_reason": _FINISH,
            "logprobs": _LOGPROBS,
        }))),
    "usage": _USAGE,
})

CHAT_CHUNK = Spec(fields={
    "id": Field(type="string"),
    "object": Field(type="string"),
    "created": Field(type="integer"),
    "model": Field(type="string"),
    "choices": Field(type="array", required=True, item=Field(
        type="object", spec=Spec(fields={
            "index": Field(type="integer", ge=0),
            # optional: some upstreams send a final finish_reason-only
            # chunk with no delta at all — that chunk must not kill the
            # stream (advisor finding, round 5)
            "delta": Field(type="object", spec=Spec(
                fields={
                    "role": Field(type="string"),
                    "content": Field(type="string"),
                    "tool_calls": Field(type="array", item=Field(
                        type="object", spec=Spec(fields={
                            "index": Field(type="integer"),
                            "id": Field(type="string"),
                            "type": Field(type="string"),
                            # deep (ISSUE 9): tpuserve streams native
                            # tool_calls deltas — name frames and
                            # incremental arguments-string frames must
                            # carry string payloads when present
                            "function": Field(type="object", spec=Spec(
                                fields={
                                    "name": Field(type="string"),
                                    "arguments": Field(type="string"),
                                })),
                        }))),
                })),
            "finish_reason": _FINISH,
            "logprobs": _LOGPROBS,
        }))),
    # usage-only final chunks carry an empty choices list — the spec
    # requires the key, not a minimum length
    "usage": _USAGE,
})

# ---------------------------------------------------------------------------
# /v1/completions

_COMPLETION_CHOICE = Field(type="object", spec=Spec(fields={
    "text": Field(type="string", required=True, nullable=False),
    "index": Field(type="integer", ge=0),
    "finish_reason": _FINISH,
    "logprobs": Field(type="object"),
}))

COMPLETIONS_RESPONSE = Spec(fields={
    "id": Field(type="string"),
    "object": Field(type="string"),
    "created": Field(type="integer"),
    "model": Field(type="string"),
    "choices": Field(type="array", required=True,
                     item=_COMPLETION_CHOICE),
    "usage": _USAGE,
})

# streamed completions chunks share the response shape
COMPLETIONS_CHUNK = COMPLETIONS_RESPONSE

# ---------------------------------------------------------------------------
# /v1/embeddings (EmbeddingResponse: data[].embedding is float array or
# base64 string depending on encoding_format)

EMBEDDINGS_RESPONSE = Spec(fields={
    "object": Field(type="string"),
    "model": Field(type="string"),
    "data": Field(type="array", required=True, item=Field(
        type="object", spec=Spec(fields={
            "object": Field(type="string"),
            "index": Field(type="integer", ge=0),
            "embedding": Field(required=True, nullable=False, union=(
                Field(type="array", item=Field(type="number")),
                Field(type="string", min_len=1),  # base64
            )),
        }))),
    "usage": Field(type="object", spec=Spec(fields={
        "prompt_tokens": Field(type="integer", ge=0),
        "total_tokens": Field(type="integer", ge=0),
    })),
})

# ---------------------------------------------------------------------------
# /v2/rerank (cohere rerank_v2 response)

RERANK_RESPONSE = Spec(fields={
    "id": Field(type="string"),
    "results": Field(type="array", required=True, item=Field(
        type="object", spec=Spec(fields={
            "index": Field(type="integer", required=True, ge=0,
                           nullable=False),
            "relevance_score": Field(type="number", required=True,
                                     nullable=False),
            "document": Field(union=(
                Field(type="string"),
                Field(type="object", spec=Spec(fields={
                    "text": Field(type="string"),
                })),
            )),
        }))),
    "meta": Field(type="object"),
})

# ---------------------------------------------------------------------------
# /v1/images/generations


def _check_image_item(value: dict, path: str) -> None:
    if "url" not in value and "b64_json" not in value:
        raise SchemaError(f"{path}: must carry url or b64_json")


IMAGES_RESPONSE = Spec(fields={
    "created": Field(type="integer"),
    "data": Field(type="array", required=True, item=Field(
        type="object", check=_check_image_item, spec=Spec(fields={
            "url": Field(type="string"),
            "b64_json": Field(type="string"),
            "revised_prompt": Field(type="string"),
        }))),
    "usage": Field(type="object"),
})

# ---------------------------------------------------------------------------
# /tokenize (vLLM-compatible)

TOKENIZE_RESPONSE = Spec(fields={
    "count": Field(type="integer", required=True, ge=0, nullable=False),
    "tokens": Field(type="array", item=Field(type="integer")),
    "max_model_len": Field(type="integer"),
})

# ---------------------------------------------------------------------------
# /v1/messages (Anthropic front door; anthropic.go Messages response)

_ANTHROPIC_CONTENT_BLOCKS: dict[str, Spec] = {
    "text": Spec(fields={
        "text": Field(type="string", required=True, nullable=False)}),
    "thinking": Spec(fields={
        "thinking": Field(type="string", required=True),
        "signature": Field(type="string"),
    }),
    "redacted_thinking": Spec(fields={
        "data": Field(type="string", required=True)}),
    "tool_use": Spec(fields={
        "id": Field(type="string", required=True),
        "name": Field(type="string", required=True),
        "input": Field(type="object", required=True, nullable=False),
    }),
    "server_tool_use": Spec(fields={
        "id": Field(type="string"),
        "name": Field(type="string"),
        "input": Field(type="object"),
    }),
}


def _check_anthropic_block(value: dict, path: str) -> None:
    t = value.get("type")
    if not isinstance(t, str) or not t:
        raise SchemaError(f"{path}.type: is required")
    spec = _ANTHROPIC_CONTENT_BLOCKS.get(t)
    if spec is not None:
        validate_object(value, spec, path)


MESSAGES_RESPONSE = Spec(fields={
    "id": Field(type="string"),
    "type": Field(type="string"),
    "role": Field(type="string"),
    "model": Field(type="string"),
    "content": Field(type="array", required=True, item=Field(
        type="object", check=_check_anthropic_block)),
    "stop_reason": Field(type="string"),
    "stop_sequence": Field(type="string"),
    "usage": Field(type="object", spec=Spec(fields={
        "input_tokens": Field(type="integer", ge=0),
        "output_tokens": Field(type="integer", ge=0),
    })),
})

#: Anthropic stream events, discriminated on "type" (anthropic.go
#: stream event types; unknown types pass — the event set grows)
_ANTHROPIC_EVENTS: dict[str, Spec] = {
    "message_start": Spec(fields={
        "message": Field(type="object", required=True, nullable=False)}),
    "content_block_start": Spec(fields={
        "index": Field(type="integer", required=True, ge=0,
                       nullable=False),
        "content_block": Field(type="object", required=True,
                               nullable=False),
    }),
    "content_block_delta": Spec(fields={
        "index": Field(type="integer", required=True, ge=0,
                       nullable=False),
        "delta": Field(type="object", required=True, nullable=False),
    }),
    "content_block_stop": Spec(fields={
        "index": Field(type="integer", required=True, ge=0,
                       nullable=False)}),
    "message_delta": Spec(fields={
        "delta": Field(type="object", required=True, nullable=False),
        "usage": Field(type="object"),
    }),
    "message_stop": Spec(),
    "ping": Spec(),
    "error": Spec(fields={
        "error": Field(type="object", required=True, nullable=False)}),
}

# ---------------------------------------------------------------------------
# /v1/responses — DEEP (r4 verdict: the request spec was "typed
# shallowly"; the response side covers the output item unions)

_RESPONSES_OUTPUT_ITEMS: dict[str, Spec] = {
    "message": Spec(fields={
        "id": Field(type="string"),
        "role": Field(type="string"),
        "status": Field(type="string"),
        "content": Field(type="array", required=True, item=Field(
            type="object", check=lambda v, p: _check_output_content(v, p))),
    }),
    "function_call": Spec(fields={
        "id": Field(type="string"),
        "call_id": Field(type="string", required=True, nullable=False),
        "name": Field(type="string", required=True, nullable=False),
        "arguments": Field(type="string", required=True, nullable=False),
        "status": Field(type="string"),
    }),
    "reasoning": Spec(fields={
        "id": Field(type="string"),
        "summary": Field(type="array", required=True, item=Field(
            type="object", spec=Spec(fields={
                "type": Field(type="string", required=True),
                "text": Field(type="string"),
            }))),
        "encrypted_content": Field(type="string"),
        "status": Field(type="string"),
    }),
    "web_search_call": Spec(fields={
        "id": Field(type="string"),
        "status": Field(type="string"),
    }),
    "file_search_call": Spec(fields={
        "id": Field(type="string"),
        "status": Field(type="string"),
    }),
}

_RESPONSES_OUTPUT_CONTENT: dict[str, Spec] = {
    "output_text": Spec(fields={
        "text": Field(type="string", required=True, nullable=False),
        "annotations": Field(type="array"),
    }),
    "refusal": Spec(fields={
        "refusal": Field(type="string", required=True, nullable=False),
    }),
}


def _check_output_content(value: dict, path: str) -> None:
    t = value.get("type")
    if not isinstance(t, str) or not t:
        raise SchemaError(f"{path}.type: is required")
    spec = _RESPONSES_OUTPUT_CONTENT.get(t)
    if spec is not None:
        validate_object(value, spec, path)


def _check_output_item(value: dict, path: str) -> None:
    t = value.get("type")
    if not isinstance(t, str) or not t:
        raise SchemaError(f"{path}.type: is required")
    spec = _RESPONSES_OUTPUT_ITEMS.get(t)
    if spec is not None:
        validate_object(value, spec, path)


RESPONSES_RESPONSE = Spec(fields={
    "id": Field(type="string", required=True, nullable=False),
    "object": Field(type="string"),
    "created_at": Field(type="number"),
    "status": Field(type="string", enum=(
        "completed", "failed", "in_progress", "cancelled", "queued",
        "incomplete")),
    "error": Field(type="object", spec=Spec(fields={
        "code": Field(type="string"),
        "message": Field(type="string"),
    })),
    "incomplete_details": Field(type="object"),
    "model": Field(type="string"),
    "output": Field(type="array", required=True, item=Field(
        type="object", check=_check_output_item)),
    "previous_response_id": Field(type="string"),
    "usage": Field(type="object", spec=Spec(fields={
        "input_tokens": Field(type="integer", ge=0),
        "output_tokens": Field(type="integer", ge=0),
        "total_tokens": Field(type="integer", ge=0),
        "input_tokens_details": Field(type="object"),
        "output_tokens_details": Field(type="object"),
    })),
})

#: Responses stream events: {type: "response.*", ...}. The envelope is
#: validated for every event; payloads deeply for the high-traffic ones.
_RESPONSES_EVENTS: dict[str, Spec] = {
    "response.output_text.delta": Spec(fields={
        "delta": Field(type="string", required=True, nullable=False),
        "item_id": Field(type="string"),
        "output_index": Field(type="integer", ge=0),
        "content_index": Field(type="integer", ge=0),
    }),
    "response.function_call_arguments.delta": Spec(fields={
        "delta": Field(type="string", required=True, nullable=False),
        "item_id": Field(type="string"),
        "output_index": Field(type="integer", ge=0),
    }),
    "response.created": Spec(fields={
        "response": Field(type="object", required=True, nullable=False)}),
    "response.in_progress": Spec(fields={
        "response": Field(type="object", required=True, nullable=False)}),
    "response.completed": Spec(fields={
        "response": Field(type="object", required=True, nullable=False,
                          spec=RESPONSES_RESPONSE)}),
    "response.output_item.added": Spec(fields={
        "output_index": Field(type="integer", ge=0),
        "item": Field(type="object", required=True, nullable=False,
                      check=_check_output_item),
    }),
    "response.output_item.done": Spec(fields={
        "output_index": Field(type="integer", ge=0),
        "item": Field(type="object", required=True, nullable=False,
                      check=_check_output_item),
    }),
}

# ---------------------------------------------------------------------------
# dispatch

_BY_ENDPOINT: dict[Endpoint, Spec] = {
    Endpoint.CHAT_COMPLETIONS: CHAT_RESPONSE,
    Endpoint.COMPLETIONS: COMPLETIONS_RESPONSE,
    Endpoint.EMBEDDINGS: EMBEDDINGS_RESPONSE,
    Endpoint.RERANK: RERANK_RESPONSE,
    Endpoint.IMAGES_GENERATIONS: IMAGES_RESPONSE,
    Endpoint.TOKENIZE: TOKENIZE_RESPONSE,
    Endpoint.MESSAGES: MESSAGES_RESPONSE,
    Endpoint.RESPONSES: RESPONSES_RESPONSE,
}

_CHUNK_BY_ENDPOINT: dict[Endpoint, Spec] = {
    Endpoint.CHAT_COMPLETIONS: CHAT_CHUNK,
    Endpoint.COMPLETIONS: COMPLETIONS_CHUNK,
}


def has_spec(endpoint: Endpoint) -> bool:
    """True when the endpoint's non-stream response is JSON-typed (audio
    bytes and multipart endpoints are not)."""
    return endpoint in _BY_ENDPOINT


def has_stream_spec(endpoint: Endpoint) -> bool:
    return (endpoint in _CHUNK_BY_ENDPOINT
            or endpoint in (Endpoint.MESSAGES, Endpoint.RESPONSES))


def validate_response(endpoint: Endpoint, body: Any) -> None:
    """Validate a non-streaming front-schema response body the gateway
    is about to re-emit; raises SchemaError (→ 502 upstream_error) on
    violation. Endpoints without a registered spec pass (audio bytes,
    multipart)."""
    spec = _BY_ENDPOINT.get(endpoint)
    if spec is not None:
        validate_object(body, spec)


def validate_stream_event(endpoint: Endpoint, event: Any) -> None:
    """Validate one parsed SSE event for a streaming response.

    - chat/completions: every chunk against the chunk spec
    - /v1/messages: discriminated Anthropic event types
    - /v1/responses: ``response.*`` envelope + deep payloads for the
      delta/item/completed events
    Raises SchemaError; the relay surfaces the stream-error event."""
    if endpoint in _CHUNK_BY_ENDPOINT:
        validate_object(event, _CHUNK_BY_ENDPOINT[endpoint])
        return
    if endpoint is Endpoint.MESSAGES:
        if not isinstance(event, dict):
            raise SchemaError("stream event must be object")
        t = event.get("type")
        if not isinstance(t, str) or not t:
            raise SchemaError("type: is required")
        spec = _ANTHROPIC_EVENTS.get(t)
        if spec is not None:
            validate_object(event, spec)
        return
    if endpoint is Endpoint.RESPONSES:
        if not isinstance(event, dict):
            raise SchemaError("stream event must be object")
        t = event.get("type")
        if not isinstance(t, str) or not t:
            raise SchemaError("type: is required")
        spec = _RESPONSES_EVENTS.get(t)
        if spec is not None:
            validate_object(event, spec)
