"""Declarative typed-schema engine for request validation.

The reference gateway carries a fully-typed API schema layer
(reference internal/apischema/openai/openai.go — ~8.8k lines of Go
structs with union (un)marshalling) so malformed bodies are rejected at
the gateway, before any upstream traffic. Go needs a struct per shape;
the idiomatic Python equivalent is a small declarative spec language —
each endpoint's request type is written as a ``Spec`` of ``Field``
declarations (type, bounds, enum, nesting, unions) and validated
structurally. Strictness is per-field, not whole-body: unknown fields
pass through untouched (the reference marshals through typed structs
but deliberately re-attaches vendor-specific fields — proposal
docs/proposals/004-vendor-specific-fields/ — and backends accept
superset bodies; rejecting unknowns would break that contract).

Errors carry a JSON-path-ish location (``messages[2].content``) the way
the reference's unmarshal errors name the offending field.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from aigw_tpu.schemas.openai import SchemaError

#: sentinel distinguishing "absent" from "present as null"
_MISSING = object()

# type atoms. "number" accepts int+float (JSON number), "integer" only
# int (bool is excluded from both — json booleans must not pass as 1/0).
_ATOMS: dict[str, Callable[[Any], bool]] = {
    "string": lambda v: isinstance(v, str),
    "integer": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "number": lambda v: isinstance(v, (int, float))
    and not isinstance(v, bool),
    "boolean": lambda v: isinstance(v, bool),
    "object": lambda v: isinstance(v, dict),
    "array": lambda v: isinstance(v, list),
    "null": lambda v: v is None,
    "any": lambda v: True,
}


@dataclass(frozen=True)
class Field:
    """One field of a request object."""

    type: str = "any"  # atom name, or "array"/"object" with item/spec
    required: bool = False
    nullable: bool = True  # explicit null allowed for optional fields?
    enum: tuple[Any, ...] | None = None
    ge: float | None = None
    le: float | None = None
    min_len: int | None = None
    max_len: int | None = None
    item: "Field | None" = None  # array element type
    spec: "Spec | None" = None  # nested object spec
    union: tuple["Field", ...] | None = None  # any-of alternatives
    check: Callable[[Any, str], None] | None = None  # custom hook


@dataclass(frozen=True)
class Spec:
    """An object schema: named fields + cross-field checks."""

    fields: dict[str, Field] = field(default_factory=dict)
    checks: tuple[Callable[[dict, str], None], ...] = ()


def _fail(path: str, msg: str) -> None:
    raise SchemaError(f"{path}: {msg}" if path else msg)


def _type_name(v: Any) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, list):
        return "array"
    if isinstance(v, dict):
        return "object"
    return type(v).__name__


def _validate_field(value: Any, f: Field, path: str) -> None:
    if value is None:
        if f.nullable and not f.required:
            return
        _fail(path, "must not be null")
    if f.union is not None:
        errors = []
        for alt in f.union:
            try:
                _validate_field(value, alt, path)
                break
            except SchemaError as e:
                errors.append(str(e))
        else:
            # prefer the alternative that matched deepest (longest error
            # path) — for `input: [{"title": "x"}]` that is the object
            # form's "input[0].content: is required", not the flat
            # "must be string" of the scalar forms
            deepest = max(errors, key=lambda e: len(e.split(": ", 1)[0]))
            if deepest.split(": ", 1)[0] != path:
                raise SchemaError(deepest)
            _fail(path, "matched no allowed form (" + "; ".join(
                e.split(": ", 1)[-1] for e in errors[:4]) + ")")
        return
    atom = _ATOMS.get(f.type)
    if atom is None:
        raise RuntimeError(f"unknown field type {f.type!r} in spec")
    if not atom(value):
        _fail(path, f"must be {f.type}, got {_type_name(value)}")
    if f.enum is not None and value not in f.enum:
        _fail(path, f"must be one of {sorted(map(str, f.enum))}, "
                    f"got {value!r}")
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        if f.ge is not None and value < f.ge:
            _fail(path, f"must be >= {f.ge}")
        if f.le is not None and value > f.le:
            _fail(path, f"must be <= {f.le}")
    if isinstance(value, (str, list, dict)):
        if f.min_len is not None and len(value) < f.min_len:
            _fail(path, f"must have at least {f.min_len} "
                        f"{'characters' if isinstance(value, str) else 'items'}")
        if f.max_len is not None and len(value) > f.max_len:
            _fail(path, f"must have at most {f.max_len} "
                        f"{'characters' if isinstance(value, str) else 'items'}")
    if isinstance(value, list) and f.item is not None:
        for i, v in enumerate(value):
            _validate_field(v, f.item, f"{path}[{i}]")
    if isinstance(value, dict) and f.spec is not None:
        validate_object(value, f.spec, path)
    if f.check is not None:
        f.check(value, path)


def validate_object(body: Any, spec: Spec, path: str = "") -> None:
    """Validate ``body`` against ``spec``; raises SchemaError on the
    first violation. Unknown fields are ignored (vendor passthrough)."""
    if not isinstance(body, dict):
        _fail(path, f"must be object, got {_type_name(body)}")
    for name, f in spec.fields.items():
        sub = f"{path}.{name}" if path else name
        value = body.get(name, _MISSING)
        if value is _MISSING:
            if f.required:
                _fail(sub, "is required")
            continue
        if value is None and f.required:
            _fail(sub, "must not be null")
        _validate_field(value, f, sub)
    for check in spec.checks:
        check(body, path)
