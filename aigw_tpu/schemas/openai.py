"""OpenAI API schema helpers (reference internal/apischema/openai/openai.go).

Covers the endpoint surface the gateway fronts: chat completions (incl.
streaming chunks and tool calls), legacy completions, embeddings, models
list, tokenize (vLLM-compatible), plus error bodies and usage extraction.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Iterable

from aigw_tpu.gateway.costs import TokenUsage


class SchemaError(ValueError):
    """Client-facing 400: malformed request body."""


# ---------------------------------------------------------------------------
# Requests
# ---------------------------------------------------------------------------


def parse_json_body(body: bytes) -> dict[str, Any]:
    try:
        data = json.loads(body)
    except json.JSONDecodeError as e:
        raise SchemaError(f"invalid JSON body: {e}") from None
    if not isinstance(data, dict):
        raise SchemaError("request body must be a JSON object")
    return data


def request_model(body: dict[str, Any]) -> str:
    model = body.get("model")
    if not isinstance(model, str) or not model:
        raise SchemaError("missing required field: model")
    return model


def request_stream(body: dict[str, Any]) -> bool:
    return bool(body.get("stream", False))


def include_stream_usage(body: dict[str, Any]) -> bool:
    opts = body.get("stream_options") or {}
    return bool(opts.get("include_usage", False))


def message_content_text(content: Any) -> str:
    """Flatten the string-or-parts content union to text
    (the union type the reference custom-unmarshals, openai.go)."""
    if content is None:
        return ""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        out = []
        for part in content:
            if isinstance(part, dict) and part.get("type") == "text":
                out.append(str(part.get("text", "")))
        return "".join(out)
    raise SchemaError(f"invalid message content type {type(content).__name__}")


def validate_chat_request(body: dict[str, Any]) -> None:
    request_model(body)
    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        raise SchemaError("messages must be a non-empty array")
    for i, m in enumerate(messages):
        if not isinstance(m, dict):
            raise SchemaError(f"messages[{i}] must be an object")
        role = m.get("role")
        if role not in ("system", "developer", "user", "assistant", "tool"):
            raise SchemaError(f"messages[{i}] has invalid role {role!r}")


# ---------------------------------------------------------------------------
# Responses
# ---------------------------------------------------------------------------


def extract_usage(body: dict[str, Any]) -> TokenUsage:
    """OpenAI usage object → TokenUsage (incl. details fields)."""
    u = body.get("usage")
    if not isinstance(u, dict):
        return TokenUsage()
    prompt_details = u.get("prompt_tokens_details") or {}
    completion_details = u.get("completion_tokens_details") or {}
    return TokenUsage(
        input_tokens=int(u.get("prompt_tokens", 0) or 0),
        output_tokens=int(u.get("completion_tokens", 0) or 0),
        total_tokens=int(u.get("total_tokens", 0) or 0),
        cached_input_tokens=int(prompt_details.get("cached_tokens", 0) or 0),
        reasoning_tokens=int(completion_details.get("reasoning_tokens", 0) or 0),
    )


def usage_dict(usage: TokenUsage) -> dict[str, Any]:
    d: dict[str, Any] = {
        "prompt_tokens": usage.input_tokens,
        "completion_tokens": usage.output_tokens,
        "total_tokens": usage.total_tokens
        or usage.input_tokens + usage.output_tokens,
    }
    if usage.cached_input_tokens:
        d["prompt_tokens_details"] = {"cached_tokens": usage.cached_input_tokens}
    if usage.reasoning_tokens:
        d["completion_tokens_details"] = {
            "reasoning_tokens": usage.reasoning_tokens
        }
    return d


def chat_completion_response(
    *,
    model: str,
    content: str,
    finish_reason: str = "stop",
    usage: TokenUsage | None = None,
    tool_calls: list[dict[str, Any]] | None = None,
    response_id: str = "",
) -> dict[str, Any]:
    message: dict[str, Any] = {"role": "assistant", "content": content}
    if tool_calls:
        message["tool_calls"] = tool_calls
        if not content:
            message["content"] = None
    return {
        "id": response_id or f"chatcmpl-{uuid.uuid4().hex[:24]}",
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {"index": 0, "message": message, "finish_reason": finish_reason}
        ],
        "usage": usage_dict(usage or TokenUsage()),
    }


def chat_completion_chunk(
    *,
    response_id: str,
    model: str,
    delta: dict[str, Any] | None = None,
    finish_reason: str | None = None,
    usage: TokenUsage | None = None,
    created: int = 0,
) -> dict[str, Any]:
    chunk: dict[str, Any] = {
        "id": response_id,
        "object": "chat.completion.chunk",
        "created": created or int(time.time()),
        "model": model,
        "choices": [],
    }
    if delta is not None or finish_reason is not None:
        chunk["choices"] = [
            {
                "index": 0,
                "delta": delta if delta is not None else {},
                "finish_reason": finish_reason,
            }
        ]
    if usage is not None:
        chunk["usage"] = usage_dict(usage)
    return chunk


def stream_chunk_sse(
    *,
    response_id: str,
    model: str,
    created: int,
    delta: dict[str, Any] | None = None,
    finish_reason: str | None = None,
    usage: TokenUsage | None = None,
) -> bytes:
    """One chat.completion.chunk encoded as an SSE event — the shared
    emitter for every cross-schema streaming translator."""
    from aigw_tpu.translate.sse import SSEEvent

    return SSEEvent(
        data=json.dumps(
            chat_completion_chunk(
                response_id=response_id,
                model=model,
                delta=delta,
                finish_reason=finish_reason,
                usage=usage,
                created=created,
            )
        )
    ).encode()


def embeddings_response(
    *, model: str, vectors: Iterable[list[float]], usage: TokenUsage
) -> dict[str, Any]:
    return {
        "object": "list",
        "model": model,
        "data": [
            {"object": "embedding", "index": i, "embedding": v}
            for i, v in enumerate(vectors)
        ],
        "usage": {
            "prompt_tokens": usage.input_tokens,
            "total_tokens": usage.total_tokens or usage.input_tokens,
        },
    }


def models_response(models: Iterable[tuple[str, str, int]]) -> dict[str, Any]:
    """(name, owned_by, created) triples → /v1/models body."""
    return {
        "object": "list",
        "data": [
            {
                "id": name,
                "object": "model",
                "created": created or int(time.time()),
                "owned_by": owned_by,
            }
            for name, owned_by, created in models
        ],
    }


def error_body(message: str, type_: str = "invalid_request_error", code: Any = None) -> bytes:
    """OpenAI-format error envelope. The gateway wraps upstream errors the
    same way the reference does (internalapi user-facing error wrapper)."""
    return json.dumps(
        {"error": {"message": message, "type": type_, "code": code}}
    ).encode()
