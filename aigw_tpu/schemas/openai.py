"""OpenAI API schema helpers (reference internal/apischema/openai/openai.go).

Covers the endpoint surface the gateway fronts: chat completions (incl.
streaming chunks and tool calls), legacy completions, embeddings, models
list, tokenize (vLLM-compatible), plus error bodies and usage extraction.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Iterable

from aigw_tpu.gateway.costs import TokenUsage, meter_to_tuple


class SchemaError(ValueError):
    """Client-facing 400: malformed request body."""

    status = 400


class NotFoundError(SchemaError):
    """Client-facing 404: a referenced resource doesn't exist (e.g. an
    unknown ``previous_response_id`` — OpenAI returns 404 for these,
    and SDK retry logic branches on 404 vs 400)."""

    status = 404


# ---------------------------------------------------------------------------
# Requests
# ---------------------------------------------------------------------------


def parse_json_body(body: bytes) -> dict[str, Any]:
    try:
        data = json.loads(body)
    except json.JSONDecodeError as e:
        raise SchemaError(f"invalid JSON body: {e}") from None
    if not isinstance(data, dict):
        raise SchemaError("request body must be a JSON object")
    return data


def request_model(body: dict[str, Any]) -> str:
    model = body.get("model")
    if not isinstance(model, str) or not model:
        raise SchemaError("missing required field: model")
    return model


def request_stream(body: dict[str, Any]) -> bool:
    return bool(body.get("stream", False))


def include_stream_usage(body: dict[str, Any]) -> bool:
    opts = body.get("stream_options") or {}
    return bool(opts.get("include_usage", False))


def message_content_text(content: Any) -> str:
    """Flatten the string-or-parts content union to text
    (the union type the reference custom-unmarshals, openai.go)."""
    if content is None:
        return ""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        out = []
        for part in content:
            if isinstance(part, dict) and part.get("type") == "text":
                out.append(str(part.get("text", "")))
        return "".join(out)
    raise SchemaError(f"invalid message content type {type(content).__name__}")


#: content-part types accepted in user messages (reference openai.go
#: ChatCompletionContentPartUnionParam)
_USER_CONTENT_PART_TYPES = ("text", "image_url", "input_audio", "file")


def _validate_content(i: int, role: str, content: Any) -> None:
    if content is None or isinstance(content, str):
        return
    if not isinstance(content, list):
        raise SchemaError(
            f"messages[{i}].content must be a string or an array of "
            f"content parts, got {type(content).__name__}")
    for j, part in enumerate(content):
        if not isinstance(part, dict):
            raise SchemaError(
                f"messages[{i}].content[{j}] must be an object")
        ptype = part.get("type")
        if role == "user":
            if ptype not in _USER_CONTENT_PART_TYPES:
                raise SchemaError(
                    f"messages[{i}].content[{j}] has invalid type "
                    f"{ptype!r}")
            if ptype == "text" and not isinstance(part.get("text"), str):
                raise SchemaError(
                    f"messages[{i}].content[{j}].text must be a string")
            if ptype == "image_url" and not isinstance(
                    part.get("image_url"), dict):
                raise SchemaError(
                    f"messages[{i}].content[{j}].image_url must be an "
                    "object")
        else:  # assistant/system/developer/tool: text, plus assistant
            # refusal and replayed thinking/redacted_thinking parts
            # (openai.go:602-612 assistant content types; clients echo
            # thinking blocks from a previous turn)
            if ptype == "refusal" and role == "assistant":
                if not isinstance(part.get("refusal"), str):
                    raise SchemaError(
                        f"messages[{i}].content[{j}].refusal must be a "
                        "string")
                continue
            if ptype == "thinking" and role == "assistant":
                text = part.get("text", part.get("thinking"))
                if not isinstance(text, str):
                    raise SchemaError(
                        f"messages[{i}].content[{j}] thinking parts "
                        "need a string text (or thinking) field")
                sig = part.get("signature")
                if sig is not None and not isinstance(sig, str):
                    raise SchemaError(
                        f"messages[{i}].content[{j}].signature must be "
                        "a string")
                continue
            if ptype == "redacted_thinking" and role == "assistant":
                data = part.get("redactedContent", part.get("data"))
                if not isinstance(data, str):
                    raise SchemaError(
                        f"messages[{i}].content[{j}] redacted_thinking "
                        "parts need a string redactedContent (or data) "
                        "field")
                continue
            if ptype != "text":
                raise SchemaError(
                    f"messages[{i}].content[{j}] has invalid type "
                    f"{ptype!r} for role {role!r}")
            if not isinstance(part.get("text"), str):
                raise SchemaError(
                    f"messages[{i}].content[{j}].text must be a string")


def _validate_tool_calls(i: int, tool_calls: Any) -> None:
    if tool_calls is None:
        return
    if not isinstance(tool_calls, list):
        raise SchemaError(f"messages[{i}].tool_calls must be an array")
    for j, tc in enumerate(tool_calls):
        if not isinstance(tc, dict):
            raise SchemaError(
                f"messages[{i}].tool_calls[{j}] must be an object")
        ttype = tc.get("type")
        if ttype == "custom":
            cu = tc.get("custom")
            if not isinstance(cu, dict) or not isinstance(
                    cu.get("name"), str):
                raise SchemaError(
                    f"messages[{i}].tool_calls[{j}].custom.name is "
                    "required")
            continue
        if ttype != "function":
            raise SchemaError(
                f"messages[{i}].tool_calls[{j}].type must be 'function' "
                "or 'custom'")
        fn = tc.get("function")
        if not isinstance(fn, dict) or not isinstance(fn.get("name"), str):
            raise SchemaError(
                f"messages[{i}].tool_calls[{j}].function.name is required")
        args = fn.get("arguments")
        if args is not None and not isinstance(args, str):
            raise SchemaError(
                f"messages[{i}].tool_calls[{j}].function.arguments must "
                "be a string")


def _validate_tools(body: dict[str, Any]) -> None:
    tools = body.get("tools")
    if tools is None:
        return
    if not isinstance(tools, list):
        raise SchemaError("tools must be an array")
    for i, t in enumerate(tools):
        if not isinstance(t, dict):
            raise SchemaError(f"tools[{i}] must be an object")
        ttype = t.get("type")
        # the reference's ToolType enum (openai.go:1223-1230): built-in
        # Gemini tools ride the same list; translators decide support
        if ttype in ("google_search", "enterprise_search",
                     "image_generation"):
            gs = t.get("google_search")
            if gs is not None:
                if not isinstance(gs, dict):
                    raise SchemaError(
                        f"tools[{i}].google_search must be an object")
                ed = gs.get("exclude_domains")
                if ed is not None and (
                        not isinstance(ed, list)
                        or not all(isinstance(d, str) for d in ed)):
                    raise SchemaError(
                        f"tools[{i}].google_search.exclude_domains must "
                        "be an array of strings")
                for key in ("blocking_confidence",):
                    v = gs.get(key)
                    if v is not None and not isinstance(v, str):
                        raise SchemaError(
                            f"tools[{i}].google_search.{key} must be a "
                            "string")
                trf = gs.get("time_range_filter")
                if trf is not None and not isinstance(trf, dict):
                    raise SchemaError(
                        f"tools[{i}].google_search.time_range_filter "
                        "must be an object")
            continue
        if ttype != "function":
            raise SchemaError(
                f"tools[{i}].type must be 'function', 'google_search', "
                f"'enterprise_search' or 'image_generation', got "
                f"{ttype!r}")
        fn = t.get("function")
        if not isinstance(fn, dict):
            raise SchemaError(f"tools[{i}].function must be an object")
        if not isinstance(fn.get("name"), str) or not fn.get("name"):
            raise SchemaError(f"tools[{i}].function.name is required")
        params = fn.get("parameters")
        if params is not None and not isinstance(params, dict):
            raise SchemaError(
                f"tools[{i}].function.parameters must be an object")


def _validate_tool_choice(body: dict[str, Any]) -> None:
    choice = body.get("tool_choice")
    if choice is None:
        return
    if isinstance(choice, str):
        if choice not in ("none", "auto", "required"):
            raise SchemaError(
                f"tool_choice must be 'none', 'auto', 'required' or a "
                f"named-tool object, got {choice!r}")
        return
    if not isinstance(choice, dict):
        raise SchemaError("tool_choice must be a string or an object")
    if choice.get("type") != "function":
        raise SchemaError("tool_choice.type must be 'function'")
    fn = choice.get("function")
    if not isinstance(fn, dict) or not isinstance(fn.get("name"), str) \
            or not fn.get("name"):
        raise SchemaError("tool_choice.function.name is required")
    if body.get("tools") in (None, []):
        raise SchemaError(
            "tool_choice requires a non-empty tools array")


def _validate_stream_options(body: dict[str, Any]) -> None:
    opts = body.get("stream_options")
    if opts is None:
        return
    if not isinstance(opts, dict):
        raise SchemaError("stream_options must be an object")
    if not body.get("stream"):
        raise SchemaError(
            "stream_options is only allowed when stream is true")
    iu = opts.get("include_usage")
    if iu is not None and not isinstance(iu, bool):
        raise SchemaError("stream_options.include_usage must be a boolean")


def _validate_sampling_fields(body: dict[str, Any]) -> None:
    for key, lo, hi in (("temperature", 0.0, 2.0), ("top_p", 0.0, 1.0),
                        ("presence_penalty", -2.0, 2.0),
                        ("frequency_penalty", -2.0, 2.0)):
        v = body.get(key)
        if v is None:
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise SchemaError(f"{key} must be a number")
        if not (lo <= float(v) <= hi):
            raise SchemaError(f"{key} must be between {lo} and {hi}")
    n = body.get("n")
    if n is not None and (isinstance(n, bool) or not isinstance(n, int)
                          or n < 1):
        raise SchemaError("n must be a positive integer")
    lp = body.get("logprobs")
    if lp is not None and not isinstance(lp, bool):
        raise SchemaError("logprobs must be a boolean")
    tlp = body.get("top_logprobs")
    if tlp is not None:
        if isinstance(tlp, bool) or not isinstance(tlp, int) \
                or not (0 <= tlp <= 20):
            raise SchemaError("top_logprobs must be an integer in [0, 20]")
    stop = body.get("stop")
    if stop is not None and not isinstance(stop, str):
        if not isinstance(stop, list) or \
                any(not isinstance(s, str) for s in stop):
            raise SchemaError(
                "stop must be a string or an array of strings")


def validate_chat_request(body: dict[str, Any]) -> None:
    """Strict request validation at the edge (reference: typed unmarshal
    of apischema/openai ChatCompletionRequest 400s malformed bodies
    before any upstream traffic)."""
    request_model(body)
    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        raise SchemaError("messages must be a non-empty array")
    for i, m in enumerate(messages):
        if not isinstance(m, dict):
            raise SchemaError(f"messages[{i}] must be an object")
        role = m.get("role")
        if role not in ("system", "developer", "user", "assistant", "tool"):
            raise SchemaError(f"messages[{i}] has invalid role {role!r}")
        _validate_content(i, role, m.get("content"))
        if role == "assistant":
            _validate_tool_calls(i, m.get("tool_calls"))
        if role == "tool" and not isinstance(m.get("tool_call_id"), str):
            raise SchemaError(
                f"messages[{i}] with role 'tool' requires tool_call_id")
    _validate_tools(body)
    _validate_tool_choice(body)
    _validate_stream_options(body)
    _validate_sampling_fields(body)
    # response_format union (lazy import: translate package imports us)
    from aigw_tpu.translate.structured import (
        JSONSchemaError,
        parse_response_format,
    )

    try:
        parse_response_format(body)
    except JSONSchemaError as e:
        raise SchemaError(str(e)) from None


# ---------------------------------------------------------------------------
# Responses
# ---------------------------------------------------------------------------


def extract_usage(body: dict[str, Any]) -> TokenUsage:
    """OpenAI usage object → TokenUsage (incl. details fields).

    ``usage.aigw_meter`` is the engine-truth MeterRecord a tpuserve
    backend attaches to its stream tail; external providers never send
    it and the key passes typed validation as an unknown field.
    """
    u = body.get("usage")
    if not isinstance(u, dict):
        return TokenUsage()
    prompt_details = u.get("prompt_tokens_details") or {}
    completion_details = u.get("completion_tokens_details") or {}
    meter = u.get("aigw_meter")
    return TokenUsage(
        input_tokens=int(u.get("prompt_tokens", 0) or 0),
        output_tokens=int(u.get("completion_tokens", 0) or 0),
        total_tokens=int(u.get("total_tokens", 0) or 0),
        cached_input_tokens=int(prompt_details.get("cached_tokens", 0) or 0),
        reasoning_tokens=int(completion_details.get("reasoning_tokens", 0) or 0),
        meter=meter_to_tuple(meter) if isinstance(meter, dict) else (),
    )


def usage_dict(usage: TokenUsage) -> dict[str, Any]:
    d: dict[str, Any] = {
        "prompt_tokens": usage.input_tokens,
        "completion_tokens": usage.output_tokens,
        "total_tokens": usage.total_tokens
        or usage.input_tokens + usage.output_tokens,
    }
    if usage.cached_input_tokens:
        d["prompt_tokens_details"] = {"cached_tokens": usage.cached_input_tokens}
    if usage.reasoning_tokens:
        d["completion_tokens_details"] = {
            "reasoning_tokens": usage.reasoning_tokens
        }
    if usage.meter:
        d["aigw_meter"] = dict(usage.meter)
    return d


def chat_completion_response(
    *,
    model: str,
    content: str,
    finish_reason: str = "stop",
    usage: TokenUsage | None = None,
    tool_calls: list[dict[str, Any]] | None = None,
    response_id: str = "",
    reasoning_content: str = "",
    thinking_blocks: list[dict[str, Any]] | None = None,
) -> dict[str, Any]:
    message: dict[str, Any] = {"role": "assistant", "content": content}
    if tool_calls:
        message["tool_calls"] = tool_calls
        if not content:
            message["content"] = None
    # reasoning surfaces (reference: message.ReasoningContent union +
    # the LiteLLM thinking_blocks convention, openai.go:644-648 — the
    # blocks carry signatures so clients can replay them next turn)
    if reasoning_content:
        message["reasoning_content"] = reasoning_content
    if thinking_blocks:
        message["thinking_blocks"] = thinking_blocks
    return {
        "id": response_id or f"chatcmpl-{uuid.uuid4().hex[:24]}",
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {"index": 0, "message": message, "finish_reason": finish_reason}
        ],
        "usage": usage_dict(usage or TokenUsage()),
    }


def chat_completion_chunk(
    *,
    response_id: str,
    model: str,
    delta: dict[str, Any] | None = None,
    finish_reason: str | None = None,
    usage: TokenUsage | None = None,
    created: int = 0,
    logprobs: dict[str, Any] | None = None,
    index: int = 0,  # choice index (n>1 streaming interleaves choices)
) -> dict[str, Any]:
    chunk: dict[str, Any] = {
        "id": response_id,
        "object": "chat.completion.chunk",
        "created": created or int(time.time()),
        "model": model,
        "choices": [],
    }
    if delta is not None or finish_reason is not None:
        choice: dict[str, Any] = {
            "index": index,
            "delta": delta if delta is not None else {},
            "finish_reason": finish_reason,
        }
        if logprobs is not None:
            choice["logprobs"] = logprobs
        chunk["choices"] = [choice]
    if usage is not None:
        chunk["usage"] = usage_dict(usage)
    return chunk


def stream_chunk_sse(
    *,
    response_id: str,
    model: str,
    created: int,
    delta: dict[str, Any] | None = None,
    finish_reason: str | None = None,
    usage: TokenUsage | None = None,
    logprobs: dict[str, Any] | None = None,
    index: int = 0,
) -> bytes:
    """One chat.completion.chunk encoded as an SSE event — the shared
    emitter for every cross-schema streaming translator."""
    from aigw_tpu.translate.sse import SSEEvent

    return SSEEvent(
        data=json.dumps(
            chat_completion_chunk(
                response_id=response_id,
                model=model,
                delta=delta,
                finish_reason=finish_reason,
                usage=usage,
                created=created,
                logprobs=logprobs,
                index=index,
            )
        )
    ).encode()


def embeddings_response(
    *, model: str, vectors: Iterable[list[float]], usage: TokenUsage
) -> dict[str, Any]:
    return {
        "object": "list",
        "model": model,
        "data": [
            {"object": "embedding", "index": i, "embedding": v}
            for i, v in enumerate(vectors)
        ],
        "usage": {
            "prompt_tokens": usage.input_tokens,
            "total_tokens": usage.total_tokens or usage.input_tokens,
        },
    }


def models_response(models: Iterable[tuple]) -> dict[str, Any]:
    """(name, owned_by, created[, extra]) tuples → /v1/models body.
    ``extra`` (optional dict) merges into the entry — tpuserve uses it
    to advertise structured-output/tool capability flags (ISSUE 9)."""
    data = []
    for item in models:
        name, owned_by, created = item[0], item[1], item[2]
        entry: dict[str, Any] = {
            "id": name,
            "object": "model",
            "created": created or int(time.time()),
            "owned_by": owned_by,
        }
        if len(item) > 3 and item[3]:
            entry.update(item[3])
        data.append(entry)
    return {"object": "list", "data": data}


def error_body(message: str, type_: str = "invalid_request_error", code: Any = None) -> bytes:
    """OpenAI-format error envelope. The gateway wraps upstream errors the
    same way the reference does (internalapi user-facing error wrapper)."""
    return json.dumps(
        {"error": {"message": message, "type": type_, "code": code}}
    ).encode()
