"""Compile a gateway Config into the native proxy core's config JSON.

The C++ core (native/proxy_core.cpp, the reference's Envoy role —
SURVEY.md §2.8) natively serves the subset of routing it can express:
same-schema (OpenAI passthrough) backends over plain HTTP with static
header auth, model exact/prefix matching, weights and priority tiers,
header set/remove mutations, retry/failover. Everything else relays to
the Python gateway on the fallback address, which remains 100%
feature-complete.

Eligibility is decided here, conservatively, per route rule:

- backend schema must be OpenAI (the front schema — no translation),
  `url` must be plain http with an explicit or default port, no picker
  endpoint pools, no body mutations, no model override;
- auth must be static-header-expressible (none / APIKey / AzureAPIKey /
  AnthropicAPIKey); `file:` keys become `value_file` entries the core
  re-reads on mtime change (credential-rotator compatible);
- the rule may match on model exact/prefix only (arbitrary header
  matchers stay in Python);
- the config must have no global/route request costs and no quotas —
  those need per-request token accounting that lives in Python.

Order matters: the gateway evaluates rules first-match-wins, so only the
longest PREFIX of the rule sequence that is fully native-eligible is
compiled. The first non-eligible rule stops compilation — a model that
would have matched it can never be shadowed by a later native rule; the
core simply finds no match and falls back.

Native-path requests trade per-request observability (OTel spans, token
metrics, access-log usage fields) for throughput — the same tradeoff as
fronting any L7 proxy. The core exposes its own counters at
``/aigw-core/stats``.
"""

from __future__ import annotations

import json
from typing import Any
from urllib.parse import urlsplit

from aigw_tpu.config.model import (
    APISchemaName,
    AuthConfig,
    AuthKind,
    Backend,
    Config,
)

#: JSON POST endpoints the core may route natively (passthrough-safe).
NATIVE_ENDPOINTS = (
    "/v1/chat/completions",
    "/v1/completions",
    "/v1/embeddings",
)

_STATIC_AUTH_KINDS = (
    AuthKind.NONE,
    AuthKind.API_KEY,
    AuthKind.AZURE_API_KEY,
    AuthKind.ANTHROPIC_API_KEY,
)


class NotEligible(Exception):
    """Why a rule/backend can't go native (collected for the report)."""


def _auth_headers(auth: AuthConfig) -> list[dict[str, str]]:
    def entry(name: str, prefix: str, key: str) -> dict[str, str]:
        d: dict[str, str] = {"name": name, "prefix": prefix}
        if key.startswith("file:"):
            d["value_file"] = key[len("file:"):]
        else:
            d["value"] = key
        return d

    if auth.kind is AuthKind.NONE:
        return []
    if auth.kind is AuthKind.API_KEY:
        return [entry("authorization", "Bearer ", auth.api_key)]
    if auth.kind is AuthKind.AZURE_API_KEY:
        return [entry("api-key", "", auth.azure_api_key)]
    if auth.kind is AuthKind.ANTHROPIC_API_KEY:
        return [
            entry("x-api-key", "", auth.api_key),
            {"name": "anthropic-version", "prefix": "",
             "value": auth.anthropic_version},
        ]
    raise NotEligible(f"auth kind {auth.kind.value} needs request signing "
                      "or token refresh")


def _backend_entry(b: Backend, weight: int, priority: int) -> dict[str, Any]:
    if b.schema.name is not APISchemaName.OPENAI:
        raise NotEligible(f"backend {b.name!r}: schema "
                          f"{b.schema.name.value} needs translation")
    if b.endpoints:
        raise NotEligible(f"backend {b.name!r}: picker endpoint pool")
    if b.body_mutation.set or b.body_mutation.remove:
        raise NotEligible(f"backend {b.name!r}: body mutation")
    if b.model_name_override:
        raise NotEligible(f"backend {b.name!r}: model override")
    if b.auth.kind not in _STATIC_AUTH_KINDS:
        raise NotEligible(f"backend {b.name!r}: auth {b.auth.kind.value}")
    u = urlsplit(b.url)
    if u.scheme not in ("http", "https"):
        raise NotEligible(f"backend {b.name!r}: scheme {u.scheme or '??'}")
    tls = u.scheme == "https"
    if not u.hostname:
        raise NotEligible(f"backend {b.name!r}: no host in url")
    if u.path not in ("", "/"):
        # the core forwards the client path verbatim; a base-path prefix
        # would be silently dropped
        raise NotEligible(f"backend {b.name!r}: url path prefix "
                          f"{u.path!r}")
    if u.query or u.fragment:
        # same verbatim-path reason: ?api-version=... (Azure) would be
        # silently dropped by the core
        raise NotEligible(f"backend {b.name!r}: url carries query/fragment")
    if u.username or u.password:
        # the core dials hostname:port only; inline credentials would be
        # silently discarded and requests would reach the upstream unsigned
        raise NotEligible(f"backend {b.name!r}: url carries userinfo")
    entry: dict[str, Any] = {
        "name": b.name,
        "host": u.hostname,
        "port": u.port or (443 if tls else 80),
        "weight": weight,
        "priority": priority,
        "read_timeout_s": int(max(b.stream_idle_timeout, 1.0)),
    }
    if tls:
        # core dials TLS itself (dlopen'd libssl, verified, SNI =
        # hostname) — real external providers are native-eligible
        entry["tls"] = True
        entry["sni"] = u.hostname
    headers = _auth_headers(b.auth)
    if headers:
        entry["auth_headers"] = headers
    if b.header_mutation.set:
        entry["set_headers"] = [
            {"name": k, "value": v} for k, v in b.header_mutation.set
        ]
    if b.header_mutation.remove:
        entry["remove_headers"] = list(b.header_mutation.remove)
    return entry


def compile_core_config(
    cfg: Config,
    *,
    listen_host: str = "0.0.0.0",
    listen_port: int = 1975,
    fallback_host: str = "127.0.0.1",
    fallback_port: int = 1976,
    access_log_path: str = "",
) -> tuple[dict[str, Any], list[str]]:
    """Returns (core_config_dict, skipped_reasons).

    ``skipped_reasons`` explains every rule that stays on the Python
    path — surfaced by the CLI so operators see exactly what the native
    core accelerates.
    """
    skipped: list[str] = []
    rules: list[dict[str, Any]] = []
    blocked = False

    if cfg.llm_request_costs:
        if access_log_path:
            # costs are computed post-hoc by the gateway's access-log
            # tailer (obs/native_spans.py make_cost_fn) from the usage
            # the core mines off the response tail — cost-bearing rules
            # can go native when the log pipe exists
            skipped.append(
                "note: global llm_request_costs computed post-hoc from "
                "the native access log (AIGW_CORE_ACCESS_LOG on the "
                "gateway)")
        else:
            skipped.append(
                "global llm_request_costs need the access-log pipe for "
                "post-hoc accounting — pass --access-log and set "
                "AIGW_CORE_ACCESS_LOG on the gateway (python path for "
                "all rules)")
            blocked = True
    if cfg.quotas:
        # quotas ENFORCE at admission time (429 before the upstream
        # call); post-hoc accounting can't do that, so quota-bearing
        # configs stay on the Python path by design
        skipped.append("quotas need request-time admission "
                       "(python path for all rules)")
        blocked = True

    for route in cfg.routes:
        if blocked:
            break
        if route.llm_request_costs:
            skipped.append(f"route {route.name!r}: route-level costs "
                           "(stops native compilation here)")
            break
        for rule in route.rules:
            label = rule.name or route.name
            try:
                if rule.headers:
                    raise NotEligible("header matchers beyond model")
                if not rule.models and not rule.model_prefixes:
                    raise NotEligible("catch-all rule (no model match)")
                # weight 0 = drained (the python router filters them the
                # same way); a rule with every backend drained can't go
                # native — let python produce its error semantics
                backends = [
                    _backend_entry(cfg.backend(ref.backend), ref.weight,
                                   ref.priority)
                    for ref in rule.backends if ref.weight > 0
                ]
                if not backends:
                    raise NotEligible("all backends drained (weight 0)")
            except NotEligible as e:
                # first non-eligible rule ends compilation: later rules
                # must not shadow it (first-match-wins order)
                skipped.append(f"rule {label!r}: {e} "
                               "(stops native compilation here)")
                blocked = True
                break
            base = {"backends": backends}
            if route.hostnames:
                base["hostnames"] = list(route.hostnames)
            for m in rule.models:
                rules.append({**base, "model_exact": m})
            for p in rule.model_prefixes:
                rules.append({**base, "model_prefix": p})

    core = {
        "listen_host": listen_host,
        "listen_port": listen_port,
        "fallback_host": fallback_host,
        "fallback_port": fallback_port,
        "endpoints": list(NATIVE_ENDPOINTS),
        "rules": rules,
    }
    if access_log_path:
        core["access_log_path"] = access_log_path
    return core, skipped


def write_core_config(path: str, core: dict[str, Any]) -> None:
    with open(path, "w") as f:
        json.dump(core, f, indent=1)
        f.write("\n")
