"""ReferenceGrant enforcement for cross-namespace references.

Gateway-API semantics (reference
``internal/controller/referencegrant.go:21-180``): an AIGatewayRoute may
reference an AIServiceBackend or InferencePool in ANOTHER namespace only
if a ReferenceGrant in the TARGET namespace allows it — From must name
{group aigateway.envoyproxy.io, kind AIGatewayRoute, namespace
<route's>}, To must name the target's {group, kind}. Same-namespace
references never need a grant. Without this check, any tenant could
route through any other namespace's backends — an authorization gap,
not just surface parity (r4 verdict missing #3).

Runs as a cross-object admission step in BOTH control planes: the dir
reconciler (config/controller.py) and the live-cluster source
(config/kube.py watches the kind); a violating route is NotAccepted
with a message naming the missing grant, exactly like the reference's
condition text.
"""

from __future__ import annotations

from typing import Any

AIGW_GROUP = "aigateway.envoyproxy.io"
#: admission (config/admission.py) only admits InferencePool refs whose
#: backendRef.group is exactly this — grants must use the same group
INFERENCE_GROUP = "inference.networking.k8s.io"
ROUTE_KIND = "AIGatewayRoute"

#: referenceable target kinds → their API group (reference validates
#: AIServiceBackend and InferencePool refs; referencegrant.go:43-70)
_TARGET_GROUPS = {
    "AIServiceBackend": AIGW_GROUP,
    "InferencePool": INFERENCE_GROUP,
}


def _namespace(obj: dict[str, Any]) -> str:
    return (obj.get("metadata") or {}).get("namespace") or "default"


def obj_key(obj: dict[str, Any]) -> str:
    """Same identity as the reconciler's (controller._obj_key):
    namespace-qualified outside the default namespace, so verdicts
    key onto exactly the condition each object receives."""
    from aigw_tpu.config.controller import _obj_key

    return _obj_key(obj)


def _grant_allows(grant: dict[str, Any], from_ns: str, to_group: str,
                  to_kind: str, to_name: str) -> bool:
    # explicit-null tolerance throughout (`or ()`): `from:`/`to:` as
    # YAML null must quarantine nothing and crash nothing
    spec = grant.get("spec") or {}
    from_ok = any(
        f.get("group") == AIGW_GROUP
        and f.get("kind") == ROUTE_KIND
        and f.get("namespace") == from_ns
        for f in (spec.get("from") or ()) if isinstance(f, dict)
    )
    if not from_ok:
        return False
    # Gateway API: a To entry with a name restricts the grant to that
    # one resource. (The reference matches group+kind only,
    # referencegrant.go matchesTo — honoring the name is strictly
    # narrower, per the upstream ReferenceGrant spec.)
    return any(
        t.get("group") == to_group and t.get("kind") == to_kind
        and (not t.get("name") or t.get("name") == to_name)
        for t in (spec.get("to") or ()) if isinstance(t, dict)
    )


def validate(objects: list[dict[str, Any]]) -> dict[str, str]:
    """Check every AIGatewayRoute's cross-namespace backendRefs against
    the ReferenceGrants present in ``objects``. Returns
    ``{obj_key(route): message}`` for each violating route."""
    grants_by_ns: dict[str, list[dict[str, Any]]] = {}
    for obj in objects:
        if obj.get("kind") == "ReferenceGrant":
            grants_by_ns.setdefault(_namespace(obj), []).append(obj)

    errors: dict[str, str] = {}
    for obj in objects:
        if obj.get("kind") != ROUTE_KIND:
            continue
        route_ns = _namespace(obj)
        key = obj_key(obj)
        spec = obj.get("spec") or {}
        for rule in (spec.get("rules") or ()):
            if not isinstance(rule, dict):
                continue
            for ref in (rule.get("backendRefs") or ()):
                if not isinstance(ref, dict):
                    continue
                target_ns = ref.get("namespace")
                if not target_ns or target_ns == route_ns:
                    continue
                kind = ref.get("kind") or "AIServiceBackend"
                group = ref.get("group") or _TARGET_GROUPS.get(
                    kind, AIGW_GROUP)
                ref_name = str(ref.get("name", "") or "")
                allowed = any(
                    _grant_allows(g, route_ns, group, kind, ref_name)
                    for g in grants_by_ns.get(target_ns, ())
                )
                if not allowed:
                    errors[key] = (
                        f"cross-namespace reference from AIGatewayRoute "
                        f"in namespace {route_ns} to {kind} "
                        f"{ref.get('name', '?')} in namespace "
                        f"{target_ns} is not permitted: no valid "
                        f"ReferenceGrant found in namespace {target_ns}."
                        f" A ReferenceGrant must allow AIGatewayRoute "
                        f"from namespace {route_ns} to reference {kind} "
                        f"in namespace {target_ns}"
                    )
                    break
            if key in errors:
                break
    return errors
