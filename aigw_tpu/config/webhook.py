"""Pod mutating webhook — sidecar injection for gateway pods.

The reference's controller registers a MutatingWebhookConfiguration and
mutates Envoy Gateway pods to inject the extproc container
(internal/controller/gateway_mutator.go:126 `Default`, :201
`ai-gateway-extproc` container; cmd/controller/main.go wires the
webhook server). Here the injected sidecar is the aigw gateway itself
running against the cluster (`aigw run kube:in-cluster`) — pods labeled
with the owning-gateway labels get the container; everything else is
admitted untouched.

Wire protocol is the standard admission.k8s.io/v1 AdmissionReview:
Kubernetes POSTs a JSON AdmissionReview, the response carries a
base64-encoded RFC 6902 JSONPatch. Run with `aigw webhook` (K8s
requires TLS on webhook endpoints — pass --tls-cert/--tls-key; the
plain-HTTP mode exists for tests and mesh-terminated TLS).
"""

from __future__ import annotations

import base64
import json
import logging
from typing import Any

logger = logging.getLogger(__name__)

#: the labels Envoy Gateway stamps on the pods it owns (the reference
#: keys its mutation on the same pair, gateway_mutator.go:131-132)
OWNING_GATEWAY_NAME_LABEL = "gateway.envoyproxy.io/owning-gateway-name"
OWNING_GATEWAY_NAMESPACE_LABEL = \
    "gateway.envoyproxy.io/owning-gateway-namespace"

SIDECAR_NAME = "ai-gateway-sidecar"  # ≈ reference's ai-gateway-extproc


def build_sidecar(
    image: str,
    *,
    port: int = 1975,
    log_level: str = "info",
    extra_env: list[dict[str, str]] | None = None,
) -> dict[str, Any]:
    """The injected container spec: the full gateway, configured from
    the cluster's CRDs via the in-cluster kube source.

    RBAC: the sidecar runs under the POD's service account (Envoy
    Gateway's), which needs list/watch on the aigw CRD kinds and patch
    on their /status — the chart ships a ClusterRole + binding for it
    (charts/aigw-tpu/templates/webhook.yaml, values
    webhook.envoyGatewayServiceAccount). Without it the sidecar's
    in-cluster list 403s and the container crash-loops."""
    return {
        "name": SIDECAR_NAME,
        "image": image,
        "args": ["run", "kube:in-cluster",
                 "--host", "0.0.0.0",
                 "--port", str(port),
                 "--log-level", log_level],
        "ports": [{"containerPort": port, "name": "aigw"}],
        "env": list(extra_env or ()),
        "readinessProbe": {
            "httpGet": {"path": "/health", "port": port},
            "initialDelaySeconds": 2,
            "periodSeconds": 5,
        },
    }


def mutate_pod(pod: dict[str, Any], image: str,
               **sidecar_kwargs: Any) -> list[dict[str, Any]]:
    """JSONPatch ops injecting the gateway sidecar, or [] when the pod
    is not a gateway pod / already carries the sidecar (idempotent —
    webhooks re-fire on every pod update)."""
    labels = (pod.get("metadata") or {}).get("labels") or {}
    if not labels.get(OWNING_GATEWAY_NAME_LABEL):
        return []
    spec = pod.get("spec") or {}
    containers = spec.get("containers") or []
    if any(c.get("name") == SIDECAR_NAME for c in containers):
        return []
    sidecar = build_sidecar(image, **sidecar_kwargs)
    if not containers:
        return [{"op": "add", "path": "/spec/containers",
                 "value": [sidecar]}]
    return [{"op": "add", "path": "/spec/containers/-",
             "value": sidecar}]


def review_response(review: dict[str, Any], image: str,
                    **sidecar_kwargs: Any) -> dict[str, Any]:
    """AdmissionReview in → AdmissionReview out (always allowed; a
    telemetry/injection failure must never block pod creation — the
    reference's webhook has failurePolicy Ignore semantics for the same
    reason)."""
    request = review.get("request") or {}
    uid = request.get("uid", "")
    response: dict[str, Any] = {"uid": uid, "allowed": True}
    try:
        # mutate_pod is a safe no-op for anything without the
        # owning-gateway label (and the webhook rules already restrict
        # to pods) — no extra kind-sniffing needed
        obj = request.get("object") or {}
        patch = mutate_pod(obj, image, **sidecar_kwargs)
        if patch:
            response["patchType"] = "JSONPatch"
            response["patch"] = base64.b64encode(
                json.dumps(patch).encode()).decode()
            name = (obj.get("metadata") or {}).get("name", "?")
            logger.info("injecting %s into pod %s", SIDECAR_NAME, name)
    except Exception:  # noqa: BLE001 — admission must not block pods
        logger.warning("pod mutation failed; admitting unmodified",
                       exc_info=True)
    return {
        "apiVersion": review.get("apiVersion", "admission.k8s.io/v1"),
        "kind": "AdmissionReview",
        "response": response,
    }


def webhook_app(image: str, **sidecar_kwargs: Any):
    """aiohttp app serving POST /mutate (and /health)."""
    from aiohttp import web

    async def mutate(request: "web.Request") -> "web.Response":
        try:
            review = json.loads(await request.read())
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"},
                                     status=400)
        return web.json_response(
            review_response(review, image, **sidecar_kwargs))

    async def health(_request: "web.Request") -> "web.Response":
        return web.json_response({"status": "ok"})

    app = web.Application()
    app.router.add_post("/mutate", mutate)
    app.router.add_get("/health", health)
    return app
