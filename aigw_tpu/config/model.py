"""Typed gateway configuration model.

The declarative model a user writes (YAML/JSON) and the gateway consumes.
It is deliberately decoupled from any orchestrator (the reference makes the
same choice for its data-plane config: filterapi/filterconfig.go:6-12).

Shape parity with the reference:

- ``Config``            ≈ filterapi.Config          (filterconfig.go:25)
- ``Backend``           ≈ filterapi.Backend + AIServiceBackend CRD
                          (api/v1alpha1/ai_service_backend.go:28)
- ``Route``/``RouteRule``≈ AIGatewayRoute CRD rules  (ai_gateway_route.go:216)
- ``RuleBackendRef``    ≈ AIGatewayRouteRuleBackendRef weight/priority
                          (ai_gateway_route.go:377-397)
- ``LLMRequestCost``    ≈ filterapi.LLMRequestCost   (shared_types.go:103-162)
- ``AuthConfig``        ≈ BackendSecurityPolicy CRD  (backendsecurity_policy.go:37)
- ``APISchema``         ≈ VersionedAPISchema         (shared_types.go:15-74)
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import json
from dataclasses import dataclass, field
from typing import Any

# Header used to carry the extracted model name from the route-selection
# phase into route matching — the same role as the reference's
# ``x-ai-eg-model`` (api/v1alpha1/shared_types.go:160-162).
MODEL_NAME_HEADER = "x-aigw-model"
# Original path of the request before backend-specific rewrites
# (reference internalapi.go `x-ai-eg-original-path`).
ORIGINAL_PATH_HEADER = "x-aigw-original-path"
# Internal per-request id linking the route phase to the upstream phase
# (reference `x-ai-eg-internal-req-id`, extproc/server.go).
INTERNAL_REQUEST_ID_HEADER = "x-aigw-internal-req-id"
# Endpoint-picker selected destination (reference
# `x-gateway-destination-endpoint`, internalapi.go:76).
DESTINATION_ENDPOINT_HEADER = "x-gateway-destination-endpoint"

# Config schema version. Configs with a different version are rejected at
# load time — the same rolling-upgrade gate as the reference
# (filterapi/filterconfig.go:26-31).
CONFIG_VERSION = "v1"


class ConfigError(ValueError):
    """Raised for invalid gateway configuration."""


class APISchemaName(str, enum.Enum):
    """Supported provider API schemas (reference shared_types.go:30-74)."""

    OPENAI = "OpenAI"
    ANTHROPIC = "Anthropic"
    AWS_BEDROCK = "AWSBedrock"
    AWS_ANTHROPIC = "AWSAnthropic"
    AZURE_OPENAI = "AzureOpenAI"
    GCP_VERTEX_AI = "GCPVertexAI"
    GCP_ANTHROPIC = "GCPAnthropic"
    COHERE = "Cohere"
    # The in-tree TPU serving engine. Speaks the OpenAI surface natively
    # plus engine-specific extensions (KV-occupancy telemetry headers).
    TPUSERVE = "TPUServe"


@dataclass(frozen=True)
class APISchema:
    """A schema name plus optional version (e.g. OpenAI "v1")."""

    name: APISchemaName
    version: str = ""

    @staticmethod
    def parse(value: Any) -> "APISchema":
        if isinstance(value, str):
            return APISchema(name=APISchemaName(value))
        if isinstance(value, dict):
            return APISchema(
                name=APISchemaName(value["name"]), version=value.get("version", "")
            )
        raise ConfigError(f"invalid APISchema: {value!r}")

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"name": self.name.value}
        if self.version:
            d["version"] = self.version
        return d


class AuthKind(str, enum.Enum):
    """Upstream credential kinds (reference backendauth/auth.go:19-61)."""

    NONE = "None"
    API_KEY = "APIKey"  # Authorization: Bearer <key>
    AWS_SIGV4 = "AWSSigV4"  # SigV4 request signing (incl. body hash)
    AZURE_API_KEY = "AzureAPIKey"  # api-key header
    AZURE_TOKEN = "AzureToken"  # Authorization: Bearer <oauth token>
    GCP_TOKEN = "GCPToken"  # Bearer token + project/region path rewrite
    ANTHROPIC_API_KEY = "AnthropicAPIKey"  # x-api-key + anthropic-version


@dataclass(frozen=True)
class AuthConfig:
    """Per-backend upstream credential configuration.

    ``api_key``/``secret_*`` fields may be literal values or ``file:<path>``
    references resolved at runtime-config build time (the reference mounts
    rotated credentials from Secret files the same way,
    backendauth/apikey.go).
    """

    kind: AuthKind = AuthKind.NONE
    api_key: str = ""
    # AWS SigV4
    aws_access_key_id: str = ""
    aws_secret_access_key: str = ""
    aws_session_token: str = ""
    aws_region: str = ""
    aws_service: str = "bedrock"
    # Azure
    azure_api_key: str = ""
    azure_access_token: str = ""
    # GCP
    gcp_access_token: str = ""
    gcp_project: str = ""
    gcp_region: str = ""
    # Anthropic
    anthropic_version: str = "2023-06-01"

    @staticmethod
    def parse(value: dict[str, Any] | None) -> "AuthConfig":
        if not value:
            return AuthConfig()
        kind = AuthKind(value.get("kind", "None"))
        known = {f.name for f in dataclasses.fields(AuthConfig)}
        kwargs = {k: v for k, v in value.items() if k in known and k != "kind"}
        unknown = set(value) - known - {"kind"}
        if unknown:
            raise ConfigError(f"unknown auth fields: {sorted(unknown)}")
        return AuthConfig(kind=kind, **kwargs)

    def to_dict(self) -> dict[str, Any]:
        d = {"kind": self.kind.value}
        for f in dataclasses.fields(self):
            if f.name == "kind":
                continue
            v = getattr(self, f.name)
            if v != f.default:
                d[f.name] = v
        return d


@dataclass(frozen=True)
class HeaderMutation:
    """Set/remove request headers toward a backend
    (reference filterapi HTTPHeaderMutation; headermutator/header_mutator.go:15).
    """

    set: tuple[tuple[str, str], ...] = ()
    remove: tuple[str, ...] = ()

    @staticmethod
    def parse(value: dict[str, Any] | None) -> "HeaderMutation":
        if not value:
            return HeaderMutation()
        sets = tuple(
            (str(h["name"]).lower(), str(h["value"])) for h in value.get("set", ())
        )
        removes = tuple(str(h).lower() for h in value.get("remove", ()))
        return HeaderMutation(set=sets, remove=removes)

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {}
        if self.set:
            d["set"] = [{"name": n, "value": v} for n, v in self.set]
        if self.remove:
            d["remove"] = list(self.remove)
        return d


@dataclass(frozen=True)
class BodyMutation:
    """Set/remove top-level JSON body fields toward a backend
    (reference bodymutator/body_mutator.go:17-85)."""

    set: tuple[tuple[str, Any], ...] = ()
    remove: tuple[str, ...] = ()

    @staticmethod
    def parse(value: dict[str, Any] | None) -> "BodyMutation":
        if not value:
            return BodyMutation()
        sets = tuple(
            (str(f["name"]), _freeze(f["value"])) for f in value.get("set", ())
        )
        removes = tuple(str(f) for f in value.get("remove", ()))
        return BodyMutation(set=sets, remove=removes)

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {}
        if self.set:
            d["set"] = [{"name": n, "value": _thaw(v)} for n, v in self.set]
        if self.remove:
            d["remove"] = list(self.remove)
        return d


def _check_endpoint(e: Any) -> Any:
    """Reject malformed picker endpoints at config load so a bad hot
    reload is dropped by the keep-last-good path instead of blowing up in
    the reload callback."""
    if isinstance(e, str) and e:
        return e
    if isinstance(e, dict) and isinstance(e.get("address"), str) and e["address"]:
        return e
    raise ConfigError(
        f"invalid endpoint entry {e!r}: expected 'host:port' or "
        "{{address: ..., slice: ...}}"
    )


def _freeze(v: Any) -> Any:
    """Make parsed JSON hashable so dataclasses stay frozen."""
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, list):
        return tuple(_freeze(x) for x in v)
    return v


def _thaw(v: Any) -> Any:
    if isinstance(v, tuple):
        if v and all(isinstance(x, tuple) and len(x) == 2 and isinstance(x[0], str) for x in v):
            return {k: _thaw(x) for k, x in v}
        return [_thaw(x) for x in v]
    return v


class LLMRequestCostType(str, enum.Enum):
    """Token-cost metrics attachable to a request
    (reference shared_types.go:103-162: 7 cost types incl. CEL)."""

    INPUT_TOKEN = "InputToken"
    OUTPUT_TOKEN = "OutputToken"
    TOTAL_TOKEN = "TotalToken"
    CACHED_INPUT_TOKEN = "CachedInputToken"
    CACHE_CREATION_INPUT_TOKEN = "CacheCreationInputToken"
    REASONING_TOKEN = "ReasoningToken"
    EXPRESSION = "Expression"  # cost expression (reference: CEL, llmcostcel)


@dataclass(frozen=True)
class LLMRequestCost:
    """One cost metric: write `<metadata_key> = <cost>` at end of stream."""

    metadata_key: str
    cost_type: LLMRequestCostType
    expression: str = ""

    @staticmethod
    def parse(value: dict[str, Any]) -> "LLMRequestCost":
        c = LLMRequestCost(
            metadata_key=value["metadata_key"],
            cost_type=LLMRequestCostType(value.get("type", "TotalToken")),
            expression=value.get("expression", ""),
        )
        if c.cost_type is LLMRequestCostType.EXPRESSION and not c.expression:
            raise ConfigError(f"cost {c.metadata_key}: Expression type needs expression")
        return c

    def to_dict(self) -> dict[str, Any]:
        d = {"metadata_key": self.metadata_key, "type": self.cost_type.value}
        if self.expression:
            d["expression"] = self.expression
        return d


def _check_picker_mode(mode: str) -> str:
    if mode not in ("static", "slo"):
        raise ConfigError(
            f"picker_mode must be 'static' or 'slo' (got {mode!r})")
    return mode


def _check_controller(value: dict[str, Any]) -> Any:
    """Validate a backend's fleet-controller block at parse time (the
    knobs are consumed by gateway/controller.ControllerConfig; storing
    the frozen mapping keeps Backend hashable). Lazy import: the config
    layer must stay importable without the gateway stack."""
    raw = value.get("controller")
    if raw is None:
        return None
    from aigw_tpu.gateway.controller import ControllerConfig

    if not value.get("endpoints"):
        raise ConfigError(
            f"backend {value.get('name', '?')!r}: controller requires "
            "an endpoint pool")
    try:
        ControllerConfig.parse(dict(raw))
    except (TypeError, ValueError) as e:
        raise ConfigError(
            f"backend {value.get('name', '?')!r}: invalid controller "
            f"block: {e}") from None
    return _freeze(raw)


@dataclass(frozen=True)
class Backend:
    """One upstream backend: schema + address + auth + mutations.

    ≈ AIServiceBackend CRD (ai_service_backend.go:28) flattened with the
    resolved Envoy Gateway ``Backend`` address.
    """

    name: str
    schema: APISchema
    # Upstream base URL, e.g. "https://api.openai.com" or
    # "http://127.0.0.1:8011". TLS decided by the scheme.
    url: str = ""
    # Replica pool for the endpoint picker (InferencePool equivalent):
    # entries are "host:port" strings or {address, slice} mappings. When
    # set, the picker chooses a replica per request by KV occupancy /
    # queue depth / slice affinity and overrides `url`.
    endpoints: tuple[Any, ...] = ()
    picker_poll_interval: float = 1.0
    # Derive a session-affinity key from the conversation prefix (all
    # messages except the latest user turn) so consecutive turns land on
    # the replica holding their KV prefix cache. Explicit
    # x-aigw-session-affinity headers still win.
    picker_content_affinity: bool = False
    # Endpoint-picker scoring mode (ISSUE 8): "static" = the classic
    # occupancy/queue score sum; "slo" = rank replicas by PREDICTED
    # TTFT derived from each replica's live phase histograms + queue
    # depth, with admission control against slo_ttft_ms.
    picker_mode: str = "static"
    # TTFT SLO budget in milliseconds for slo mode: when > 0 and every
    # candidate's predicted TTFT exceeds it, the gateway sheds the
    # request with 429 + Retry-After instead of queueing into collapse.
    # 0 = route predictively but never shed.
    slo_ttft_ms: float = 0.0
    # Prefill/decode disaggregation (ISSUE 8): let the gateway hand a
    # young streaming session from a prefill-pressured replica to a
    # decode-leaning sibling (KV page migration through the replicas'
    # /migrate endpoints). Requires an endpoint pool.
    migration: bool = False
    # Migrate only while the source replica's admission queue is at
    # least this deep (prefill pressure)…
    migration_queue_depth: int = 2
    # …and only sessions still young (streamed tokens ≤ this): mature
    # decodes have amortized their prefill and aren't worth moving.
    migration_young_tokens: int = 32
    # Fleet KV memory hierarchy (ISSUE 11): maintain a chain-hash →
    # replica index from the replicas' polled /state digests and name
    # chain-holding siblings in the x-aigw-kv-peers header so a prefix
    # miss on the chosen replica becomes a cross-replica page fetch.
    # Costs nothing against replicas that don't advertise chains;
    # False suppresses the peers header entirely.
    kv_fleet: bool = True
    # Fleet observability plane (ISSUE 12): feed the live SLO burn-rate
    # monitor from the polled TTFT histograms and record every routing
    # decision in the /debug/decisions audit ring. False is the A/B
    # control (bench --ab fleet_obs); /fleet/state and /fleet/metrics
    # stay served either way (health machine + rollups are ~free).
    fleet_obs: bool = True
    # SLO burn-rate monitor knobs: the availability objective the error
    # budget derives from (goodput target; budget = 1 - objective), the
    # goodput window length, and how many consecutive over-budget
    # windows raise the sustained-overshoot flag (the autoscale
    # predicate). The TTFT threshold itself is slo_ttft_ms (falling
    # back to the monitor's 500ms default when unset).
    slo_objective: float = 0.95
    slo_window_s: float = 30.0
    slo_burn_windows: int = 3
    # Fleet control plane (ISSUE 14): the replica lifecycle manager —
    # autoscaling off the SLO monitor's sustained-overshoot flag,
    # scale-in via lossless drain, crash failover. A mapping of
    # gateway/controller.ControllerConfig knobs (min_replicas,
    # max_replicas, tick_s, scale_cooldown_s, idle_ticks,
    # idle_slots_frac, down_grace_s, drain_timeout_s, launcher:
    # {kind: local, spec: {...}, env: {...}}). None = static pool (no
    # controller). Requires an endpoint pool.
    controller: Any = None
    auth: AuthConfig = AuthConfig()
    header_mutation: HeaderMutation = HeaderMutation()
    body_mutation: BodyMutation = BodyMutation()
    # Rewrite the model name sent upstream (reference modelNameOverride).
    model_name_override: str = ""
    # Timeouts (seconds). stream_idle_timeout guards stalled SSE streams and
    # triggers failover (reference ai_gateway_route.go:268-281 →
    # per_try_idle_timeout).
    request_timeout: float = 120.0
    stream_idle_timeout: float = 30.0

    @staticmethod
    def parse(value: dict[str, Any]) -> "Backend":
        try:
            return Backend(
                name=value["name"],
                schema=APISchema.parse(value["schema"]),
                url=value.get("url", ""),
                endpoints=tuple(
                    _freeze(_check_endpoint(e))
                    for e in value.get("endpoints", ())
                ),
                picker_poll_interval=float(
                    value.get("picker_poll_interval", 1.0)
                ),
                picker_content_affinity=bool(
                    value.get("picker_content_affinity", False)
                ),
                picker_mode=_check_picker_mode(
                    str(value.get("picker_mode", "static"))),
                slo_ttft_ms=float(value.get("slo_ttft_ms", 0.0)),
                migration=bool(value.get("migration", False)),
                migration_queue_depth=int(
                    value.get("migration_queue_depth", 2)),
                migration_young_tokens=int(
                    value.get("migration_young_tokens", 32)),
                kv_fleet=bool(value.get("kv_fleet", True)),
                fleet_obs=bool(value.get("fleet_obs", True)),
                slo_objective=float(value.get("slo_objective", 0.95)),
                slo_window_s=float(value.get("slo_window_s", 30.0)),
                slo_burn_windows=int(value.get("slo_burn_windows", 3)),
                controller=_check_controller(value),
                auth=AuthConfig.parse(value.get("auth")),
                header_mutation=HeaderMutation.parse(value.get("header_mutation")),
                body_mutation=BodyMutation.parse(value.get("body_mutation")),
                model_name_override=value.get("model_name_override", ""),
                request_timeout=float(value.get("request_timeout", 120.0)),
                stream_idle_timeout=float(value.get("stream_idle_timeout", 30.0)),
            )
        except KeyError as e:
            raise ConfigError(f"backend missing required field {e}") from None

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"name": self.name, "schema": self.schema.to_dict()}
        if self.url:
            d["url"] = self.url
        if self.endpoints:
            d["endpoints"] = [_thaw(e) for e in self.endpoints]
        if self.picker_poll_interval != 1.0:
            d["picker_poll_interval"] = self.picker_poll_interval
        if self.picker_content_affinity:
            d["picker_content_affinity"] = True
        if self.picker_mode != "static":
            d["picker_mode"] = self.picker_mode
        if self.slo_ttft_ms:
            d["slo_ttft_ms"] = self.slo_ttft_ms
        if self.migration:
            d["migration"] = True
        if self.migration_queue_depth != 2:
            d["migration_queue_depth"] = self.migration_queue_depth
        if self.migration_young_tokens != 32:
            d["migration_young_tokens"] = self.migration_young_tokens
        if not self.kv_fleet:
            d["kv_fleet"] = False
        if not self.fleet_obs:
            d["fleet_obs"] = False
        if self.slo_objective != 0.95:
            d["slo_objective"] = self.slo_objective
        if self.slo_window_s != 30.0:
            d["slo_window_s"] = self.slo_window_s
        if self.slo_burn_windows != 3:
            d["slo_burn_windows"] = self.slo_burn_windows
        if self.controller is not None:
            d["controller"] = _thaw(self.controller)
        if self.auth.kind is not AuthKind.NONE:
            d["auth"] = self.auth.to_dict()
        if self.header_mutation != HeaderMutation():
            d["header_mutation"] = self.header_mutation.to_dict()
        if self.body_mutation != BodyMutation():
            d["body_mutation"] = self.body_mutation.to_dict()
        if self.model_name_override:
            d["model_name_override"] = self.model_name_override
        if self.request_timeout != 120.0:
            d["request_timeout"] = self.request_timeout
        if self.stream_idle_timeout != 30.0:
            d["stream_idle_timeout"] = self.stream_idle_timeout
        return d


@dataclass(frozen=True)
class RuleBackendRef:
    """Weighted/priority reference from a route rule to a backend
    (reference ai_gateway_route.go:377-397: weight for traffic split,
    priority for fallback ordering — lower number = tried first)."""

    backend: str
    weight: int = 1
    priority: int = 0

    @staticmethod
    def parse(value: Any) -> "RuleBackendRef":
        if isinstance(value, str):
            return RuleBackendRef(backend=value)
        return RuleBackendRef(
            backend=value["backend"],
            weight=int(value.get("weight", 1)),
            priority=int(value.get("priority", 0)),
        )

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"backend": self.backend}
        if self.weight != 1:
            d["weight"] = self.weight
        if self.priority != 0:
            d["priority"] = self.priority
        return d


@dataclass(frozen=True)
class HeaderMatch:
    """Exact or regex header match for a route rule (reference matches on
    x-ai-eg-model via HTTPRoute header matching, types Exact and
    RegularExpression)."""

    name: str
    value: str
    regex: bool = False

    def match(self, got: str) -> bool:
        if self.regex:
            import re

            try:
                return re.fullmatch(self.value, got) is not None
            except re.error:
                return False
        return got == self.value

    @staticmethod
    def parse(value: dict[str, Any]) -> "HeaderMatch":
        m = HeaderMatch(
            name=str(value["name"]).lower(),
            value=str(value["value"]),
            regex=bool(value.get("regex", False)),
        )
        if m.regex:
            import re

            try:
                re.compile(m.value)
            except re.error as e:
                raise ConfigError(
                    f"invalid regex for header {m.name!r}: {e}") from None
        return m

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"name": self.name, "value": self.value}
        if self.regex:
            d["regex"] = True
        return d


@dataclass(frozen=True)
class RouteRule:
    """One route rule: header matches (typically on the model header) →
    backend refs (reference AIGatewayRouteRule, ai_gateway_route.go:216)."""

    backends: tuple[RuleBackendRef, ...]
    headers: tuple[HeaderMatch, ...] = ()
    # Convenience sugar: `models: [m1, m2]` expands to model-header matches.
    models: tuple[str, ...] = ()
    # Prefix matches (e.g. "claude-" routes every Claude model).
    model_prefixes: tuple[str, ...] = ()
    name: str = ""

    def matches(self, headers: dict[str, str]) -> bool:
        model = headers.get(MODEL_NAME_HEADER, "")
        if self.models or self.model_prefixes:
            exact = model in self.models
            prefix = any(model.startswith(p) for p in self.model_prefixes)
            if not exact and not prefix:
                return False
        for m in self.headers:
            got = headers.get(m.name)
            # a missing header never matches — even patterns that accept
            # the empty string (HTTPRoute semantics: header must exist)
            if got is None or not m.match(got):
                return False
        return True

    @staticmethod
    def parse(value: dict[str, Any]) -> "RouteRule":
        backends = tuple(RuleBackendRef.parse(b) for b in value.get("backends", ()))
        if not backends:
            raise ConfigError("route rule needs at least one backend")
        return RouteRule(
            backends=backends,
            headers=tuple(HeaderMatch.parse(h) for h in value.get("headers", ())),
            models=tuple(value.get("models", ())),
            model_prefixes=tuple(value.get("model_prefixes", ())),
            name=value.get("name", ""),
        )

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"backends": [b.to_dict() for b in self.backends]}
        if self.headers:
            d["headers"] = [h.to_dict() for h in self.headers]
        if self.models:
            d["models"] = list(self.models)
        if self.model_prefixes:
            d["model_prefixes"] = list(self.model_prefixes)
        if self.name:
            d["name"] = self.name
        return d


@dataclass(frozen=True)
class Model:
    """Entry for /v1/models discovery (reference filterapi Model +
    AIGatewayRouteRule model-listing metadata)."""

    name: str
    owned_by: str = "aigw-tpu"
    created_at: int = 0

    @staticmethod
    def parse(value: Any) -> "Model":
        if isinstance(value, str):
            return Model(name=value)
        return Model(
            name=value["name"],
            owned_by=value.get("owned_by", "aigw-tpu"),
            created_at=int(value.get("created_at", 0)),
        )

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"name": self.name}
        if self.owned_by != "aigw-tpu":
            d["owned_by"] = self.owned_by
        if self.created_at:
            d["created_at"] = self.created_at
        return d


@dataclass(frozen=True)
class Route:
    """A named route: rules evaluated in order, first match wins."""

    name: str
    rules: tuple[RouteRule, ...]
    # Hostnames this route applies to ("" = all), mirroring per-host model
    # scoping (reference filterapi ModelsByHost).
    hostnames: tuple[str, ...] = ()
    # Route-level costs, merged over the global list (reference
    # AIGatewayRoute.Spec.LLMRequestCosts, ai_gateway_route.go:57).
    llm_request_costs: tuple[LLMRequestCost, ...] = ()

    @staticmethod
    def parse(value: dict[str, Any]) -> "Route":
        return Route(
            name=value["name"],
            rules=tuple(RouteRule.parse(r) for r in value.get("rules", ())),
            hostnames=tuple(value.get("hostnames", ())),
            llm_request_costs=tuple(
                LLMRequestCost.parse(c)
                for c in value.get("llm_request_costs", ())
            ),
        )

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name,
            "rules": [r.to_dict() for r in self.rules],
        }
        if self.hostnames:
            d["hostnames"] = list(self.hostnames)
        if self.llm_request_costs:
            d["llm_request_costs"] = [
                c.to_dict() for c in self.llm_request_costs
            ]
        return d


@dataclass(frozen=True)
class Config:
    """The complete gateway configuration (≈ filterapi.Config,
    filterconfig.go:25). Immutable; hot reload swaps whole objects."""

    backends: tuple[Backend, ...] = ()
    routes: tuple[Route, ...] = ()
    models: tuple[Model, ...] = ()
    llm_request_costs: tuple[LLMRequestCost, ...] = ()
    # Quota rules (parsed/enforced by aigw_tpu.gateway.ratelimit — the
    # QuotaPolicy equivalent); stored frozen for hashability.
    quotas: tuple[Any, ...] = ()
    mcp: dict[str, Any] | None = None  # parsed by aigw_tpu.mcp
    # Engine-truth usage metering (ISSUE 20): the gateway ledger's
    # knobs, stored frozen. None = metering ON with defaults (in-memory
    # ledger, 60s windows, no budgets). Mapping keys: enabled (bool),
    # window_s (float), retain_windows (int), journal (JSONL path, ""
    # = in-memory), budgets ({tenant: cost-per-window}), burn_windows
    # (K consecutive over-budget windows → sustained alert).
    usage: Any = None
    version: str = CONFIG_VERSION
    uuid: str = ""

    def backend(self, name: str) -> Backend:
        for b in self.backends:
            if b.name == name:
                return b
        raise ConfigError(f"unknown backend {name!r}")

    def validate(self) -> None:
        names = [b.name for b in self.backends]
        if len(names) != len(set(names)):
            raise ConfigError("duplicate backend names")
        # NOTE: a backend with neither url nor endpoints is legal — it can
        # be driven purely by the x-gateway-destination-endpoint header
        # (external EPP flow, reference post_cluster_modify.go:67-80).
        for r in self.routes:
            for rule in r.rules:
                for ref in rule.backends:
                    if ref.backend not in names:
                        raise ConfigError(
                            f"route {r.name!r} references unknown backend "
                            f"{ref.backend!r}"
                        )
                    if ref.weight < 0:
                        raise ConfigError("backend weight must be >= 0")
        keys = [c.metadata_key for c in self.llm_request_costs]
        if len(keys) != len(set(keys)):
            raise ConfigError("duplicate llm_request_costs metadata keys")
        for r in self.routes:
            rkeys = [c.metadata_key for c in r.llm_request_costs]
            if len(rkeys) != len(set(rkeys)):
                raise ConfigError(
                    f"route {r.name!r}: duplicate cost metadata keys"
                )

    @staticmethod
    def parse(value: dict[str, Any]) -> "Config":
        version = value.get("version", CONFIG_VERSION)
        if version != CONFIG_VERSION:
            # Version-gated load: reject configs written by a different
            # framework version mid rolling-upgrade (filterconfig.go:26-31).
            raise ConfigError(
                f"config version {version!r} != supported {CONFIG_VERSION!r}"
            )
        cfg = Config(
            backends=tuple(Backend.parse(b) for b in value.get("backends", ())),
            routes=tuple(Route.parse(r) for r in value.get("routes", ())),
            models=tuple(Model.parse(m) for m in value.get("models", ())),
            llm_request_costs=tuple(
                LLMRequestCost.parse(c) for c in value.get("llm_request_costs", ())
            ),
            quotas=tuple(_freeze(q) for q in value.get("quotas", ())),
            mcp=value.get("mcp"),
            usage=(_freeze(value["usage"])
                   if value.get("usage") is not None else None),
            version=version,
            uuid=value.get("uuid", ""),
        )
        cfg.validate()
        return cfg

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"version": self.version}
        if self.uuid:
            d["uuid"] = self.uuid
        if self.backends:
            d["backends"] = [b.to_dict() for b in self.backends]
        if self.routes:
            d["routes"] = [r.to_dict() for r in self.routes]
        if self.models:
            d["models"] = [m.to_dict() for m in self.models]
        if self.llm_request_costs:
            d["llm_request_costs"] = [c.to_dict() for c in self.llm_request_costs]
        if self.quotas:
            d["quotas"] = [_thaw(q) for q in self.quotas]
        if self.mcp is not None:
            d["mcp"] = self.mcp
        if self.usage is not None:
            d["usage"] = _thaw(self.usage)
        return d

    def checksum(self) -> str:
        """Stable content hash, used by the watcher to skip no-op reloads
        (the reference checksums bundle parts, config_bundle.go:21)."""
        blob = json.dumps(self.to_dict(), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()


def load_config(path: str) -> Config:
    """Load a Config from a YAML or JSON file. K8s CRD manifests (the
    reference's example YAML, multi-document with kind/apiVersion) are
    detected and compiled via config.crd — ``aigw run basic.yaml`` works
    on the reference's own examples unchanged."""
    import yaml

    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    docs = [d for d in yaml.safe_load_all(text) if d is not None]
    if not docs:
        raise ConfigError(f"empty config file {path!r}")
    from aigw_tpu.config.crd import compile_crd_objects, looks_like_crd

    if looks_like_crd([d for d in docs if isinstance(d, dict)]):
        return Config.parse(compile_crd_objects(
            [d for d in docs if isinstance(d, dict)]))
    if len(docs) > 1:
        raise ConfigError(
            f"{path!r} contains {len(docs)} YAML documents but is not a "
            "K8s CRD manifest; native configs must be a single document")
    data = docs[0]
    if not isinstance(data, dict):
        raise ConfigError(f"config root must be a mapping, got {type(data)}")
    return Config.parse(data)
