"""Kind-aware loader for the reference's Kubernetes CRD YAML.

Accepts the reference's example manifests **unchanged** (the files under
/root/reference/examples/{basic,aigw,token_ratelimit,provider_fallback,
inference-pool,mcp}) and compiles them into the native config dict that
``Config.parse`` consumes — the same role the reference's ``aigw
translate`` plays by running its real controllers against a fake K8s
client (cmd/aigw/translate.go:114-392), collapsed into a direct
compilation because this framework has no K8s dependency.

Kinds handled:
- ``AIGatewayRoute`` (v1alpha1/v1beta1) → routes + llm_request_costs
  (ai_gateway_route.go:37)
- ``AIServiceBackend`` → backend schema/timeouts (ai_service_backend.go:28)
- ``Backend`` (gateway.envoyproxy.io) → backend address(es)
- ``BackendSecurityPolicy`` → backend auth, secrets resolved from co-bundled
  ``Secret`` objects with ``${ENV}`` substitution (backendsecurity_policy.go)
- ``BackendTLSPolicy`` → https scheme
- ``InferencePool`` → picker-driven backend (x-gateway-destination-endpoint
  contract, internalapi.go:76)
- ``BackendTrafficPolicy`` rateLimit → token quotas (QuotaPolicy-style
  descriptor rules)
- ``MCPRoute`` → MCP proxy config (mcp_route.go:25)
- ``GatewayConfig`` → global llm_request_costs (gateway_config.go:40)

Infrastructure kinds (GatewayClass, Gateway, EnvoyProxy, Deployment,
Service, ClientTrafficPolicy, HTTPRoute, …) are recognized and skipped —
the native data plane subsumes their roles.
"""

from __future__ import annotations

import hashlib
import logging
import os
import re
from typing import Any

from aigw_tpu.config.model import ConfigError

logger = logging.getLogger(__name__)

#: CRD kinds that carry gateway semantics we compile
_HANDLED = {
    "AIGatewayRoute", "AIServiceBackend", "BackendSecurityPolicy",
    "Backend", "BackendTLSPolicy", "InferencePool", "BackendTrafficPolicy",
    "MCPRoute", "GatewayConfig", "QuotaPolicy", "Secret", "Gateway",
}
#: infra kinds silently skipped
_SKIPPED = {
    "GatewayClass", "EnvoyProxy", "Deployment", "Service",
    "ClientTrafficPolicy", "HTTPRoute", "HTTPRouteFilter", "ServiceAccount",
    "ConfigMap", "Role", "RoleBinding", "ClusterRole", "ClusterRoleBinding",
    "InferenceObjective", "InferenceModel", "Namespace", "Job",
    "SecurityPolicy", "EnvoyExtensionPolicy",
    # consumed by config.refgrant (cross-namespace authorization), not
    # compiled into the serving config itself
    "ReferenceGrant",
}

MODEL_HEADER = "x-ai-eg-model"


def looks_like_crd(docs: list[dict[str, Any]]) -> bool:
    """True when the YAML stream contains K8s-style objects."""
    return any(
        isinstance(d, dict) and "kind" in d and "apiVersion" in d
        for d in docs
    )


def load_crd_documents(text: str) -> list[dict[str, Any]]:
    import yaml

    return [d for d in yaml.safe_load_all(text) if isinstance(d, dict)]


def _name(obj: dict[str, Any]) -> str:
    return str((obj.get("metadata") or {}).get("name", ""))


def _duration_seconds(v: Any, default: float) -> float:
    """'120s' / '3m' / '1h' / '100ms' → seconds."""
    if v is None:
        return default
    if isinstance(v, (int, float)):
        return float(v)
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h)?", str(v).strip())
    if not m:
        raise ConfigError(f"unparseable duration {v!r}")
    n = float(m.group(1))
    return n * {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0,
                None: 1.0}[m.group(2)]


def _env_substitute(s: str) -> str:
    """Expand ``${VAR}`` from the environment (the reference's ``aigw run``
    does the same substitution over Secret stringData, run.go:154-159)."""
    return re.sub(
        r"\$\{(\w+)\}", lambda m: os.environ.get(m.group(1), ""), s)


class _Secrets:
    def __init__(self, objs: list[dict[str, Any]]):
        self._by_name: dict[str, dict[str, str]] = {}
        for o in objs:
            data: dict[str, str] = {}
            for k, v in (o.get("stringData") or {}).items():
                data[k] = _env_substitute(str(v))
            for k, v in (o.get("data") or {}).items():
                import base64

                try:
                    data.setdefault(
                        k, base64.b64decode(str(v)).decode("utf-8"))
                except Exception:
                    pass
            self._by_name[_name(o)] = data

    def get(self, name: str, key: str) -> str:
        return self._by_name.get(name, {}).get(key, "")


def _backend_url(backend_obj: dict[str, Any], tls: bool) -> tuple[str, list]:
    """Envoy Gateway Backend endpoints → (url, picker endpoints)."""
    scheme = "https" if tls else "http"
    addrs: list[str] = []
    for ep in (backend_obj.get("spec") or {}).get("endpoints", ()):
        if "fqdn" in ep:
            host = ep["fqdn"].get("hostname", "")
            port = int(ep["fqdn"].get("port", 80))
        elif "ip" in ep:
            host = ep["ip"].get("address", "")
            port = int(ep["ip"].get("port", 80))
        elif "unix" in ep:
            continue
        else:
            continue
        if port == 443:
            scheme = "https"
        addrs.append(f"{host}:{port}")
    if not addrs:
        return "", []
    if len(addrs) == 1:
        return f"{scheme}://{addrs[0]}", []
    return "", addrs  # replica pool → endpoint picker


def _auth_from_bsp(spec: dict[str, Any], secrets: _Secrets) -> dict[str, Any]:
    kind = spec.get("type", "")
    if kind == "APIKey":
        ref = ((spec.get("apiKey") or {}).get("secretRef") or {})
        return {"kind": "APIKey",
                "api_key": secrets.get(ref.get("name", ""), "apiKey")}
    if kind == "AnthropicAPIKey":
        ref = ((spec.get("anthropicAPIKey") or {}).get("secretRef") or {})
        out: dict[str, Any] = {
            "kind": "AnthropicAPIKey",
            "api_key": secrets.get(ref.get("name", ""), "apiKey")}
        if (spec.get("anthropicAPIKey") or {}).get("apiVersion"):
            out["anthropic_version"] = spec["anthropicAPIKey"]["apiVersion"]
        return out
    if kind == "AzureAPIKey":
        ref = ((spec.get("azureAPIKey") or {}).get("secretRef") or {})
        return {"kind": "AzureAPIKey",
                "azure_api_key": secrets.get(ref.get("name", ""), "apiKey")}
    if kind == "AzureCredentials":
        # OIDC client-credentials exchange happens at runtime (oidc.py);
        # statically we map the token secret when present
        ref = (((spec.get("azureCredentials") or {}).get(
            "clientSecretRef")) or {})
        return {"kind": "AzureToken",
                "azure_access_token": secrets.get(ref.get("name", ""),
                                                  "client-secret")}
    if kind == "AWSCredentials":
        aws = spec.get("awsCredentials") or {}
        out = {"kind": "AWSSigV4", "aws_region": aws.get("region", "")}
        ref = ((aws.get("credentialsFile") or {}).get("secretRef") or {})
        creds = secrets.get(ref.get("name", ""), "credentials")
        if creds:
            # AWS shared-credentials INI (the rotators write this format)
            for line in creds.splitlines():
                line = line.strip()
                if line.startswith("aws_access_key_id"):
                    out["aws_access_key_id"] = line.split("=", 1)[1].strip()
                elif line.startswith("aws_secret_access_key"):
                    out["aws_secret_access_key"] = \
                        line.split("=", 1)[1].strip()
                elif line.startswith("aws_session_token"):
                    out["aws_session_token"] = line.split("=", 1)[1].strip()
        return out
    if kind == "GCPCredentials":
        gcp = spec.get("gcpCredentials") or {}
        return {
            "kind": "GCPToken",
            "gcp_project": gcp.get("projectName", ""),
            "gcp_region": gcp.get("region", ""),
        }
    raise ConfigError(f"unsupported BackendSecurityPolicy type {kind!r}")


def _compile_route_rules(route_obj: dict[str, Any]) -> list[dict[str, Any]]:
    """AIGatewayRoute rules → native route rules. A CRD rule's ``matches``
    entries are OR'd (each is an AND of header matches) — expanded into
    one native rule per match."""
    out: list[dict[str, Any]] = []
    spec = route_obj.get("spec") or {}
    route_name = _name(route_obj)
    for ri, rule in enumerate(spec.get("rules", ())):
        backends = []
        for ref in rule.get("backendRefs", ()):
            b: dict[str, Any] = {"backend": ref.get("name", "")}
            if ref.get("weight") is not None:
                b["weight"] = int(ref["weight"])
            if ref.get("priority") is not None:
                b["priority"] = int(ref["priority"])
            backends.append(b)
        if not backends:
            continue
        matches = rule.get("matches") or [{}]
        timeout = (rule.get("timeouts") or {}).get("request")
        for mi, match in enumerate(matches):
            models: list[str] = []
            headers: list[dict[str, Any]] = []
            for h in match.get("headers", ()):
                htype = h.get("type", "Exact")
                name = str(h.get("name", "")).lower()
                value = str(h.get("value", ""))
                if name == MODEL_HEADER and htype == "Exact":
                    models.append(value)
                elif htype == "Exact":
                    headers.append({"name": name, "value": value})
                elif htype == "RegularExpression":
                    if name == MODEL_HEADER:
                        if value in (".*", "^.*$"):
                            pass  # match-all model: no constraint
                        else:
                            # the native gateway stamps the model under its
                            # own header name (MODEL_NAME_HEADER) — rewrite
                            # the CRD's x-ai-eg-model to match it
                            from aigw_tpu.config.model import (
                                MODEL_NAME_HEADER,
                            )

                            headers.append({"name": MODEL_NAME_HEADER,
                                            "value": value, "regex": True})
                    else:
                        headers.append({"name": name, "value": value,
                                        "regex": True})
                else:
                    raise ConfigError(
                        f"route {route_name!r}: unsupported header match "
                        f"type {htype!r}")
            native: dict[str, Any] = {
                "backends": backends,
                "name": f"{route_name}/rule{ri}"
                        + (f"/m{mi}" if len(matches) > 1 else ""),
            }
            if models:
                native["models"] = models
            if headers:
                native["headers"] = headers
            if timeout is not None:
                native["_request_timeout"] = _duration_seconds(timeout, 120.0)
            out.append(native)
    return out


def _costs_of(spec: dict[str, Any], key: str) -> list[dict[str, Any]]:
    out = []
    for c in spec.get(key, ()) or ():
        cost: dict[str, Any] = {
            "metadata_key": c.get("metadataKey", ""),
            "type": c.get("type", "TotalToken"),
        }
        if cost["type"] == "CEL":
            # reference llmcostcel CEL → native Expression engine
            cost["type"] = "Expression"
            cost["expression"] = c.get("cel", "")
        out.append(cost)
    return out


_UNIT_SECONDS = {"Second": 1, "Minute": 60, "Hour": 3600, "Day": 86400}

#: QuotaPolicy window enum (quotapolicies CRD: duration 1s|1m|1h|1d)
_QP_DURATION = {"1s": 1, "1m": 60, "1h": 3600, "1d": 86400}


def _quotas_from_quota_policy(
    objs: list[dict[str, Any]],
) -> tuple[list[dict[str, Any]], list[dict[str, Any]]]:
    """QuotaPolicy CRD objects → (native quota rules, synthesized
    llm_request_costs). r5 fix: the kind was admission-validated and
    chart-shipped but silently dropped by the compiler — `kubectl apply`
    of a QuotaPolicy enforced nothing.

    Mapping (quotapolicies CRD schema, api/v1alpha1):
    - targetRefs (AIServiceBackend) → per-rule backend scope
    - serviceQuota.quota → one rule per target, any model
    - perModelQuotas[].quota.defaultBucket → model-scoped rule
    - perModelQuotas[].quota.bucketRules[] → model-scoped rules keyed
      by the first Distinct header selector (client buckets); rules in
      shadowMode are skipped (observe-only)
    - costExpression → a synthesized Expression cost metric the rule
      draws down ("total_tokens" default → shared TotalToken metric);
      name/namespace-alphabetical precedence for duplicate model keys
      follows the CRD's own documented tie-break."""
    quotas: list[dict[str, Any]] = []
    costs: dict[str, dict[str, Any]] = {}

    def cost_key(expr: str | None) -> str:
        if not expr:
            key = "aigw_qp_total_tokens"
            costs.setdefault(key, {
                "metadata_key": key, "type": "TotalToken"})
            return key
        key = "aigw_qp_cost_" + hashlib.sha256(
            expr.encode()).hexdigest()[:10]
        costs.setdefault(key, {
            "metadata_key": key, "type": "Expression",
            "expression": expr})
        return key

    def client_header(rule: dict[str, Any]) -> str:
        for sel in rule.get("clientSelectors") or ():
            for h in (sel or {}).get("headers") or ():
                if h.get("type") == "Distinct" and h.get("name"):
                    return str(h["name"]).lower()
        return ""

    #: (model, backend) pairs already claimed by an alphabetically
    #: earlier policy — the CRD's documented tie-break ("the policy
    #: whose namespace/name is alphabetically first takes precedence")
    claimed: set[tuple[str, str]] = set()
    for o in sorted(objs, key=lambda x: (_namespace_of(x), _name(x))):
        ns = _namespace_of(o)
        # namespace-qualified identity: two same-named policies in
        # different namespaces must not merge into one budget (rule
        # names key the limiter's buckets)
        pname = _name(o) if ns == "default" else f"{ns}/{_name(o)}"
        spec = o.get("spec") or {}
        targets = [str(r.get("name", "")) for r in
                   (spec.get("targetRefs") or ())
                   if r.get("kind") in (None, "AIServiceBackend")
                   and r.get("name")]
        if not targets:
            continue
        sq = spec.get("serviceQuota") or {}
        sq_quota = sq.get("quota") or {}
        if sq_quota.get("limit"):
            key = cost_key(sq.get("costExpression"))
            for t in targets:
                quotas.append({
                    "name": f"{pname}/service/{t}",
                    "metadata_key": key,
                    "limit": int(sq_quota["limit"]),
                    "window_seconds": _QP_DURATION.get(
                        sq_quota.get("duration", "1h"), 3600),
                    "backend": t,
                })
        for pm in spec.get("perModelQuotas") or ():
            model = str(pm.get("modelName", "") or "")
            q = pm.get("quota") or {}
            live_targets = [t for t in targets
                            if (model, t) not in claimed]
            if not live_targets:
                continue  # a preceding policy owns this (model, backend)
            buckets: list[tuple[str, dict[str, Any], str]] = []
            db = q.get("defaultBucket") or {}
            if db.get("limit"):
                buckets.append(("default", db, ""))
            for j, br in enumerate(q.get("bucketRules") or ()):
                if br.get("shadowMode"):
                    continue  # observe-only: never rejects
                brq = (br or {}).get("quota") or {}
                if brq.get("limit"):
                    buckets.append((f"bucket{j}", brq,
                                    client_header(br)))
            if not buckets:
                # a shadow-only / limit-less entry enforces nothing and
                # must not claim the (model, backend) pair away from an
                # alphabetically later policy with a real limit
                continue
            claimed.update((model, t) for t in live_targets)
            key = cost_key(q.get("costExpression"))
            for label, bq, hdr in buckets:
                for t in live_targets:
                    rule = {
                        "name": f"{pname}/{model}/{label}/{t}",
                        "metadata_key": key,
                        "limit": int(bq["limit"]),
                        "window_seconds": _QP_DURATION.get(
                            bq.get("duration", "1h"), 3600),
                        "model": model,
                        "backend": t,
                        # CRD "Shared" mode: default bucket + bucket
                        # rules of one per-model entry charge together
                        # and allow while ANY has headroom
                        "shared_group": f"{pname}/{model}/{t}",
                    }
                    if hdr:
                        rule["client_key_header"] = hdr
                    quotas.append(rule)
    return quotas, list(costs.values())


def _namespace_of(obj: dict[str, Any]) -> str:
    return (obj.get("metadata") or {}).get("namespace") or "default"


def _quotas_from_btp(objs: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """BackendTrafficPolicy global rate-limit rules whose response cost
    reads io.envoy.ai_gateway metadata → native token quotas."""
    quotas: list[dict[str, Any]] = []
    for o in objs:
        rl = ((o.get("spec") or {}).get("rateLimit") or {})
        for i, rule in enumerate((rl.get("global") or {}).get("rules", ())):
            meta = (((rule.get("cost") or {}).get("response") or {})
                    .get("metadata") or {})
            if meta.get("namespace") not in ("io.envoy.ai_gateway", None) \
                    or not meta.get("key"):
                continue
            limit = rule.get("limit") or {}
            window = _UNIT_SECONDS.get(limit.get("unit", "Hour"), 3600)
            q: dict[str, Any] = {
                "name": f"{_name(o)}/rule{i}",
                "metadata_key": meta["key"],
                "limit": int(limit.get("requests", 0)),
                "window_seconds": window,
            }
            for sel in rule.get("clientSelectors", ()):
                for h in sel.get("headers", ()):
                    if h.get("type") == "Distinct" and h.get("name"):
                        q["client_key_header"] = str(h["name"]).lower()
            quotas.append(q)
    return quotas


def _mcp_config(mcp_routes: list[dict[str, Any]],
                backends: dict[str, dict[str, Any]],
                tls_targets: set[str],
                secrets: _Secrets) -> dict[str, Any] | None:
    if not mcp_routes:
        return None
    out_backends: list[dict[str, Any]] = []
    path = "/mcp"
    for route in mcp_routes:
        spec = route.get("spec") or {}
        path = spec.get("path", path) or path
        for ref in spec.get("backendRefs", ()):
            name = ref.get("name", "")
            bobj = backends.get(name)
            if bobj is None:
                raise ConfigError(
                    f"MCPRoute references unknown Backend {name!r}")
            url, pool = _backend_url(bobj, name in tls_targets)
            if not url and pool:
                url = f"http://{pool[0]}"
            b: dict[str, Any] = {
                "name": name,
                "url": url + str(ref.get("path", "") or ""),
            }
            sel = ref.get("toolSelector") or {}
            include = list(sel.get("include", ()) or ())
            include_regex = list(sel.get("includeRegex", ()) or ())
            if include or include_regex:
                tf: dict[str, Any] = {}
                if include:
                    tf["include"] = include
                if include_regex:
                    tf["include_regex"] = include_regex
                b["tool_filter"] = tf
            sp = ref.get("securityPolicy") or {}
            key_ref = ((sp.get("apiKey") or {}).get("secretRef") or {})
            if key_ref.get("name"):
                key = secrets.get(key_ref["name"], "apiKey") or \
                    secrets.get(key_ref["name"], "token")
                if key:
                    b["headers"] = [{"name": "authorization",
                                     "value": f"Bearer {key}"}]
            out_backends.append(b)
    return {"backends": out_backends, "path": path}


def compile_crd_objects(docs: list[dict[str, Any]]) -> dict[str, Any]:
    """K8s CRD objects → native config dict (feed to ``Config.parse``)."""
    by_kind: dict[str, list[dict[str, Any]]] = {}
    for d in docs:
        kind = d.get("kind", "")
        if kind in _HANDLED or kind in _SKIPPED:
            by_kind.setdefault(kind, []).append(d)
        else:
            logger.warning("ignoring unrecognized kind %r", kind)

    secrets = _Secrets(by_kind.get("Secret", []))
    eg_backends = {_name(o): o for o in by_kind.get("Backend", [])}
    tls_targets: set[str] = set()
    for o in by_kind.get("BackendTLSPolicy", []):
        for ref in (o.get("spec") or {}).get("targetRefs", ()):
            tls_targets.add(ref.get("name", ""))

    # BSPs indexed by the AIServiceBackend they target
    bsp_by_backend: dict[str, dict[str, Any]] = {}
    for o in by_kind.get("BackendSecurityPolicy", []):
        spec = o.get("spec") or {}
        for ref in spec.get("targetRefs", ()):
            if ref.get("kind", "AIServiceBackend") == "AIServiceBackend":
                bsp_by_backend[ref.get("name", "")] = spec

    pools = {_name(o): o for o in by_kind.get("InferencePool", [])}

    backends: list[dict[str, Any]] = []
    seen: set[str] = set()
    for o in by_kind.get("AIServiceBackend", []):
        name = _name(o)
        spec = o.get("spec") or {}
        schema = spec.get("schema") or {}
        native: dict[str, Any] = {
            "name": name,
            "schema": ({"name": schema.get("name", "OpenAI"),
                        "version": schema["version"]}
                       if schema.get("version")
                       else schema.get("name", "OpenAI")),
        }
        ref_name = (spec.get("backendRef") or {}).get("name", name)
        bobj = eg_backends.get(ref_name)
        if bobj is not None:
            tls = ref_name in tls_targets
            url, pool_eps = _backend_url(bobj, tls)
            if url:
                native["url"] = url
            elif pool_eps:
                native["endpoints"] = pool_eps
        timeout = (spec.get("timeouts") or {}).get("request")
        if timeout is not None:
            native["request_timeout"] = _duration_seconds(timeout, 120.0)
        if name in bsp_by_backend:
            native["auth"] = _auth_from_bsp(bsp_by_backend[name], secrets)
        backends.append(native)
        seen.add(name)

    # InferencePool backends: no static address — replicas are picked at
    # request time (the reference resolves pods by selector + EPP; natively
    # the x-gateway-destination-endpoint header or a configured pool drives
    # the picker)
    for name, pool in pools.items():
        if name in seen:
            continue
        backends.append({"name": name, "schema": "OpenAI"})
        seen.add(name)

    routes: list[dict[str, Any]] = []
    costs: list[dict[str, Any]] = []
    models: list[str] = []
    for o in by_kind.get("AIGatewayRoute", []):
        rules = _compile_route_rules(o)
        # referenced-but-undeclared backends (e.g. InferencePool refs by
        # bare name) must exist
        for rule in rules:
            for b in rule["backends"]:
                if b["backend"] not in seen:
                    backends.append({"name": b["backend"],
                                     "schema": "OpenAI"})
                    seen.add(b["backend"])
            models.extend(rule.get("models", ()))
        # per-rule timeouts land on the referenced backends
        for rule in rules:
            t = rule.pop("_request_timeout", None)
            if t is not None:
                for b in rule["backends"]:
                    for nb in backends:
                        if nb["name"] == b["backend"]:
                            nb.setdefault("request_timeout", t)
        routes.append({"name": _name(o), "rules": rules})
        costs.extend(_costs_of(o.get("spec") or {}, "llmRequestCosts"))

    for o in by_kind.get("GatewayConfig", []):
        costs.extend(_costs_of(o.get("spec") or {}, "globalLLMRequestCosts"))

    # de-duplicate costs by metadata key (route-level + global may repeat)
    uniq_costs: list[dict[str, Any]] = []
    cost_keys: set[str] = set()
    for c in costs:
        if c["metadata_key"] and c["metadata_key"] not in cost_keys:
            cost_keys.add(c["metadata_key"])
            uniq_costs.append(c)

    out: dict[str, Any] = {
        "version": "v1",
        "backends": backends,
        "routes": routes,
    }
    uniq_models = sorted(set(m for m in models if m))
    if uniq_models:
        out["models"] = uniq_models
    if uniq_costs:
        out["llm_request_costs"] = uniq_costs
    quotas = _quotas_from_btp(by_kind.get("BackendTrafficPolicy", []))
    qp_quotas, qp_costs = _quotas_from_quota_policy(
        by_kind.get("QuotaPolicy", []))
    quotas += qp_quotas
    if qp_costs:
        have = {c.get("metadata_key") for c in
                out.get("llm_request_costs", ())}
        out.setdefault("llm_request_costs", []).extend(
            c for c in qp_costs if c["metadata_key"] not in have)
    if quotas:
        out["quotas"] = quotas
    mcp = _mcp_config(by_kind.get("MCPRoute", []), eg_backends,
                      tls_targets, secrets)
    if mcp:
        out["mcp"] = mcp
    return out


def load_crd_yaml(text: str) -> dict[str, Any]:
    return compile_crd_objects(load_crd_documents(text))
