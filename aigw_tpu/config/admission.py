"""CRD admission validation — the reference's CEL/schema rules, natively.

The reference encodes apply-time invariants as CEL expressions and
OpenAPI constraints on its CRDs (api/v1beta1/*.go ``+kubebuilder``
markers), exercised by tests/crdcel/main_test.go against a real API
server. Without an API server, the same invariants run here as plain
checks, invoked by the reconciling control plane before an object is
compiled — an invalid object is NotAccepted with the rule's message,
mirroring an admission rejection.

``tests/test_crd_cel.py`` replays the reference's own fixture corpus
(tests/crdcel/testdata/*) through this validator: every fixture the API
server would reject must produce an error here, and every fixture it
accepts must pass.
"""

from __future__ import annotations

from typing import Any

SUPPORTED_SCHEMAS = (
    "OpenAI", "Cohere", "AWSBedrock", "AzureOpenAI", "GCPVertexAI",
    "GCPAnthropic", "Anthropic",
)

#: BackendSecurityPolicy type → its configuration field
_BSP_FIELDS = {
    "APIKey": "apiKey",
    "AWSCredentials": "awsCredentials",
    "AzureAPIKey": "azureAPIKey",
    "AzureCredentials": "azureCredentials",
    "GCPCredentials": "gcpCredentials",
    "AnthropicAPIKey": "anthropicAPIKey",
}

_RESERVED_RULE_NAMES = {"route-not-found"}
_MAX_ROUTE_RULES = 15


def validate(obj: dict[str, Any]) -> list[str]:
    """Admission errors for one CRD object ([] = accepted)."""
    kind = obj.get("kind", "")
    spec = obj.get("spec") or {}
    if kind == "AIGatewayRoute":
        return _validate_route(spec)
    if kind == "AIServiceBackend":
        return _validate_backend(spec)
    if kind == "BackendSecurityPolicy":
        return _validate_bsp(spec)
    if kind == "MCPRoute":
        return _validate_mcp(spec)
    if kind == "QuotaPolicy":
        return _validate_quota(spec)
    return []


def _parse_duration(value: Any) -> float | None:
    """Gateway-API Duration ("1h2m3s500ms") → seconds, None if unparseable."""
    import re

    if not isinstance(value, str):
        return None
    m = re.fullmatch(
        r"(?:(\d+)h)?(?:(\d+)m)?(?:(\d+)s)?(?:(\d+)ms)?", value.strip())
    if not m or not any(m.groups()):
        return None
    h, mi, sec, ms = (int(g) if g else 0 for g in m.groups())
    return h * 3600 + mi * 60 + sec + ms / 1000.0


def _validate_parent_refs(spec: dict[str, Any]) -> list[str]:
    errors = []
    for ref in spec.get("parentRefs") or ():
        if (ref or {}).get("kind", "Gateway") != "Gateway":
            errors.append("spec.parentRefs: only Gateway is supported")
    return errors


def _validate_route(spec: dict[str, Any]) -> list[str]:
    errors = _validate_parent_refs(spec)
    rules = spec.get("rules") or ()
    if len(rules) > _MAX_ROUTE_RULES:
        errors.append(
            f"spec.rules: too many: {len(rules)}: must have at most "
            f"{_MAX_ROUTE_RULES} items")
    seen_names: set[str] = set()
    for i, rule in enumerate(rules):
        name = (rule or {}).get("name", "")
        if name:
            if name in _RESERVED_RULE_NAMES:
                errors.append(
                    f"spec.rules[{i}]: rule name {name} is reserved")
            elif name in seen_names:
                errors.append(
                    "spec.rules: rule name must be unique within the route")
            seen_names.add(name)
        pools = 0
        non_pools = 0
        for j, ref in enumerate(rule.get("backendRefs") or ()):
            group = (ref or {}).get("group")
            rkind = (ref or {}).get("kind")
            if (group is None) != (rkind is None):
                errors.append(
                    f"spec.rules[{i}].backendRefs[{j}]: group and kind "
                    "must be specified together")
                continue
            if group is None:
                non_pools += 1
                continue
            if rkind != "InferencePool" or \
                    group != "inference.networking.k8s.io":
                errors.append(
                    f"spec.rules[{i}].backendRefs[{j}]: only InferencePool "
                    "from inference.networking.k8s.io group is supported")
                continue
            pools += 1
        timeouts = rule.get("timeouts") or {}
        req_t = _parse_duration(timeouts.get("request"))
        be_t = _parse_duration(timeouts.get("backendRequest"))
        if req_t is not None and be_t is not None and be_t > req_t:
            errors.append(
                f"spec.rules[{i}].timeouts: backendRequest timeout cannot "
                "be longer than request timeout")
        if pools and non_pools:
            errors.append(
                f"spec.rules[{i}]: cannot mix InferencePool and "
                "AIServiceBackend references in the same rule")
        if pools > 1:
            errors.append(
                f"spec.rules[{i}]: only one InferencePool backend is "
                "allowed per rule")
    return errors


def _validate_backend(spec: dict[str, Any]) -> list[str]:
    errors = []
    schema_name = (spec.get("schema") or {}).get("name", "")
    if schema_name not in SUPPORTED_SCHEMAS:
        errors.append(
            f"spec.schema.name: unsupported value {schema_name!r}: "
            f"supported values: {', '.join(SUPPORTED_SCHEMAS)}")
    ref = spec.get("backendRef") or {}
    if ref and ref.get("kind", "Backend") != "Backend":
        errors.append(
            "spec.backendRef: BackendRef must be a Backend resource of "
            "Envoy Gateway")
    return errors


def _validate_bsp(spec: dict[str, Any]) -> list[str]:
    errors = []
    btype = spec.get("type", "")
    field = _BSP_FIELDS.get(btype)
    if field is None:
        errors.append(
            f"spec.type: unsupported value {btype!r}: supported values: "
            f"{', '.join(_BSP_FIELDS)}")
    else:
        others = [f for t, f in _BSP_FIELDS.items()
                  if f != field and spec.get(f) is not None]
        if spec.get(field) is None or others:
            errors.append(
                f"spec: when type is {btype}, only {field} field "
                "should be set")
    az = spec.get("azureCredentials")
    if az is not None:
        if not (az.get("clientID") or ""):
            errors.append(
                "spec.azureCredentials.clientID should be at least 1 "
                "chars long")
        if not (az.get("tenantID") or ""):
            errors.append(
                "spec.azureCredentials.tenantID should be at least 1 "
                "chars long")
        has_secret = az.get("clientSecretRef") is not None
        has_oidc = az.get("oidcExchangeToken") is not None
        if has_secret == has_oidc:
            errors.append(
                "spec.azureCredentials: exactly one of clientSecretRef or "
                "oidcExchangeToken must be specified")
    gcp = spec.get("gcpCredentials")
    if gcp is not None:
        wif = (gcp.get("workloadIdentityFederationConfig") is not None)
        cred_file = (gcp.get("credentialsFile") is not None)
        if wif and cred_file:
            errors.append(
                "spec.gcpCredentials: at most one of credentialsFile or "
                "workloadIdentityFederationConfig may be specified")
        if not wif and not cred_file:
            errors.append(
                "spec.gcpCredentials: exactly one of "
                "GCPWorkloadIdentityFederationConfig or GCPCredentialsFile "
                "must be specified")
    target_groups = {
        "AIServiceBackend": "aigateway.envoyproxy.io",
        "InferencePool": "inference.networking.k8s.io",
    }
    for i, ref in enumerate(spec.get("targetRefs") or ()):
        rkind = (ref or {}).get("kind", "AIServiceBackend")
        want_group = target_groups.get(rkind)
        group = (ref or {}).get("group", want_group)
        if want_group is None or group != want_group:
            errors.append(
                f"spec.targetRefs[{i}]: targetRefs must reference "
                "AIServiceBackend or InferencePool resources")
    return errors


def _validate_mcp_tool_selector(sel: dict[str, Any],
                                path: str) -> list[str]:
    errors = []
    keys = [k for k in ("include", "includeRegex", "exclude",
                        "excludeRegex") if sel.get(k)]
    if not keys:
        errors.append(
            f"{path}: at least one of include, includeRegex, exclude, or "
            "excludeRegex must be specified")
    if sel.get("include") and sel.get("includeRegex"):
        errors.append(
            f"{path}: include and includeRegex are mutually exclusive")
    if sel.get("exclude") and sel.get("excludeRegex"):
        errors.append(
            f"{path}: exclude and excludeRegex are mutually exclusive")
    return errors


_MCP_REF_GROUPS = {"", "multicluster.x-k8s.io", "gateway.envoyproxy.io"}
_MCP_REF_KINDS = {"Service", "ServiceImport", "Backend"}


def _validate_mcp(spec: dict[str, Any]) -> list[str]:
    errors = _validate_parent_refs(spec)
    if spec.get("backendRef") is not None:
        errors.append(
            "spec: BackendRefs must be used, backendRef is not supported")
    if not (spec.get("backendRefs") or ()):
        errors.append("spec: backendRef or backendRefs needs to be set")
    seen: set[str] = set()
    for i, ref in enumerate(spec.get("backendRefs") or ()):
        group = (ref or {}).get("group", "") or ""
        rkind = (ref or {}).get("kind", "Service")
        if group not in _MCP_REF_GROUPS:
            errors.append(
                f"spec.backendRefs[{i}]: BackendRefs only supports Core, "
                "multicluster.x-k8s.io, and gateway.envoyproxy.io groups")
        elif rkind not in _MCP_REF_KINDS:
            errors.append(
                f"spec.backendRefs[{i}]: BackendRefs only supports "
                "Service, ServiceImport, and Backend kind")
        name = (ref or {}).get("name", "")
        if name in seen:
            errors.append(
                "spec.backendRefs: all backendRefs names must be unique")
        seen.add(name)
        sel = ref.get("toolSelector")
        if sel is not None:
            errors.extend(_validate_mcp_tool_selector(
                sel, f"spec.backendRefs[{i}].toolSelector"))
        api_key = ((ref.get("securityPolicy") or {}).get("apiKey"))
        if api_key is not None:
            has_secret = api_key.get("secretRef") is not None
            has_inline = api_key.get("inline") is not None
            if has_secret == has_inline:
                errors.append(
                    f"spec.backendRefs[{i}].securityPolicy.apiKey: exactly "
                    "one of secretRef or inline must be set")
            if api_key.get("header") and api_key.get("queryParam"):
                errors.append(
                    f"spec.backendRefs[{i}].securityPolicy.apiKey: only "
                    "one of header or queryParam can be set")
    policy = spec.get("securityPolicy") or {}
    oauth = policy.get("oauth")
    if oauth is not None:
        jwks = oauth.get("jwks") or {}
        has_remote = jwks.get("remoteJWKS") is not None
        has_local = jwks.get("localJWKS") is not None
        if not has_remote and not has_local:
            errors.append(
                "spec.securityPolicy.oauth.jwks: either remoteJWKS or "
                "localJWKS must be specified")
        if has_remote and has_local:
            errors.append(
                "spec.securityPolicy.oauth.jwks: remoteJWKS and localJWKS "
                "cannot both be specified")
    for i, rule in enumerate(
            (policy.get("authorization") or {}).get("rules") or ()):
        jwt = ((rule or {}).get("source") or {}).get("jwt")
        if jwt is None:
            continue
        if oauth is None:
            errors.append(
                "spec.securityPolicy: oauth must be configured when any "
                "authorization rule uses a jwt source")
        claims = jwt.get("claims") or ()
        if not claims and not (jwt.get("scopes") or ()):
            errors.append(
                f"spec.securityPolicy.authorization.rules[{i}].source.jwt: "
                "either scopes or claims must be specified")
        for claim in claims:
            if (claim or {}).get("name") == "scope":
                errors.append(
                    f"spec.securityPolicy.authorization.rules[{i}].source"
                    ".jwt.claims: 'scope' claim name is reserved for "
                    "OAuth scopes")
    return errors


def _validate_quota(spec: dict[str, Any]) -> list[str]:
    errors = []
    for i, ref in enumerate(spec.get("targetRefs") or ()):
        if (ref or {}).get("kind", "AIServiceBackend") != \
                "AIServiceBackend":
            errors.append(
                f"spec.targetRefs[{i}]: targetRefs must reference "
                "AIServiceBackend resources")
    for i, rule in enumerate(spec.get("rules") or ()):
        for j, m in enumerate((rule or {}).get("matches") or ()):
            if not any((m or {}).get(k) for k in (
                    "headers", "methods", "path", "sourceCIDR",
                    "queryParams")):
                errors.append(
                    f"spec.rules[{i}].matches[{j}]: at least one of "
                    "headers, methods, path, sourceCIDR or queryParams "
                    "must be specified")
    return errors
