"""Watching control plane: reconcile a directory of CRD manifests.

The reference's primary operating mode is "apply a CRD, the gateway
reconfigures itself": a controller watches live K8s objects, reconciles
them into gateway config, and writes Accepted/error status conditions
back onto each object (internal/controller/controller.go:117-330,
gateway.go:89; condition helpers in routes.go newRouteCondition).

Without a K8s API server, the watched source here is a manifest
directory — every ``*.yaml``/``*.yml`` file holds CRD objects — and the
reconcile semantics are kept:

- editing/adding/removing a manifest converges the serving config within
  the watch interval, no restart;
- every object gets a status condition (Accepted True/False with a
  reason), written to ``<dir>/aigw-status.json`` — the file-system
  equivalent of the reference writing ``status.conditions`` on each CRD;
- a broken object quarantines only itself: the remaining objects
  compile and serve (the reference's per-object reconcile failure marks
  that object NotAccepted while the rest of the config stands).

Kubernetes-style generation tracking: the status records the content
checksum it was computed from, so a reader can tell whether the
condition reflects the manifest they are looking at.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Any

from aigw_tpu.config.crd import compile_crd_objects
from aigw_tpu.config.model import Config, ConfigError

logger = logging.getLogger(__name__)

STATUS_FILE = "aigw-status.json"

#: cross-kind order for the quarantine pass: providers before consumers,
#: and policies AFTER their targets — a broken BackendSecurityPolicy only
#: manifests once its target backend is present, so adding the policy
#: last pins the blame on the policy object, not the healthy backend.
_KIND_ORDER = [
    "Secret",
    "Backend",
    "BackendTLSPolicy",
    "InferencePool",
    "AIServiceBackend",
    "BackendSecurityPolicy",
    "GatewayConfig",
    "BackendTrafficPolicy",
    "AIGatewayRoute",
    "MCPRoute",
]
_KIND_RANK = {k: i for i, k in enumerate(_KIND_ORDER)}


def _obj_key(obj: dict[str, Any]) -> str:
    """Object identity for conditions/status. Namespace-qualified for
    non-default namespaces so same-named objects in different
    namespaces never share a verdict (r5 review); the bare Kind/name
    form is kept for the default namespace — the common single-tenant
    manifest-dir case and the format `aigw status` has always shown.
    Collision-free: '/' is illegal in K8s names."""
    kind = obj.get("kind", "?")
    meta = obj.get("metadata") or {}
    name = meta.get("name", "?")
    ns = meta.get("namespace") or "default"
    return f"{kind}/{name}" if ns == "default" else f"{kind}/{ns}/{name}"


def _obj_checksum(obj: dict[str, Any]) -> str:
    blob = json.dumps(obj, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class Reconciler:
    """Scan a manifest directory → (Config, per-object status conditions).

    ``load()`` is the ConfigWatcher loader: it compiles the directory and
    writes the status file as a side effect, raising only when *nothing*
    servable could be compiled (startup must fail loudly; a partial
    manifest set serves the accepted subset).
    """

    def __init__(self, directory: str, status_path: str | None = None):
        self.directory = directory
        self.status_path = status_path or os.path.join(
            directory, STATUS_FILE)
        # accepted-state memory so lastTransitionTime only moves on flips
        self._conditions: dict[str, dict[str, Any]] = {}

    # -- manifest scanning -------------------------------------------------

    def _manifest_files(self) -> list[str]:
        try:
            names = sorted(os.listdir(self.directory))
        except FileNotFoundError:
            raise ConfigError(
                f"manifest directory {self.directory!r} does not exist"
            ) from None
        return [
            os.path.join(self.directory, n)
            for n in names
            if n.endswith((".yaml", ".yml")) and not n.startswith(".")
        ]

    def _read_objects(
        self,
    ) -> tuple[list[dict[str, Any]], dict[str, str]]:
        """All CRD objects across the directory, plus per-file parse
        errors (a torn file quarantines that file, not the directory)."""
        import yaml

        objects: list[dict[str, Any]] = []
        file_errors: dict[str, str] = {}
        for path in self._manifest_files():
            try:
                with open(path, "r", encoding="utf-8") as f:
                    docs = list(yaml.safe_load_all(f.read()))
            except Exception as e:  # noqa: BLE001 — yaml errors vary
                file_errors[os.path.basename(path)] = (
                    f"{type(e).__name__}: {e}")
                continue
            for d in docs:
                if isinstance(d, dict) and d.get("kind"):
                    objects.append(d)
        objects.sort(key=lambda o: _KIND_RANK.get(o.get("kind", ""), 99))
        return objects, file_errors

    # -- compile with per-object quarantine --------------------------------

    @staticmethod
    def _compile(objs: list[dict[str, Any]]) -> Config:
        cfg = Config.parse(compile_crd_objects(objs))
        cfg.validate()
        return cfg

    def _reconcile(
        self, objects: list[dict[str, Any]]
    ) -> tuple[Config, dict[str, str]]:
        """Compile, quarantining objects that break the compile.

        Admission first: the reference's CRD CEL rules run on every
        object (config.admission); an object an API server would refuse
        at apply time is NotAccepted with the rule's message. Then the
        fast path: everything compiles together. Slow path (something is
        broken): add objects one at a time in dependency order, keeping
        the growing good set — each rejected object is blamed with its
        own error. N+1 compiles of small dicts; only runs on bad input.
        """
        from aigw_tpu.config import admission, refgrant

        errors: dict[str, str] = {}
        # cross-object admission: ReferenceGrant enforcement for
        # cross-namespace backendRefs (reference referencegrant.go)
        grant_errors = refgrant.validate(objects)
        admitted: list[dict[str, Any]] = []
        for obj in objects:
            errs = admission.validate(obj)
            key = _obj_key(obj)
            if key in grant_errors:
                errs = list(errs) + [grant_errors[key]]
            if errs:
                errors[key] = "; ".join(errs)
            else:
                admitted.append(obj)
        objects = admitted
        try:
            return self._compile(objects), errors
        except Exception:  # noqa: BLE001 — find the offenders
            pass
        good: list[dict[str, Any]] = []
        for obj in objects:
            try:
                self._compile(good + [obj])
            except Exception as e:  # noqa: BLE001
                errors[_obj_key(obj)] = f"{type(e).__name__}: {e}"
                continue
            good.append(obj)
        return self._compile(good), errors

    # -- status conditions -------------------------------------------------

    def _update_conditions(
        self,
        objects: list[dict[str, Any]],
        errors: dict[str, str],
        file_errors: dict[str, str],
    ) -> bool:
        """Refresh conditions; True when anything actually changed."""
        now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        fresh: dict[str, dict[str, Any]] = {}
        for obj in objects:
            key = _obj_key(obj)
            err = errors.get(key, "")
            cond = {
                "type": "Accepted",
                "status": "False" if err else "True",
                "reason": "NotAccepted" if err else "Accepted",
                "message": err or "object compiled into the serving config",
            }
            prev = self._conditions.get(key)
            if prev is not None and prev["status"] == cond["status"]:
                cond["lastTransitionTime"] = prev["lastTransitionTime"]
            else:
                cond["lastTransitionTime"] = now
            cond["observedChecksum"] = _obj_checksum(obj)
            fresh[key] = cond
        for fname, err in file_errors.items():
            key = f"file/{fname}"
            prev = self._conditions.get(key)
            fresh[key] = {
                "type": "Accepted",
                "status": "False",
                "reason": "ParseError",
                "message": err,
                "lastTransitionTime": (
                    prev["lastTransitionTime"]
                    if prev is not None and prev["status"] == "False"
                    else now
                ),
            }
        changed = fresh != self._conditions
        self._conditions = fresh
        return changed

    def _write_status(self) -> None:
        payload = {
            "apiVersion": "aigateway.envoyproxy.io/v1alpha1",
            "kind": "StatusReport",
            "objects": self._conditions,
        }
        tmp = f"{self.status_path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, self.status_path)
        except OSError as e:
            logger.warning("status write failed: %s", e)

    def conditions(self) -> dict[str, dict[str, Any]]:
        """Per-object Accepted conditions from the last load() —
        ``{key: {status, reason, message, ...}}``. Public accessor for
        the CLI/status surfaces (the reference exposes the same data as
        `kubectl get` conditions on each object)."""
        return dict(self._conditions)

    def not_accepted(self) -> dict[str, dict[str, Any]]:
        """Subset of conditions() whose status is not \"True\"."""
        return {k: c for k, c in self._conditions.items()
                if c.get("status") != "True"}

    # -- watcher loader ----------------------------------------------------

    def load(self) -> Config:
        objects, file_errors = self._read_objects()
        cfg, errors = self._reconcile(objects)
        # write + log only on transitions: the watcher ticks every few
        # seconds and a persistently broken object must not churn the
        # status file's mtime or spam the log (the reference writes
        # conditions only when they change)
        if self._update_conditions(objects, errors, file_errors):
            self._write_status()
            for key, err in {**errors,
                             **{f"file/{f}": e
                                for f, e in file_errors.items()}}.items():
                logger.warning("reconcile: %s NOT accepted: %s", key, err)
        return cfg


def is_manifest_dir(path: str) -> bool:
    """A directory of CRD manifests (vs a sharded config bundle, which
    carries an index.json)."""
    if not os.path.isdir(path):
        return False
    if os.path.exists(os.path.join(path, "index.json")):
        return False
    try:
        return any(
            n.endswith((".yaml", ".yml")) and not n.startswith(".")
            for n in os.listdir(path)
        )
    except OSError:
        return False
