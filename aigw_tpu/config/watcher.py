"""Config hot-reload watcher.

Polls a config file, bundle directory, or CRD manifest directory (default
5s, the reference's tick — filterapi/watcher.go:79-145), checksums content
to skip no-op reloads, and swaps in a freshly built RuntimeConfig on
change. A bad new config is logged and rejected; the gateway keeps serving
the last good one (the reference's watcher has the same keep-last-good
semantics). A manifest directory goes through the reconciling control
plane (config.controller), which also writes per-object status conditions.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Awaitable, Callable

from aigw_tpu.config.bundle import read_bundle
from aigw_tpu.config.controller import Reconciler, is_manifest_dir
from aigw_tpu.config.model import Config, ConfigError, load_config
from aigw_tpu.config.runtime import RuntimeConfig

logger = logging.getLogger(__name__)

ReloadCallback = Callable[[RuntimeConfig], None]


class ConfigWatcher:
    def __init__(
        self,
        path: str,
        on_reload: ReloadCallback,
        interval: float = 5.0,
        transform=None,
    ):
        self.path = path
        self.on_reload = on_reload
        self.interval = interval
        #: optional Config → Config hook applied after every load —
        #: config-file reloads must re-apply CLI-side merges (e.g. the
        #: --mcp-config backends) or a touch of the YAML would drop them
        self.transform = transform
        self._checksum = ""
        self._task: asyncio.Task | None = None
        self._current: RuntimeConfig | None = None
        self._reconciler: Reconciler | None = None
        self._kube_reconciler = None
        self._kube_source = None

    def not_accepted(self) -> dict:
        """Per-object NOT-Accepted conditions from the reconciling
        control plane (empty when the source isn't reconciled)."""
        if self._kube_reconciler is not None:
            return self._kube_reconciler.not_accepted()
        if self._reconciler is None:
            return {}
        return self._reconciler.not_accepted()

    def _load(self) -> Config:
        if self.path.startswith("kube:"):
            # live cluster source: list/watch CRDs, conditions patched
            # back onto object status (config/kube.py — the reference's
            # controller mode, controller.go:117-330)
            if self._kube_reconciler is None:
                from aigw_tpu.config.kube import (
                    KubeReconciler,
                    KubeSource,
                    parse_kube_target,
                )

                source = KubeSource(parse_kube_target(self.path))
                source.start()
                if not source.wait_synced(60.0):
                    source.stop()
                    raise ConfigError(
                        f"kube source {self.path!r} never synced "
                        "(API server unreachable?)")
                self._kube_source = source
                self._kube_reconciler = KubeReconciler(source)
            return self._kube_reconciler.load()
        if is_manifest_dir(self.path):
            if self._reconciler is None:
                self._reconciler = Reconciler(self.path)
            return self._reconciler.load()
        if os.path.isdir(self.path):
            return read_bundle(self.path)
        return load_config(self.path)

    def load_initial(self) -> RuntimeConfig:
        """Synchronous first load; raises on invalid config (startup must
        fail loudly, reloads must not — same split as the reference)."""
        cfg = self._load()
        self._checksum = cfg.checksum()
        if self.transform is not None:
            cfg = self.transform(cfg)
        rc = RuntimeConfig.build(cfg)
        self._current = rc
        self.on_reload(rc)
        return rc

    async def start(self) -> None:
        self._task = asyncio.create_task(self._run(), name="config-watcher")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._kube_source is not None:
            if self._kube_reconciler is not None:
                # surrender the status-writer lease before tearing down
                # the loop the surrender runs on
                self._kube_reconciler.shutdown()
                await asyncio.sleep(0.1)
            await asyncio.to_thread(self._kube_source.stop)
            self._kube_source = None
            self._kube_reconciler = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                cfg = self._load()
                checksum = cfg.checksum()
                if checksum == self._checksum:
                    continue
                if self.transform is not None:
                    cfg = self.transform(cfg)
                rc = RuntimeConfig.build(cfg, previous=self._current)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # keep last good config
                logger.warning("config reload failed, keeping current: %s", e)
                continue
            self._checksum = checksum
            self._current = rc
            self.on_reload(rc)
            logger.info(
                "config reloaded (uuid=%s, %d backends, %d routes)",
                cfg.uuid,
                len(cfg.backends),
                len(cfg.routes),
            )
