"""Live Kubernetes source for the reconciling control plane.

The reference's primary deployment mode is a controller-runtime manager
that list/watches the AI Gateway CRDs on a cluster, converges config,
and writes Accepted conditions back onto each object's status
(reference internal/controller/controller.go:117-330 — watch wiring per
kind; gateway.go:89 — the gateway reconciler; `kubectl get` shows the
conditions). Rounds 1-3 reproduced the reconcile *semantics* against a
manifest directory; this module feeds the same reconcile loop from a
real API server.

Design: no Kubernetes client library is vendored (none is available in
the image) — the API surface needed is four HTTP verbs against a stable
REST layout, so a ~200-line client over aiohttp covers it:

- ``KubeClient.from_kubeconfig`` / ``in_cluster`` — auth material
  (bearer token, client cert, CA bundle) from the standard locations.
- ``list_resource`` / ``watch_resource`` — ``GET /apis/{g}/{v}/{plural}``
  and the same with ``?watch=true&resourceVersion=`` streaming JSON
  lines, the protocol `kubectl get -w` speaks.
- ``patch_status`` — ``PATCH .../{name}/status`` with
  ``application/merge-patch+json``, the reference's status writeback.

``KubeSource`` runs the watches on a dedicated thread/event loop and
maintains an object cache; ``KubeReconciler`` plugs that cache into the
existing Reconciler (admission → compile → quarantine → conditions) and
pushes per-object conditions back to the cluster. The directory mode
stays the default; select this source with ``aigw run kube:<kubeconfig>``
(or ``kube:in-cluster``).
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import os
import ssl
import tempfile
import threading
from dataclasses import dataclass
from typing import Any, Callable

logger = logging.getLogger(__name__)

#: kind → (group, version, plural, namespaced). Groups per the reference
#: CRD manifests (api/v1alpha1; gateway.envoyproxy.io for Backend;
#: gateway-api + inference-extension for the imported kinds).
RESOURCES: dict[str, tuple[str, str, str, bool]] = {
    "AIGatewayRoute": (
        "aigateway.envoyproxy.io", "v1alpha1", "aigatewayroutes", True),
    "AIServiceBackend": (
        "aigateway.envoyproxy.io", "v1alpha1", "aiservicebackends", True),
    "BackendSecurityPolicy": (
        "aigateway.envoyproxy.io", "v1alpha1",
        "backendsecuritypolicies", True),
    "MCPRoute": (
        "aigateway.envoyproxy.io", "v1alpha1", "mcproutes", True),
    "GatewayConfig": (
        "aigateway.envoyproxy.io", "v1alpha1", "gatewayconfigs", True),
    "QuotaPolicy": (
        "aigateway.envoyproxy.io", "v1alpha1", "quotapolicies", True),
    "Backend": (
        "gateway.envoyproxy.io", "v1alpha1", "backends", True),
    "BackendTLSPolicy": (
        "gateway.networking.k8s.io", "v1alpha3",
        "backendtlspolicies", True),
    "InferencePool": (
        "inference.networking.x-k8s.io", "v1alpha2",
        "inferencepools", True),
    "ReferenceGrant": (
        "gateway.networking.k8s.io", "v1beta1", "referencegrants", True),
    "Secret": ("", "v1", "secrets", True),
}

#: kinds whose status we own (the reference writes Accepted conditions
#: only on its own API group's objects)
STATUS_KINDS = {
    "AIGatewayRoute", "AIServiceBackend", "BackendSecurityPolicy",
    "MCPRoute", "GatewayConfig", "QuotaPolicy",
}


def resource_path(kind: str, namespace: str = "", name: str = "") -> str:
    group, version, plural, namespaced = RESOURCES[kind]
    prefix = f"/apis/{group}/{version}" if group else f"/api/{version}"
    if namespace and namespaced:
        path = f"{prefix}/namespaces/{namespace}/{plural}"
    else:
        path = f"{prefix}/{plural}"  # cluster-wide (all namespaces)
    if name:
        path += f"/{name}"
    return path


@dataclass
class KubeAuth:
    server: str
    token: str = ""
    ca_data: bytes | None = None
    client_cert: tuple[str, str] | None = None  # (cert path, key path)
    insecure: bool = False

    def ssl_context(self) -> ssl.SSLContext | bool:
        if self.server.startswith("http://"):
            return False  # plain HTTP (tests, kind port-forwards)
        ctx = ssl.create_default_context()
        if self.ca_data:
            ctx.load_verify_locations(cadata=self.ca_data.decode())
        if self.client_cert:
            ctx.load_cert_chain(*self.client_cert)
        if self.insecure:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        return ctx


def _b64_to_tempfile(data: str, suffix: str) -> str:
    f = tempfile.NamedTemporaryFile("wb", suffix=suffix, delete=False)
    f.write(base64.b64decode(data))
    f.close()
    return f.name


def load_kubeconfig(path: str) -> KubeAuth:
    """Parse the standard kubeconfig: current-context → cluster + user.
    Supports token, token-file, client-certificate(-data) and
    certificate-authority(-data)."""
    import yaml

    with open(path, encoding="utf-8") as f:
        doc = yaml.safe_load(f) or {}
    ctx_name = doc.get("current-context", "")
    contexts = {c["name"]: c["context"] for c in doc.get("contexts", [])}
    clusters = {c["name"]: c["cluster"] for c in doc.get("clusters", [])}
    users = {u["name"]: u.get("user", {}) for u in doc.get("users", [])}
    if ctx_name not in contexts:
        raise ValueError(f"kubeconfig {path}: no context {ctx_name!r}")
    ctx = contexts[ctx_name]
    cluster = clusters.get(ctx.get("cluster", ""), {})
    user = users.get(ctx.get("user", ""), {})
    server = cluster.get("server", "")
    if not server:
        raise ValueError(f"kubeconfig {path}: cluster has no server")
    ca_data = None
    if cluster.get("certificate-authority-data"):
        ca_data = base64.b64decode(cluster["certificate-authority-data"])
    elif cluster.get("certificate-authority"):
        with open(cluster["certificate-authority"], "rb") as f:
            ca_data = f.read()
    token = user.get("token", "")
    if not token and user.get("tokenFile"):
        with open(user["tokenFile"], encoding="utf-8") as f:
            token = f.read().strip()
    client_cert = None
    if user.get("client-certificate-data") and user.get("client-key-data"):
        client_cert = (
            _b64_to_tempfile(user["client-certificate-data"], ".crt"),
            _b64_to_tempfile(user["client-key-data"], ".key"),
        )
    elif user.get("client-certificate") and user.get("client-key"):
        client_cert = (user["client-certificate"], user["client-key"])
    return KubeAuth(
        server=server.rstrip("/"), token=token, ca_data=ca_data,
        client_cert=client_cert,
        insecure=bool(cluster.get("insecure-skip-tls-verify")),
    )


_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_auth() -> KubeAuth:
    host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        raise ValueError("not running in-cluster "
                         "(KUBERNETES_SERVICE_HOST unset)")
    # AIGW_SA_DIR: test seam — the composed webhook→sidecar e2e runs
    # the REAL `run kube:in-cluster` args the webhook injects, against
    # a local TLS apiserver, by pointing the token/ca mount elsewhere
    # (the reference's envtest plays the same role)
    sa_dir = os.environ.get("AIGW_SA_DIR", _SA_DIR)
    with open(f"{sa_dir}/token", encoding="utf-8") as f:
        token = f.read().strip()
    with open(f"{sa_dir}/ca.crt", "rb") as f:
        ca = f.read()
    return KubeAuth(server=f"https://{host}:{port}", token=token,
                    ca_data=ca)


class KubeClient:
    """Async REST client for the subset of the API the reconciler needs.
    One aiohttp session, created lazily on the owning loop."""

    def __init__(self, auth: KubeAuth):
        self.auth = auth
        self._session = None

    def _headers(self) -> dict[str, str]:
        h = {"accept": "application/json"}
        if self.auth.token:
            h["authorization"] = f"Bearer {self.auth.token}"
        return h

    async def session(self):
        import aiohttp

        if self._session is None or self._session.closed:
            conn = aiohttp.TCPConnector(ssl=self.auth.ssl_context())
            self._session = aiohttp.ClientSession(
                connector=conn, headers=self._headers())
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def list_resource(
        self, kind: str,
    ) -> tuple[list[dict], str, bool]:
        """(objects cluster-wide, list resourceVersion the watch starts
        from, CRD-installed flag)."""
        s = await self.session()
        url = self.auth.server + resource_path(kind)
        async with s.get(url) as resp:
            if resp.status == 404:
                # CRD not installed: empty + not-installed, not fatal
                # (the reference's manager degrades the same way for
                # optional kinds); the caller polls slowly instead of
                # hot-looping a watch on a missing resource
                return [], "", False
            resp.raise_for_status()
            body = await resp.json()
        items = body.get("items") or []
        for item in items:
            item.setdefault("kind", kind)
            gv = RESOURCES[kind]
            item.setdefault(
                "apiVersion", f"{gv[0]}/{gv[1]}" if gv[0] else gv[1])
        rv = (body.get("metadata") or {}).get("resourceVersion", "")
        return items, rv, True

    async def watch_resource(
        self, kind: str, resource_version: str,
        on_event: Callable[[str, dict], None],
    ) -> None:
        """One watch stream; returns when the server closes it (caller
        re-lists and re-watches — the standard watch loop)."""
        s = await self.session()
        url = (self.auth.server + resource_path(kind)
               + f"?watch=true&resourceVersion={resource_version}"
               + "&allowWatchBookmarks=true")
        async with s.get(url, timeout=None) as resp:
            resp.raise_for_status()
            buf = b""
            async for chunk in resp.content.iter_any():
                buf += chunk
                while b"\n" in buf:
                    line, _, buf = buf.partition(b"\n")
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    etype = ev.get("type", "")
                    obj = ev.get("object") or {}
                    if etype == "BOOKMARK":
                        continue
                    if etype == "ERROR" or etype not in (
                            "ADDED", "MODIFIED", "DELETED"):
                        # in-stream error (e.g. 410 Gone: expired
                        # resourceVersion) carries a Status object that
                        # must never enter the cache — end the stream so
                        # the caller re-lists
                        raise RuntimeError(
                            f"watch {kind}: server sent "
                            f"{etype or 'untyped'} event")
                    on_event(etype, obj)

    async def patch_status(self, obj: dict,
                           conditions: list[dict]) -> bool:
        """merge-patch Accepted conditions onto the object's /status
        (the reference's writeback, controller.go status updates)."""
        kind = obj.get("kind", "")
        meta = obj.get("metadata") or {}
        path = resource_path(
            kind, meta.get("namespace", ""), meta.get("name", ""))
        s = await self.session()
        url = self.auth.server + path + "/status"
        patch = {"status": {"conditions": conditions}}
        async with s.patch(
            url, data=json.dumps(patch).encode(),
            headers={"content-type": "application/merge-patch+json"},
        ) as resp:
            if resp.status >= 400:
                logger.warning(
                    "status patch %s/%s -> %d", kind,
                    meta.get("name", ""), resp.status)
                return False
            return True


class KubeSource:
    """Object cache fed by list+watch on a dedicated thread. The
    reconcile loop reads a consistent snapshot via ``objects()``; status
    patches are shipped back through ``submit()`` onto the same loop."""

    def __init__(self, auth: KubeAuth,
                 kinds: tuple[str, ...] | None = None):
        self.auth = auth
        self.kinds = tuple(kinds or RESOURCES)
        self._cache: dict[tuple[str, str, str], dict] = {}
        self._lock = threading.Lock()
        self._synced = threading.Event()
        self._stopping = threading.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._client: KubeClient | None = None
        self._synced_kinds: set[str] = set()
        self._listeners: list[Callable[[str, dict], None]] = []
        self.generation = 0  # bumped on every cache change

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="kube-source", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopping.set()
        if self._loop is not None:
            self._loop.call_soon_threadsafe(lambda: None)  # wake
        if self._thread is not None:
            self._thread.join(timeout=10)

    def wait_synced(self, timeout: float = 30.0) -> bool:
        return self._synced.wait(timeout)

    def _run(self) -> None:
        asyncio.run(self._main())

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._client = KubeClient(self.auth)
        try:
            tasks = [
                asyncio.create_task(self._kind_loop(kind),
                                    name=f"watch-{kind}")
                for kind in self.kinds
            ]
            while not self._stopping.is_set():
                await asyncio.sleep(0.2)
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
        finally:
            await self._client.close()

    async def _kind_loop(self, kind: str) -> None:
        """list → watch → (on stream close/error) re-list, forever.
        A kind whose CRD is not installed is polled slowly instead of
        watched (installing the CRD later is picked up within 30s)."""
        while not self._stopping.is_set():
            try:
                items, rv, installed = \
                    await self._client.list_resource(kind)
                with self._lock:
                    # resync delta for listeners (client-go replays the
                    # gap on re-list; informers must not silently miss
                    # objects created/deleted while the watch was down)
                    old = {k: v for k, v in self._cache.items()
                           if k[0] == kind}
                    new = {self._key(item): item for item in items}
                    for key in old:
                        del self._cache[key]
                    self._cache.update(new)
                    self.generation += 1
                    listeners = list(self._listeners)
                for key, obj in old.items():
                    if key not in new:
                        for fn in listeners:
                            try:
                                fn("DELETED", obj)
                            except Exception:  # noqa: BLE001
                                logger.exception(
                                    "informer handler failed")
                for key, obj in new.items():
                    prev = old.get(key)
                    if prev != obj:
                        etype = "ADDED" if prev is None else "MODIFIED"
                        for fn in listeners:
                            try:
                                fn(etype, obj)
                            except Exception:  # noqa: BLE001
                                logger.exception(
                                    "informer handler failed")
                self._synced_kinds.add(kind)
                if self._synced_kinds >= set(self.kinds):
                    self._synced.set()
                if not installed:
                    await asyncio.sleep(30.0)
                    continue
                await self._client.watch_resource(kind, rv, self._event)
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001 — network flaps
                logger.warning("watch %s failed: %s; re-listing", kind, e)
                await asyncio.sleep(1.0)

    @staticmethod
    def _key(obj: dict) -> tuple[str, str, str]:
        meta = obj.get("metadata") or {}
        return (obj.get("kind", ""), meta.get("namespace", ""),
                meta.get("name", ""))

    def _event(self, etype: str, obj: dict) -> None:
        if not obj.get("kind"):
            return
        with self._lock:
            if etype == "DELETED":
                self._cache.pop(self._key(obj), None)
            else:  # ADDED / MODIFIED
                self._cache[self._key(obj)] = obj
            self.generation += 1
            listeners = list(self._listeners)
        # informer hook (generated <Kind>Informer classes): called on
        # the watch thread after the cache applied the event
        for fn in listeners:
            try:
                fn(etype, obj)
            except Exception:  # noqa: BLE001 — a handler must not
                logger.exception("informer handler failed")  # kill watch

    def add_listener(self, fn: "Callable[[str, dict], None]") -> None:
        """Subscribe to (event_type, object) pairs — the informer
        contract over the shared watch (client-go informer parity for
        the generated clientset, SURVEY §2.1 #8)."""
        with self._lock:
            self._listeners.append(fn)

    # -- reconcile-side API ----------------------------------------------
    def objects(self) -> list[dict]:
        with self._lock:
            return [dict(o) for o in self._cache.values()]

    def submit(self, coro) -> None:
        """Run a coroutine on the source loop (status patches)."""
        if self._loop is not None and not self._stopping.is_set():
            asyncio.run_coroutine_threadsafe(coro, self._loop)

    @property
    def client(self) -> KubeClient:
        assert self._client is not None
        return self._client


def _pod_namespace() -> str:
    """The pod's own namespace when in-cluster (a Role there is enough
    for the election lease); "default" otherwise. Honors the same
    AIGW_SA_DIR seam as in_cluster_auth — credentials and namespace
    must come from the SAME mount."""
    sa_dir = os.environ.get("AIGW_SA_DIR", _SA_DIR)
    try:
        with open(f"{sa_dir}/namespace", encoding="utf-8") as f:
            return f.read().strip() or "default"
    except OSError:
        return "default"


class KubeReconciler:
    """The Reconciler's admission → compile → quarantine → conditions
    pipeline (config/controller.py), fed from a KubeSource cache instead
    of a manifest directory, with conditions written back onto each
    object's ``status.conditions`` via the API — the reference's
    controller shape (controller.go:117-330): `kubectl get` then shows
    Accepted/NotAccepted exactly like the reference's columns.
    """

    def __init__(self, source: KubeSource,
                 status_path: str | None = None,
                 leader_election: bool | None = None,
                 dry_run: bool = False):
        from aigw_tpu.config.controller import Reconciler

        self.source = source
        # Leader election (default on; AIGW_LEADER_ELECTION=off to
        # disable): every replica serves from its watch cache, but only
        # the elected leader patches object status — the reference's
        # manager runs the same split (controller-runtime leader
        # election, cmd/controller/main.go). Single replica elects
        # itself trivially.
        if leader_election is None:
            leader_election = os.environ.get(
                "AIGW_LEADER_ELECTION", "").lower() != "off"
        self._elector: LeaderElector | None = None
        if leader_election:
            self._elector = LeaderElector(
                source.client,
                lease_name=os.environ.get(
                    "AIGW_LEASE_NAME", "aigw-tpu-status-writer"),
                namespace=os.environ.get("AIGW_LEASE_NAMESPACE",
                                         _pod_namespace()),
            )
            source.submit(self._elector.run())
        # delegate: a Reconciler whose file-reading entry points we
        # bypass; it keeps the condition memory + status file writing
        if status_path is None:
            # per-instance path: two gateways on one host must not
            # clobber each other's report via a shared predictable name
            fd, status_path = tempfile.mkstemp(
                prefix="aigw-kube-status-", suffix=".json")
            os.close(fd)
        self._rec = Reconciler(directory=".", status_path=status_path)
        self._dry_run = dry_run
        self._patched: dict[str, str] = {}  # key → last patched checksum

    def conditions(self) -> dict[str, dict[str, Any]]:
        return self._rec.conditions()

    def not_accepted(self) -> dict[str, dict[str, Any]]:
        return self._rec.not_accepted()

    def shutdown(self) -> None:
        """Stop the election loop and surrender the lease NOW (the renew
        loop may be mid-sleep; waiting for its final iteration would
        race source teardown) so a peer takes over immediately — a
        graceful restart must not leave the cluster writer-less for
        leaseDurationSeconds."""
        if self._elector is not None:
            self._elector.stop()
            self.source.submit(self._elector.release())

    def load(self):
        """Compile the current cluster state; patch changed conditions
        back onto the objects (status subresource, merge-patch)."""
        from aigw_tpu.config.controller import _KIND_RANK, _obj_key

        objects = self.source.objects()
        objects.sort(key=lambda o: _KIND_RANK.get(o.get("kind", ""), 99))
        cfg, errors = self._rec._reconcile(objects)
        if self._rec._update_conditions(objects, errors, {}):
            self._rec._write_status()
        # status writeback: only our API group's kinds, and only when
        # the condition for the object's current content hasn't been
        # pushed yet (otherwise every reconcile tick re-patches and the
        # watch event from our own patch re-triggers the reconcile)
        conds = self._rec.conditions()
        if self._dry_run:
            # validate mode: report, never write onto the cluster
            return cfg
        if self._elector is not None and not self._elector.is_leader:
            # not the leader: serve, but leave status writing (and the
            # patched-stamp cache) to whoever is — if leadership moves
            # here later, unpatched conditions go out then
            return cfg
        for obj in objects:
            kind = obj.get("kind", "")
            if kind not in STATUS_KINDS:
                continue
            key = _obj_key(obj)
            cond = conds.get(key)
            if cond is None:
                continue
            stamp = cond.get("observedChecksum", "") + cond["status"]
            if self._patched.get(key) == stamp:
                continue
            # stamp optimistically (dedupes the in-flight window), but
            # clear on failure so the next reconcile tick retries — a
            # transient 403/blip must not leave `kubectl get` stale
            # forever
            self._patched[key] = stamp
            k8s_cond = {
                "type": "Accepted",
                "status": cond["status"],
                "reason": cond["reason"],
                "message": cond["message"],
                "lastTransitionTime": cond["lastTransitionTime"],
                "observedGeneration": (
                    (obj.get("metadata") or {}).get("generation", 0)),
            }
            self.source.submit(
                self._patch_with_retry(obj, k8s_cond, key, stamp))
        return cfg

    async def _patch_with_retry(self, obj: dict, cond: dict, key: str,
                                stamp: str) -> None:
        try:
            ok = await self.source.client.patch_status(obj, [cond])
        except Exception as e:  # noqa: BLE001 — network flaps
            logger.warning("status patch %s failed: %s", key, e)
            ok = False
        if not ok and self._patched.get(key) == stamp:
            del self._patched[key]


def parse_kube_target(target: str) -> KubeAuth:
    """``kube:<kubeconfig-path>`` / ``kube:in-cluster`` / bare ``kube:``
    (KUBECONFIG env, else ~/.kube/config, else in-cluster)."""
    spec = target[len("kube:"):] if target.startswith("kube:") else target
    if spec == "in-cluster":
        return in_cluster_auth()
    if not spec:
        spec = os.environ.get("KUBECONFIG", "")
        if not spec:
            default = os.path.expanduser("~/.kube/config")
            if os.path.exists(default):
                spec = default
            else:
                return in_cluster_auth()
    return load_kubeconfig(spec)


# ---------------------------------------------------------------------------
# Leader election (coordination.k8s.io Leases)
# ---------------------------------------------------------------------------

LEASE_PATH = "/apis/coordination.k8s.io/v1/namespaces/{ns}/leases"


class LeaderElector:
    """Lease-based leader election — the reference's manager runs with
    LeaderElection enabled so only one controller replica writes status
    (controller-runtime's leasecandidate; cmd/controller/main.go).
    Multiple gateway replicas in kube mode all *serve* from their watch
    caches; only the elected leader patches object status, so replicas
    don't fight over conditions.

    Protocol (client-go parity): acquire the Lease if absent or expired
    (renewTime + leaseDuration < now), renew every ``renew_seconds``
    while held, surrender on stop. Clock skew tolerance comes from the
    duration/renew gap."""

    def __init__(self, client: KubeClient, *, lease_name: str,
                 namespace: str = "default", identity: str = "",
                 lease_seconds: float = 15.0, renew_seconds: float = 5.0):
        import socket
        import uuid as _uuid

        self.client = client
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity or (
            f"{socket.gethostname()}_{_uuid.uuid4().hex[:8]}")
        self.lease_seconds = lease_seconds
        self.renew_seconds = renew_seconds
        self._leader = False
        self._stopping = False
        self._valid_until = 0.0  # when the lease we hold expires

    @property
    def is_leader(self) -> bool:
        return self._leader

    def _became_leader(self) -> None:
        import time as _time

        self._leader = True
        self._valid_until = _time.time() + self.lease_seconds

    def _lease_url(self, name: str = "") -> str:
        url = (self.client.auth.server
               + LEASE_PATH.format(ns=self.namespace))
        return f"{url}/{name}" if name else url

    @staticmethod
    def _now() -> str:
        import time as _time

        return _time.strftime("%Y-%m-%dT%H:%M:%S.000000Z", _time.gmtime())

    @staticmethod
    def _parse_micro_time(value: str) -> float:
        import calendar
        import time as _time

        try:
            base, _, frac = value.partition(".")
            # seconds-precision RFC3339 carries the Z on the base
            # ("...T12:00:00Z"): a parse failure here would read as
            # "expired" and elect a second writer
            secs = calendar.timegm(
                _time.strptime(base.rstrip("Zz"), "%Y-%m-%dT%H:%M:%S"))
            frac = frac.rstrip("Zz")
            if frac.isdigit():
                secs += float(f"0.{frac}")
            return secs
        except (ValueError, AttributeError):
            return 0.0

    async def try_acquire(self) -> bool:
        """One acquire/renew attempt; updates ``is_leader``. Transient
        failures do NOT demote while our own lease is still valid."""
        import time as _time

        s = await self.client.session()
        try:
            async with s.get(self._lease_url(self.lease_name)) as resp:
                if resp.status == 404:
                    lease = None
                else:
                    resp.raise_for_status()
                    lease = await resp.json()
            if lease is None:
                body = {
                    "apiVersion": "coordination.k8s.io/v1",
                    "kind": "Lease",
                    "metadata": {"name": self.lease_name,
                                 "namespace": self.namespace},
                    "spec": self._spec(acquisitions=1),
                }
                async with s.post(
                    self._lease_url(),
                    data=json.dumps(body).encode(),
                    headers={"content-type": "application/json"},
                ) as resp:
                    if resp.status < 300:
                        self._became_leader()
                    else:
                        self._leader = False
                return self._leader
            spec = lease.get("spec") or {}
            holder = spec.get("holderIdentity", "")
            renew = self._parse_micro_time(
                spec.get("renewTime", "") or spec.get("acquireTime", ""))
            duration = float(spec.get("leaseDurationSeconds",
                                      self.lease_seconds))
            expired = renew + duration < _time.time()
            if holder != self.identity and not expired:
                self._leader = False
                return False
            acquisitions = int(spec.get("leaseTransitions", 0) or 0)
            if holder != self.identity:
                acquisitions += 1
            body = dict(lease)
            body["spec"] = self._spec(acquisitions=acquisitions)
            async with s.put(
                self._lease_url(self.lease_name),
                data=json.dumps(body).encode(),
                headers={"content-type": "application/json"},
            ) as resp:
                # a 409 means another candidate updated first — not us
                if resp.status < 300:
                    self._became_leader()
                else:
                    self._leader = False
                    self._valid_until = 0.0
            return self._leader
        except Exception as e:  # noqa: BLE001 — election must not crash
            logger.warning("leader election attempt failed: %s", e)
            # client-go parity: a transient renew failure does not
            # abdicate while the lease we wrote is still unexpired —
            # nobody else can acquire it in that window, so halting our
            # own status writes would leave the cluster writer-less
            if self._leader and _time.time() >= self._valid_until:
                self._leader = False
            return self._leader

    def _spec(self, acquisitions: int) -> dict[str, Any]:
        now = self._now()
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.lease_seconds),
            "acquireTime": now,
            "renewTime": now,
            "leaseTransitions": acquisitions,
        }

    async def run(self) -> None:
        """Renew loop; run on the KubeSource loop via ``submit``."""
        while not self._stopping:
            await self.try_acquire()
            await asyncio.sleep(self.renew_seconds)
        if self._leader:
            await self.release()

    async def release(self) -> None:
        """Surrender the lease (graceful shutdown): blank the holder and
        pre-expire it so a peer can acquire immediately instead of
        waiting out leaseDurationSeconds.

        Guarded (r5): the blank PUT only goes out if we still HOLD the
        lease on the server — a peer that acquired after our lease
        lapsed must not have its fresh lease overwritten by our stale
        surrender (that window would let a THIRD candidate acquire and
        give the cluster two writers). The fetched resourceVersion rides
        the PUT so a real API server 409s any concurrent change."""
        if not self._leader:
            return
        self._leader = False
        self._valid_until = 0.0
        try:
            s = await self.client.session()
            async with s.get(self._lease_url(self.lease_name)) as resp:
                if resp.status != 200:
                    return
                lease = await resp.json()
            holder = (lease.get("spec") or {}).get("holderIdentity", "")
            if holder != self.identity:
                return  # someone else already holds it — not ours to blank
            meta = {"name": self.lease_name, "namespace": self.namespace}
            rv = (lease.get("metadata") or {}).get("resourceVersion")
            if rv is not None:
                meta["resourceVersion"] = rv
            body = {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": meta,
                "spec": {"holderIdentity": "",
                         "leaseDurationSeconds": 1,
                         "renewTime": "1970-01-01T00:00:00.000000Z"},
            }
            async with s.put(
                self._lease_url(self.lease_name),
                data=json.dumps(body).encode(),
                headers={"content-type": "application/json"},
            ) as resp:
                await resp.read()
        except Exception as e:  # noqa: BLE001 — best-effort surrender
            logger.debug("lease release failed: %s", e)

    def stop(self) -> None:
        self._stopping = True
