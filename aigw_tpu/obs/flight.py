"""Engine flight recorder — per-request lifecycle timelines, no backend.

A tracing pipeline answers "why was this request slow" only when a
collector was already attached and sampling. Production incidents rarely
oblige, so tpuserve also keeps a bounded in-process ring of compact
per-request timelines (one :class:`FlightEntry` each) that a replica can
serve AFTER the fact:

- ``GET /debug/requests``        — recent + slow-request summaries
- ``GET /debug/requests/{id}``   — one request's full phase timeline

The same per-request sink (:class:`RequestTrace`) fans events out to the
request's OTel span tree when tracing IS enabled, so the flight recorder
and the exported spans can never disagree about what happened — they are
fed by the identical engine-side calls.

Threading: entries are written by the engine thread and the server's
event loop and read by debug endpoints. Every mutation is a dict/list
append or scalar store (GIL-atomic); the ring itself takes a small lock
only on begin/finish, never per token or per event.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Any

#: per-entry cap on recorded events — a long generation must not grow an
#: unbounded timeline; past the cap only counters advance
MAX_EVENTS = 48

#: decode windows individually recorded per request (the rest aggregate)
MAX_WINDOW_EVENTS = 8


@dataclass
class FlightEntry:
    """One request's compact timeline. Times are milliseconds relative
    to ``t0`` (request arrival at the server); -1.0 = not reached."""

    rid: str
    model: str = ""
    trace_id: str = ""
    span_id: str = ""
    ts: float = field(default_factory=time.time)  # wall clock at arrival
    t0: float = field(default_factory=time.monotonic)
    prompt_tokens: int = 0
    max_tokens: int = 0
    stream: bool = False
    # phase timings (ms)
    queue_wait_ms: float = -1.0
    prefill_ms: float = -1.0
    ttft_ms: float = -1.0  # arrival → first engine token emit
    total_ms: float = -1.0
    tokens_out: int = 0
    decode_windows: int = 0
    spec_accepted: int = 0
    # grammar-constrained decoding (ISSUE 9): windows cut at a mask
    # boundary for this request (each ≈ two windows of slot time —
    # the per-request view of tpuserve_constraint_rollbacks_total)
    constraint_rollbacks: int = 0
    transfer_ms: float = 0.0
    finish: str = ""  # "" = in flight
    admission: dict[str, Any] = field(default_factory=dict)
    events: list[tuple[str, float, dict]] = field(default_factory=list)
    events_dropped: int = 0

    def rel_ms(self) -> float:
        return (time.monotonic() - self.t0) * 1e3

    def event(self, name: str, **attrs: Any) -> None:
        if len(self.events) >= MAX_EVENTS:
            self.events_dropped += 1
            return
        self.events.append((name, round(self.rel_ms(), 3), attrs))

    def summary(self) -> dict[str, Any]:
        return {
            "id": self.rid,
            "model": self.model,
            "trace_id": self.trace_id,
            "ts": self.ts,
            "prompt_tokens": self.prompt_tokens,
            "tokens_out": self.tokens_out,
            "queue_wait_ms": round(self.queue_wait_ms, 3),
            "prefill_ms": round(self.prefill_ms, 3),
            "ttft_ms": round(self.ttft_ms, 3),
            "total_ms": round(self.total_ms, 3),
            "finish": self.finish or "in_flight",
        }

    def detail(self) -> dict[str, Any]:
        out = self.summary()
        out.update(
            span_id=self.span_id,
            max_tokens=self.max_tokens,
            stream=self.stream,
            decode_windows=self.decode_windows,
            spec_accepted=self.spec_accepted,
            constraint_rollbacks=self.constraint_rollbacks,
            transfer_ms=round(self.transfer_ms, 3),
            admission=self.admission,
            events=[
                {"name": n, "t_ms": t, **({"attrs": a} if a else {})}
                for n, t, a in self.events
            ],
            events_dropped=self.events_dropped,
        )
        return out


class FlightRecorder:
    """Bounded ring of :class:`FlightEntry` plus a rolling slow-request
    log. The ring evicts oldest-first; eviction SPARES entries currently
    held by the slow log (worst-N by TTFT and by queue wait), so "the
    slowest request of the last hour" survives an hour of fast traffic."""

    def __init__(self, capacity: int = 256, slow_n: int = 16):
        self.capacity = max(1, capacity)
        self.slow_n = max(1, slow_n)
        self._ring: "collections.OrderedDict[str, FlightEntry]" = (
            collections.OrderedDict()
        )
        # separate retention for the worst finished requests
        self._slow_ttft: list[FlightEntry] = []
        self._slow_queue: list[FlightEntry] = []
        self._lock = threading.Lock()

    # -- write side -------------------------------------------------------
    def begin(self, rid: str, **fields: Any) -> FlightEntry:
        entry = FlightEntry(rid=rid, **fields)
        with self._lock:
            self._ring[rid] = entry
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)
        return entry

    def finish(self, entry: FlightEntry, finish: str,
               tokens_out: int | None = None) -> None:
        entry.finish = finish or "stop"
        if tokens_out is not None:
            entry.tokens_out = tokens_out
        entry.total_ms = entry.rel_ms()
        with self._lock:
            self._note_slow(self._slow_ttft, entry,
                            lambda e: e.ttft_ms)
            self._note_slow(self._slow_queue, entry,
                            lambda e: e.queue_wait_ms)

    def _note_slow(self, worst: list[FlightEntry], entry: FlightEntry,
                   key) -> None:
        if key(entry) < 0:
            return  # phase never reached (errored before it)
        worst.append(entry)
        worst.sort(key=key, reverse=True)
        del worst[self.slow_n:]

    # -- read side --------------------------------------------------------
    def get(self, rid: str) -> FlightEntry | None:
        with self._lock:
            e = self._ring.get(rid)
            if e is not None:
                return e
            for worst in (self._slow_ttft, self._slow_queue):
                for cand in worst:
                    if cand.rid == rid:
                        return cand
        return None

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            recent = [e.summary() for e in
                      reversed(list(self._ring.values()))]
            slow_ttft = [e.summary() for e in self._slow_ttft]
            slow_queue = [e.summary() for e in self._slow_queue]
        return {
            "capacity": self.capacity,
            "recent": recent,
            "slow_by_ttft": slow_ttft,
            "slow_by_queue_wait": slow_queue,
        }

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


class RequestTrace:
    """Per-request lifecycle sink handed to the engine via
    ``GenRequest.trace``: every call lands in the flight-recorder entry
    and, when tracing is enabled, in the request's span tree (child
    spans for queue-wait / prefill / decode, events for the rest).

    Called from the engine thread — methods must be cheap and must never
    raise into the engine loop (a telemetry bug aborting every in-flight
    request would be worse than no telemetry). Phase HISTOGRAMS are
    observed by the engine itself (they cover untraced requests too);
    this sink only records timelines and spans."""

    __slots__ = ("entry", "tracer", "span", "_decode_span")

    def __init__(self, entry: FlightEntry, tracer: Any = None,
                 span: Any = None):
        self.entry = entry
        self.tracer = tracer
        self.span = span
        self._decode_span = None

    @property
    def trace_id(self) -> str:
        return self.entry.trace_id

    def _child(self, name: str, start_ns: int | None = None):
        if self.span is None or self.tracer is None:
            return None
        child = self.tracer.start_span(name, self.span.context)
        if start_ns is not None:
            child.start_ns = start_ns
        return child

    def _backdated_child(self, name: str, dur_ms: float,
                         attrs: dict) -> None:
        """Emit a completed child span covering the last ``dur_ms``."""
        child = self._child(
            name, start_ns=time.time_ns() - int(dur_ms * 1e6))
        if child is None:
            return
        child.attributes.update(attrs)
        child.end()

    # -- engine-side lifecycle calls --------------------------------------
    def queue_wait(self, ms: float) -> None:
        try:
            self.entry.queue_wait_ms = ms
            self._backdated_child("engine.queue_wait", ms,
                                  {"tpuserve.queue_wait_ms": round(ms, 3)})
        except Exception:  # noqa: BLE001 — never into the engine loop
            pass

    def admission(self, **attrs: Any) -> None:
        try:
            self.entry.admission.update(attrs)
            self.entry.event("admission", **attrs)
            if self.span is not None:
                self.span.add_event("admission", attrs)
        except Exception:  # noqa: BLE001
            pass

    def event(self, name: str, **attrs: Any) -> None:
        try:
            self.entry.event(name, **attrs)
            if self.span is not None:
                self.span.add_event(name, attrs)
        except Exception:  # noqa: BLE001
            pass

    def prefill(self, ms: float, **attrs: Any) -> None:
        try:
            self.entry.prefill_ms = ms
            self.entry.admission.update(attrs)
            self._backdated_child(
                "engine.prefill", ms,
                {"tpuserve.prefill_ms": round(ms, 3),
                 **{f"tpuserve.{k}": v for k, v in attrs.items()}})
        except Exception:  # noqa: BLE001
            pass

    def first_token(self) -> None:
        try:
            self.entry.ttft_ms = self.entry.rel_ms()
            self.entry.event("first_token")
            if self.span is not None:
                self.span.add_event("first_token")
        except Exception:  # noqa: BLE001
            pass

    def decode_window(self, k: int, lean: bool, draft: int) -> None:
        try:
            e = self.entry
            e.decode_windows += 1
            if e.decode_windows <= MAX_WINDOW_EVENTS:
                attrs = {"k": k, "program": "lean" if lean else "full",
                         "spec_rung": draft}
                e.event("decode_window", **attrs)
                if self._decode_span is None and self.span is not None:
                    self._decode_span = self._child("engine.decode")
                if self._decode_span is not None:
                    self._decode_span.add_event("decode_window", attrs)
        except Exception:  # noqa: BLE001
            pass

    def spec_window(self, proposed: int, accepted: int) -> None:
        try:
            self.entry.spec_accepted += accepted
            if self.entry.decode_windows <= MAX_WINDOW_EVENTS:
                self.event("spec_accept", proposed=proposed,
                           accepted=accepted)
        except Exception:  # noqa: BLE001
            pass

    def constraint_rollback(self) -> None:
        """One decode window cut at a grammar mask boundary — the slot
        rolled back to its last accepted token (ISSUE 9)."""
        try:
            self.entry.constraint_rollbacks += 1
            if self.entry.constraint_rollbacks <= MAX_WINDOW_EVENTS:
                self.event("constraint_rollback")
        except Exception:  # noqa: BLE001
            pass

    def transfer(self, ms: float) -> None:
        try:
            self.entry.transfer_ms += ms
        except Exception:  # noqa: BLE001
            pass

    def tokens(self, n: int) -> None:
        try:
            self.entry.tokens_out += n
        except Exception:  # noqa: BLE001
            pass

    def engine_finish(self, reason: str) -> None:
        """EOS / length / cancel seen by the engine (the server still
        owns the entry's finalization — its view includes stop-string
        trims and client disconnects the engine never sees)."""
        try:
            self.event("engine_finish", reason=reason)
            if self._decode_span is not None:
                self._decode_span.set(
                    "tpuserve.decode_windows", self.entry.decode_windows)
                self._decode_span.set(
                    "tpuserve.spec_accepted", self.entry.spec_accepted)
                self._decode_span.end()
                self._decode_span = None
        except Exception:  # noqa: BLE001
            pass
