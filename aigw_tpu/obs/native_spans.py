"""OTel spans + costs for native-path requests.

The C++ core (native/proxy_core.cpp) relays eligible requests without
ever entering Python — fast, but round 3 left those requests spanless
and costless (VERDICT: "the fastest requests are the least traceable").
Instead of teaching the core OTLP, the core writes one JSON access-log
line per request carrying the span identity it already used on the wire
(it generates a child span id and re-parents the upstream's
``traceparent``), and this tailer turns each line into a real OTel span
through the gateway's existing exporter (protobuf OTLP / console) and —
when the config defines LLMRequestCosts — computes the CEL costs from
the mined token usage post-hoc, feeding the same cost sink the Python
path uses. The reference gets the equivalent for free because Envoy's
filters run in-process; here the access-log pipe is the cheap
side-channel (VERDICT r3 item 4 suggested exactly this).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Callable

from aigw_tpu.obs.tracing import Span, SpanContext, Tracer

logger = logging.getLogger(__name__)

_OPERATIONS = {
    "/v1/chat/completions": "chat",
    "/v1/completions": "text_completion",
    "/v1/embeddings": "embeddings",
    "/v1/messages": "chat",
}


class NativeLogTailer:
    """Tail the core's JSON-lines access log; emit a span per line.

    Rotation-safe: the file is reopened when its inode changes or it
    shrinks. Lines written before ``start()`` are skipped (history is
    not replayed as fresh telemetry)."""

    def __init__(
        self,
        path: str,
        tracer: Tracer,
        cost_fn: Callable[[dict[str, Any]], None] | None = None,
        poll_interval: float = 0.3,
        from_start: bool = False,
    ):
        self.path = path
        self.tracer = tracer
        self.cost_fn = cost_fn
        self.poll_interval = poll_interval
        self._from_start = from_start
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="native-span-tailer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- tail loop --------------------------------------------------------
    def _run(self) -> None:
        f = None
        ino = -1
        try:
            while not self._stop.is_set():
                if f is None:
                    try:
                        f = open(self.path, "r", encoding="utf-8",
                                 errors="replace")
                        ino = os.fstat(f.fileno()).st_ino
                        if not self._from_start:
                            f.seek(0, os.SEEK_END)
                        self._from_start = False  # reopens read fully
                    except FileNotFoundError:
                        self._stop.wait(self.poll_interval)
                        continue
                pos = f.tell()  # cookie BEFORE the read: len(line) is
                # chars, not bytes, and non-ASCII log content would skew
                # arithmetic on the opaque text-mode offset
                line = f.readline()
                if line:
                    if line.endswith("\n"):
                        self._handle_line(line)
                    else:
                        # torn tail: rewind and wait for the writer
                        f.seek(pos)
                        self._stop.wait(self.poll_interval)
                    continue
                # EOF: check rotation/truncation, then wait
                try:
                    st = os.stat(self.path)
                    if st.st_ino != ino or st.st_size < f.tell():
                        f.close()
                        f = None
                        self._from_start = True
                        continue
                except FileNotFoundError:
                    f.close()
                    f = None
                self._stop.wait(self.poll_interval)
        finally:
            if f is not None:
                f.close()

    def _handle_line(self, line: str) -> None:
        try:
            entry = json.loads(line)
        except json.JSONDecodeError:
            return
        if not isinstance(entry, dict) or not entry.get("native"):
            return
        try:
            self._emit(entry)
        except Exception:  # noqa: BLE001 — telemetry must never crash
            logger.debug("native span emit failed", exc_info=True)

    def _emit(self, entry: dict[str, Any]) -> None:
        trace_id = str(entry.get("trace_id", ""))
        span_id = str(entry.get("span_id", ""))
        usage = entry.get("usage") or {}
        if self.cost_fn is not None and usage:
            self.cost_fn(entry)
        if not self.tracer.enabled or len(trace_id) != 32 \
                or len(span_id) != 16:
            return
        if entry.get("sampled") is False:
            return
        start_ns = int(entry.get("start_unix_ns", 0) or 0)
        duration_ms = float(entry.get("duration_ms", 0) or 0)
        path = str(entry.get("path", ""))
        model = str(entry.get("model", ""))
        operation = _OPERATIONS.get(path, "chat")
        span = Span(
            name=f"{operation} {model}".strip(),
            context=SpanContext(trace_id=trace_id, span_id=span_id),
            parent_span_id=str(entry.get("parent_span_id", "")),
            start_ns=start_ns,
            attributes={
                "gen_ai.operation.name": operation,
                "gen_ai.request.model": model,
                "gen_ai.provider.name": str(entry.get("backend", "")),
                "http.response.status_code": int(
                    entry.get("status", 0) or 0),
                "aigw.native": True,
                "aigw.relay.result": str(entry.get("result", "")),
            },
        )
        if usage.get("prompt_tokens"):
            span.attributes["gen_ai.usage.input_tokens"] = int(
                usage["prompt_tokens"])
        if usage.get("completion_tokens"):
            span.attributes["gen_ai.usage.output_tokens"] = int(
                usage["completion_tokens"])
        status = int(entry.get("status", 0) or 0)
        if status >= 500 or entry.get("result") == "upstream_broken":
            span.status_error = f"upstream status {status}"
        span.end_ns = start_ns + int(duration_ms * 1e6)
        self.tracer._export(span)


def make_cost_fn(get_runtime, cost_sink) -> Callable[[dict[str, Any]], None]:
    """Cost computation for native-path requests: CEL costs from the
    mined usage counters, post-hoc (the round-3 gap that kept
    cost-bearing rules Python-only). ``get_runtime`` is late-bound so
    config hot reloads pick up new cost programs."""
    from aigw_tpu.gateway.costs import TokenUsage

    def cost_fn(entry: dict[str, Any]) -> None:
        runtime = get_runtime()
        if runtime is None:
            return
        usage = entry.get("usage") or {}
        tu = TokenUsage(
            input_tokens=int(usage.get("prompt_tokens", 0) or 0),
            output_tokens=int(usage.get("completion_tokens", 0) or 0),
            total_tokens=int(usage.get("total_tokens", 0) or 0),
        )
        model = str(entry.get("model", ""))
        backend = str(entry.get("backend", ""))
        # native rules never carry route-level costs (they stay on the
        # Python path), so the global calculator is the right one
        costs = runtime.cost_calculator_for("").calculate(
            tu, model=model, backend=backend, route_name="")
        if costs and cost_sink is not None:
            cost_sink(costs, {"model": model, "backend": backend,
                              "route": "", "native": "true"})

    return cost_fn
