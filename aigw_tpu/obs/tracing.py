"""Distributed tracing with OTel GenAI semantic conventions.

Equivalent of the reference's internal/tracing (tracing.go:116-230):
env-driven configuration, W3C ``traceparent`` propagation to upstreams,
per-request spans carrying GenAI attributes (model, token usage, TTFT).

The environment provides only the OTel *API* package, not the SDK, so the
span pipeline here is self-contained: spans are exported as JSON lines
(console) or OTLP/HTTP JSON (``/v1/traces``) from a background flusher.

Env vars (the reference honors the same ones):
- ``OTEL_SDK_DISABLED=true``            — tracing off
- ``OTEL_TRACES_EXPORTER=console|otlp|none``
- ``OTEL_EXPORTER_OTLP_ENDPOINT``       — e.g. http://collector:4318
- ``OTEL_SERVICE_NAME``                 — default aigw-tpu
"""

from __future__ import annotations

import json
import os
import queue
import re
import secrets
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


@dataclass
class SpanContext:
    trace_id: str  # 32 hex
    span_id: str  # 16 hex
    sampled: bool = True

    def traceparent(self) -> str:
        flags = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flags}"

    @staticmethod
    def parse(header: str) -> "SpanContext | None":
        m = _TRACEPARENT_RE.match(header.strip())
        if not m:
            return None
        _, trace_id, span_id, flags = m.groups()
        if trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        return SpanContext(trace_id=trace_id, span_id=span_id,
                           sampled=bool(int(flags, 16) & 1))


@dataclass
class Span:
    name: str
    context: SpanContext
    parent_span_id: str = ""
    start_ns: int = field(default_factory=time.time_ns)
    end_ns: int = 0
    attributes: dict[str, Any] = field(default_factory=dict)
    # (name, time_ns, attributes) — attrs {} for plain markers
    events: list[tuple[str, int, dict]] = field(default_factory=list)
    status_error: str = ""
    _tracer: "Tracer | None" = None

    def set(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def add_event(self, name: str,
                  attributes: dict[str, Any] | None = None) -> None:
        self.events.append((name, time.time_ns(), attributes or {}))

    def record_error(self, message: str) -> None:
        self.status_error = message

    def end(self) -> None:
        self.end_ns = time.time_ns()
        if self._tracer is not None:
            self._tracer._export(self)


class Propagators:
    """Context propagation per ``OTEL_PROPAGATORS`` (reference
    tracing.go uses contrib autoprop, same env contract): comma list of
    ``tracecontext`` (W3C traceparent), ``b3`` (single header),
    ``b3multi`` (X-B3-* headers). Default matches the OTel SDK:
    ``tracecontext,baggage`` (baggage is a no-op here). Extraction tries
    each configured propagator in order; injection writes all of them."""

    def __init__(self, spec: str = ""):
        spec = spec or os.environ.get("OTEL_PROPAGATORS",
                                      "tracecontext,baggage")
        self.names = [p.strip().lower() for p in spec.split(",")
                      if p.strip() and p.strip().lower() != "baggage"]
        if not self.names:
            self.names = ["tracecontext"]

    def extract(self, headers: dict[str, str]) -> "SpanContext | None":
        for name in self.names:
            ctx = None
            if name == "tracecontext":
                ctx = SpanContext.parse(headers.get("traceparent", ""))
            elif name == "b3":
                ctx = self._parse_b3_single(headers.get("b3", ""))
            elif name == "b3multi":
                ctx = self._parse_b3_multi(headers)
            if ctx is not None:
                return ctx
        return None

    def inject(self, ctx: "SpanContext", headers: dict[str, str]) -> None:
        for name in self.names:
            if name == "tracecontext":
                headers["traceparent"] = ctx.traceparent()
            elif name == "b3":
                headers["b3"] = (
                    f"{ctx.trace_id}-{ctx.span_id}-"
                    f"{'1' if ctx.sampled else '0'}"
                )
            elif name == "b3multi":
                headers["x-b3-traceid"] = ctx.trace_id
                headers["x-b3-spanid"] = ctx.span_id
                headers["x-b3-sampled"] = "1" if ctx.sampled else "0"

    @staticmethod
    def _hex_id(value: str, width: int) -> str:
        """Lowercased id iff exactly ``width`` hex chars (64-bit B3
        trace ids are left-padded first); "" otherwise. Ids flow into
        protobuf export via bytes.fromhex, so non-hex input must be
        rejected here, not crash the flusher."""
        value = value.strip().lower()
        if width == 32 and len(value) == 16:
            value = "0" * 16 + value
        if len(value) != width or not all(
                c in "0123456789abcdef" for c in value):
            return ""
        return value

    @classmethod
    def _parse_b3_single(cls, value: str) -> "SpanContext | None":
        parts = value.strip().split("-")
        if len(parts) < 2:
            return None
        trace_id = cls._hex_id(parts[0], 32)
        span_id = cls._hex_id(parts[1], 16)
        if not trace_id or not span_id:
            return None
        sampled = len(parts) < 3 or parts[2] not in ("0", "false")
        return SpanContext(trace_id=trace_id, span_id=span_id,
                           sampled=sampled)

    @classmethod
    def _parse_b3_multi(cls, headers: dict[str, str]) -> "SpanContext | None":
        trace_id = cls._hex_id(headers.get("x-b3-traceid", ""), 32)
        span_id = cls._hex_id(headers.get("x-b3-spanid", ""), 16)
        if not trace_id or not span_id:
            return None
        sampled = headers.get("x-b3-sampled", "1") not in ("0", "false")
        return SpanContext(trace_id=trace_id, span_id=span_id,
                           sampled=sampled)


class Tracer:
    """Span factory + background exporter."""

    def __init__(self, exporter: str = "", service_name: str = ""):
        disabled = os.environ.get("OTEL_SDK_DISABLED", "").lower() == "true"
        self.exporter = (
            "none" if disabled
            else (exporter or os.environ.get("OTEL_TRACES_EXPORTER",
                                             "none")).lower()
        )
        self.service_name = (
            service_name or os.environ.get("OTEL_SERVICE_NAME", "aigw-tpu")
        )
        # standard OTLP protocol selection (the SDK's env contract):
        # protobuf is the default a stock collector expects; http/json
        # kept for the round-1..3 consumers; grpc completes the
        # reference's autoexport matrix (tracing.go:116-230, :4317)
        self.protocol = os.environ.get(
            "OTEL_EXPORTER_OTLP_TRACES_PROTOCOL",
            os.environ.get("OTEL_EXPORTER_OTLP_PROTOCOL",
                           "http/protobuf"),
        ).lower()
        self.endpoint = os.environ.get(
            "OTEL_EXPORTER_OTLP_ENDPOINT",
            "http://127.0.0.1:4317" if self.protocol == "grpc"
            else "http://127.0.0.1:4318",
        ).rstrip("/")
        self._grpc_call = None  # lazily-built TraceService/Export stub
        self.propagators = Propagators()
        self._q: "queue.Queue[Span]" = queue.Queue(maxsize=4096)
        self._flusher: threading.Thread | None = None
        if self.exporter == "otlp":
            self._flusher = threading.Thread(
                target=self._flush_loop, name="otlp-flusher", daemon=True
            )
            self._flusher.start()

    @property
    def enabled(self) -> bool:
        return self.exporter in ("console", "otlp")

    def start_span(
        self, name: str, parent: SpanContext | None = None
    ) -> Span:
        # parent-based sampling: honor the caller's opt-out (flags 00)
        sampled = parent.sampled if parent else True
        ctx = SpanContext(
            trace_id=parent.trace_id if parent else secrets.token_hex(16),
            span_id=secrets.token_hex(8),
            sampled=sampled,
        )
        return Span(
            name=name,
            context=ctx,
            parent_span_id=parent.span_id if parent else "",
            _tracer=self if self.enabled and sampled else None,
        )

    # -- export -----------------------------------------------------------
    def _export(self, span: Span) -> None:
        if self.exporter == "console":
            print(json.dumps(self._to_dict(span)), file=sys.stderr)
        elif self.exporter == "otlp":
            try:
                self._q.put_nowait(span)
            except queue.Full:
                pass  # drop rather than block the data plane

    def _to_dict(self, s: Span) -> dict[str, Any]:
        return {
            "name": s.name,
            "traceId": s.context.trace_id,
            "spanId": s.context.span_id,
            "parentSpanId": s.parent_span_id,
            "startTimeUnixNano": s.start_ns,
            "endTimeUnixNano": s.end_ns,
            "attributes": s.attributes,
            "events": [
                {"name": n, "timeUnixNano": t,
                 **({"attributes": a} if a else {})}
                for n, t, a in s.events
            ],
            "status": {"code": 2, "message": s.status_error}
            if s.status_error
            else {"code": 1},
            "service": self.service_name,
        }

    def _flush_loop(self) -> None:
        import urllib.request

        while True:
            spans = [self._q.get()]
            try:
                while len(spans) < 128:
                    spans.append(self._q.get_nowait())
            except queue.Empty:
                pass
            try:
                if self.protocol == "grpc":
                    # same ExportTraceServiceRequest bytes, carried as a
                    # gRPC unary call instead of an HTTP POST — grpcio
                    # handles the framing; the hand-rolled encoder stays
                    # the single wire-format source
                    from aigw_tpu.obs.otlp_proto import encode_traces

                    self._grpc_export(
                        encode_traces(spans, self.service_name))
                    continue
                if self.protocol == "http/json":
                    data = json.dumps(self._otlp_payload(spans)).encode()
                    ctype = "application/json"
                else:  # http/protobuf — the standard default
                    from aigw_tpu.obs.otlp_proto import encode_traces

                    data = encode_traces(spans, self.service_name)
                    ctype = "application/x-protobuf"
                req = urllib.request.Request(
                    f"{self.endpoint}/v1/traces",
                    data=data,
                    headers={"content-type": ctype},
                )
                urllib.request.urlopen(req, timeout=5)
            except Exception:  # noqa: BLE001 — telemetry must never crash
                pass

    def _grpc_export(self, data: bytes) -> None:
        """opentelemetry.proto.collector.trace.v1.TraceService/Export
        over an insecure channel (OTEL_EXPORTER_OTLP_ENDPOINT, default
        :4317 — the collector's stock gRPC port)."""
        if self._grpc_call is None:
            import grpc

            target = self.endpoint
            secure = target.startswith("https://")
            for prefix in ("http://", "https://"):
                if target.startswith(prefix):
                    target = target[len(prefix):]
            # OTLP spec: an https scheme selects a TLS channel — a
            # silent plaintext downgrade would either leak span data or
            # fail every flush invisibly
            channel = (
                grpc.secure_channel(target, grpc.ssl_channel_credentials())
                if secure else grpc.insecure_channel(target)
            )
            self._grpc_call = channel.unary_unary(
                "/opentelemetry.proto.collector.trace.v1."
                "TraceService/Export",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
        self._grpc_call(data, timeout=5)

    def _otlp_payload(self, spans: list[Span]) -> dict[str, Any]:
        def attr(k: str, v: Any) -> dict[str, Any]:
            if isinstance(v, bool):
                val: dict[str, Any] = {"boolValue": v}
            elif isinstance(v, int):
                val = {"intValue": str(v)}
            elif isinstance(v, float):
                val = {"doubleValue": v}
            else:
                val = {"stringValue": str(v)}
            return {"key": k, "value": val}

        return {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [
                            attr("service.name", self.service_name)
                        ]
                    },
                    "scopeSpans": [
                        {
                            "scope": {"name": "aigw_tpu"},
                            "spans": [
                                {
                                    **{
                                        k: v
                                        for k, v in self._to_dict(s).items()
                                        if k in ("name", "traceId", "spanId",
                                                 "parentSpanId", "status")
                                    },
                                    "kind": 3,  # CLIENT
                                    "startTimeUnixNano": str(s.start_ns),
                                    "endTimeUnixNano": str(s.end_ns),
                                    "attributes": [
                                        attr(k, v)
                                        for k, v in s.attributes.items()
                                    ],
                                    "events": [
                                        {"name": n,
                                         "timeUnixNano": str(t),
                                         "attributes": [
                                             attr(k, v)
                                             for k, v in a.items()
                                         ]}
                                        for n, t, a in s.events
                                    ],
                                }
                                for s in spans
                            ],
                        }
                    ],
                }
            ]
        }


def genai_attributes(
    *,
    operation: str,
    request_model: str,
    response_model: str = "",
    backend: str = "",
    input_tokens: int = 0,
    output_tokens: int = 0,
    streaming: bool = False,
) -> dict[str, Any]:
    """GenAI semconv span attributes (reference openinference/* builders)."""
    attrs: dict[str, Any] = {
        "gen_ai.operation.name": operation,
        "gen_ai.request.model": request_model,
        "gen_ai.provider.name": backend,
        "llm.is_streaming": streaming,
    }
    if response_model:
        attrs["gen_ai.response.model"] = response_model
    if input_tokens:
        attrs["gen_ai.usage.input_tokens"] = input_tokens
    if output_tokens:
        attrs["gen_ai.usage.output_tokens"] = output_tokens
    return attrs


def parse_header_attribute_mapping(spec: str) -> list[tuple[str, str]]:
    """``header:attribute[,header:attribute...]`` → mapping list
    (reference internalapi.ParseRequestHeaderAttributeMapping; default
    ``agent-session-id:session.id``). Configured via
    ``AIGW_HEADER_ATTRIBUTES``."""
    out: list[tuple[str, str]] = []
    for pair in spec.split(","):
        pair = pair.strip()
        if not pair:
            continue
        header, _, attr = pair.partition(":")
        if header and attr:
            out.append((header.strip().lower(), attr.strip()))
    return out


DEFAULT_HEADER_ATTRIBUTES = "agent-session-id:session.id"


def header_attributes(
    headers: dict[str, str], mapping: list[tuple[str, str]]
) -> dict[str, str]:
    return {
        attr: headers[h] for h, attr in mapping if h in headers
    }
