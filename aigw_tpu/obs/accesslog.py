"""Structured JSON access logs.

One JSON line per gateway request, carrying the same enrichment the
reference injects into Envoy's access log via dynamic metadata
(``internal/extproc/util.go`` buildRequestHeaderDynamicMetadata →
``io.envoy.ai_gateway`` namespace: model name, backend name, route name,
plus per-request costs and token usage recorded at end-of-stream).

Configured via ``AIGW_ACCESS_LOG``:
- unset/empty/``off`` — disabled
- ``stdout`` / ``stderr`` — write to that stream
- any other value — append to that file path
"""

from __future__ import annotations

import json
import logging
import os
import queue
import sys
import threading
import time
from typing import Any, IO

logger = logging.getLogger(__name__)


class AccessLogger:
    """Lines are handed to a daemon writer thread — a synchronous
    write+flush per request on the event loop would be exactly the
    hot-path tax that dropping aiohttp's access log removed. The queue
    is bounded; overflow drops lines rather than stalling requests."""

    _QUEUE_MAX = 8192

    def __init__(self, target: str | None = None):
        if target is None:
            target = os.environ.get("AIGW_ACCESS_LOG", "")
        self._target = (target or "").strip()
        self._fp: IO[str] | None = None
        self._q: "queue.Queue[str]" = queue.Queue(maxsize=self._QUEUE_MAX)
        if not self._target or self._target.lower() == "off":
            return
        if self._target == "stdout":
            self._fp = sys.stdout
        elif self._target == "stderr":
            self._fp = sys.stderr
        else:
            try:
                self._fp = open(self._target, "a", encoding="utf-8")
            except OSError as e:
                logger.warning("access log %s unavailable: %s",
                               self._target, e)
        if self._fp is not None:
            threading.Thread(target=self._writer, name="access-log",
                             daemon=True).start()

    def _writer(self) -> None:
        while True:
            lines = [self._q.get()]
            # batch whatever else is queued before flushing once
            try:
                while True:
                    lines.append(self._q.get_nowait())
            except queue.Empty:
                pass
            try:
                for line in lines:
                    self._fp.write(line)
                self._fp.flush()
            except (OSError, ValueError):
                pass  # telemetry must never crash the data plane
            finally:
                for _ in lines:
                    self._q.task_done()

    def drain(self, timeout: float = 5.0) -> None:
        """Block until queued lines are written (tests, shutdown)."""
        if self._fp is None:
            return
        deadline = time.monotonic() + timeout
        while self._q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.01)

    @property
    def enabled(self) -> bool:
        return self._fp is not None

    def log(
        self,
        *,
        method: str,
        path: str,
        status: int,
        duration_ms: float,
        route: str = "",
        backend: str = "",
        model: str = "",
        response_model: str = "",
        stream: bool = False,
        input_tokens: int = 0,
        output_tokens: int = 0,
        total_tokens: int = 0,
        cached_tokens: int = 0,
        costs: dict[str, int] | None = None,
        error_type: str = "",
        client: str = "",
        trace_id: str = "",
        span_id: str = "",
        request_id: str = "",
        upstream_request_id: str = "",
        attempts: int = 0,
        decision: dict[str, Any] | None = None,
    ) -> None:
        if self._fp is None:
            return
        entry: dict[str, Any] = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "method": method,
            "path": path,
            "status": status,
            "duration_ms": round(duration_ms, 2),
            "route": route,
            "backend": backend,
            "model": model,
        }
        if response_model and response_model != model:
            entry["response_model"] = response_model
        if stream:
            entry["stream"] = True
        usage = {
            k: v for k, v in (
                ("input", input_tokens), ("output", output_tokens),
                ("total", total_tokens), ("cached", cached_tokens),
            ) if v
        }
        if usage:
            entry["usage"] = usage
        if costs:
            entry["costs"] = costs
        if error_type:
            entry["error"] = error_type
        if client:
            entry["client"] = client
        if trace_id:
            entry["trace_id"] = trace_id
        if span_id:
            # with trace_id, joins the line against the exported span
            # tree AND (via the replica's matching trace id) tpuserve's
            # /debug/requests flight-recorder timelines
            entry["span_id"] = span_id
        if request_id:
            entry["request_id"] = request_id
        if upstream_request_id:
            # the serving replica's own id (x-aigw-request-id): the
            # direct key into /debug/requests/{id} on that replica
            entry["upstream_request_id"] = upstream_request_id
        if attempts > 1:
            entry["attempts"] = attempts
        if decision:
            # routing outcome (ISSUE 12): the compact view of the
            # gateway's decision-ring entry — chosen endpoint plus the
            # flags that change what a log reader does next. The full
            # explain stays in /debug/decisions (joined by
            # upstream_request_id), not on every log line.
            d: dict[str, Any] = {}
            if decision.get("chosen"):
                d["endpoint"] = decision["chosen"]
            pick = decision.get("pick") or {}
            for flag in ("kv_fleet_hit", "sticky", "prefix_affinity"):
                if pick.get(flag):
                    d[flag] = True
            if decision.get("shed"):
                d["shed"] = True
            if decision.get("migrated_to"):
                d["migrated_to"] = decision["migrated_to"]
            if d:
                entry["decision"] = d
        try:
            self._q.put_nowait(json.dumps(entry) + "\n")
        except queue.Full:
            pass  # drop rather than block the data plane
