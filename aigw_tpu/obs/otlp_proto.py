"""OTLP/HTTP protobuf encoding — hand-rolled wire format.

The reference exports spans via the standard OTel SDK autoexport
(internal/tracing/tracing.go:116-230), whose default protocol is
OTLP/HTTP **protobuf** on :4318 ``/v1/traces`` with
``content-type: application/x-protobuf``. A stock collector will not
ingest JSON unless explicitly configured, so JSON-only export (rounds
1-3 here) was a fidelity gap (VERDICT r3 missing #4).

This module encodes ``ExportTraceServiceRequest`` directly in protobuf
wire format. The message subset is tiny and frozen (OTLP is a stable
protocol), so a ~100-line encoder beats dragging in a codegen toolchain:

    ExportTraceServiceRequest { repeated ResourceSpans resource_spans=1 }
    ResourceSpans { Resource resource=1; repeated ScopeSpans scope_spans=2 }
    Resource      { repeated KeyValue attributes=1 }
    ScopeSpans    { InstrumentationScope scope=1; repeated Span spans=2 }
    InstrumentationScope { string name=1 }
    Span { bytes trace_id=1; bytes span_id=2; bytes parent_span_id=4;
           string name=5; SpanKind kind=6; fixed64 start=7; fixed64 end=8;
           repeated KeyValue attributes=9; repeated Event events=11;
           Status status=15 }
    Event  { fixed64 time_unix_nano=1; string name=2;
             repeated KeyValue attributes=3 }
    Status { string message=2; StatusCode code=3 }
    KeyValue { string key=1; AnyValue value=2 }
    AnyValue { string_value=1 | bool_value=2 | int_value=3 |
               double_value=4 }

(opentelemetry-proto trace/v1/trace.proto; field numbers verified
against the collector's decoder.)
"""

from __future__ import annotations

import struct
from typing import Any


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _str_field(field: int, s: str) -> bytes:
    return _len_field(field, s.encode("utf-8"))


def _fixed64(field: int, n: int) -> bytes:
    return _tag(field, 1) + struct.pack("<Q", n)


def _varint_field(field: int, n: int) -> bytes:
    return _tag(field, 0) + _varint(n)


def _any_value(v: Any) -> bytes:
    if isinstance(v, bool):
        return _varint_field(2, 1 if v else 0)
    if isinstance(v, int):
        # int_value is a signed varint (zigzag NOT used; negative values
        # encode as 10-byte two's complement per proto3 int64)
        return _tag(3, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)
    if isinstance(v, float):
        return _tag(4, 1) + struct.pack("<d", v)
    return _str_field(1, str(v))


def _key_value(k: str, v: Any) -> bytes:
    return _str_field(1, k) + _len_field(2, _any_value(v))


def _span(s: Any) -> bytes:
    """``s`` is obs.tracing.Span (duck-typed to avoid a cycle)."""
    out = bytearray()
    out += _len_field(1, bytes.fromhex(s.context.trace_id))
    out += _len_field(2, bytes.fromhex(s.context.span_id))
    if s.parent_span_id:
        out += _len_field(4, bytes.fromhex(s.parent_span_id))
    out += _str_field(5, s.name)
    out += _varint_field(6, 3)  # SPAN_KIND_CLIENT
    out += _fixed64(7, s.start_ns)
    out += _fixed64(8, s.end_ns)
    for k, v in s.attributes.items():
        out += _len_field(9, _key_value(k, v))
    for name, t_ns, attrs in s.events:
        ev = _fixed64(1, t_ns) + _str_field(2, name)
        for k, v in attrs.items():
            ev += _len_field(3, _key_value(k, v))  # Event.attributes=3
        out += _len_field(11, ev)
    if s.status_error:
        out += _len_field(15, _str_field(2, s.status_error)
                          + _varint_field(3, 2))  # STATUS_CODE_ERROR
    else:
        out += _len_field(15, _varint_field(3, 1))  # STATUS_CODE_OK
    return bytes(out)


def encode_traces(spans: list[Any], service_name: str,
                  scope: str = "aigw_tpu") -> bytes:
    """spans → serialized ExportTraceServiceRequest bytes (POST body for
    /v1/traces with content-type application/x-protobuf)."""
    resource = _len_field(1, _key_value("service.name", service_name))
    scope_spans = _len_field(1, _str_field(1, scope))
    for s in spans:
        scope_spans += _len_field(2, _span(s))
    resource_spans = _len_field(1, resource) + _len_field(2, scope_spans)
    return _len_field(1, resource_spans)


# ---------------------------------------------------------------------------
# minimal decoder — test-side verification that a stock protobuf parser
# would accept the payload (tests/test_tracing.py decodes and asserts)

def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = 0
    n = 0
    while True:
        b = buf[i]
        n |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            return n, i
        shift += 7


def decode_message(buf: bytes) -> dict[int, list[Any]]:
    """Generic wire-format decode → {field: [values]}; length-delimited
    values stay bytes (decode nested messages by calling again)."""
    out: dict[int, list[Any]] = {}
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 1:
            v = struct.unpack("<Q", buf[i:i + 8])[0]
            i += 8
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wire == 5:
            v = struct.unpack("<I", buf[i:i + 4])[0]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append(v)
    return out
